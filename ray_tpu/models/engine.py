"""Continuous-batching decode engine, TPU-first.

The reference has no serving engine for LLMs (Serve hosts arbitrary
torch callables; continuous batching lives outside it in vLLM-class
engines). Serving an LM is this framework's flagship deployment, so
slot-based continuous batching is first-class here, built the XLA way:

- ONE fused decode program for the whole engine: B fixed decode slots
  advance together, every row at its OWN cache offset (per-row scatter
  writes + per-row masks — no recompilation as requests come and go,
  no left-padding). H decode iterations run inside a single program
  (`_decode_multi`: lax.scan + on-device sampling + per-row eos/budget
  freezing), so the host pays ONE dispatch and ONE device->host
  transfer per H tokens instead of a blocking sample per token — the
  vLLM/Orca lesson that the decode inner loop must be free of host
  synchronization, applied the XLA way.
- Admission is a per-length-bucket BATCHED prefill program
  (`_prefill_rows`): all same-bucket admissions of a step write their
  prompts' K/V into freed slots' cache rows in one dispatch while the
  other rows' state rides along untouched (donated buffers, in-place
  in HBM). First tokens are sampled on device by the fused decode from
  the device-resident `last_logits` — admission costs zero host
  round-trips.
- A finished row's slot is reused immediately: its stale K/V need no
  clearing because every mask is `slot < row_len`, and the next
  occupant's prefill overwrites from slot 0. Rows finishing
  mid-horizon freeze on device (row_len stops, emits masked to -1)
  and are retired by the host replay of the token block.
- The decode loop is ASYNC double-buffered (`pipeline_depth`, default
  2): during pure-decode stretches (queue empty, nothing mid-prefill)
  the engine keeps a bounded ring of fused steps in flight, chaining
  each run-ahead dispatch off the previous one's device-carried row
  state and issuing `copy_to_host_async` on every token block, so the
  host replays step N's tokens while the device computes step N+1.
  The ring is flushed before any admission/prefill/prefix copy (those
  mutate the donated cache from the host side), and run-ahead
  iterations on rows that finished mid-flight are masked on device and
  accounted as `pipeline_overrun_tokens`.
- SPECULATIVE decoding composes with all of the above
  (`draft_params=`/`draft_cfg=`/`spec_window=`): the engine keeps a
  second (draft) KV plane per slot — dense rings, or a second block
  pool in paged mode — and each decode dispatch becomes ONE batched
  draft-propose / target-verify round (`_spec_round`): the draft scans
  up to `spec_window` greedy proposals for every live row, one batched
  target pass verifies the [B, window+1] chunk, and per-row
  acceptance / correction / eos / budget freezing happens on device,
  so the host still sees a single [window+1, B] token block per
  dispatch (the -1-trailing-column emit contract is unchanged). Greedy
  rows stay token-identical to solo `generate(greedy=True)`; sampled
  rows fall back to the plain fused decode per-row via the decode-mode
  lane (`submit(..., greedy=...)`) — rejection sampling is follow-up
  work. Per-row draft widths adapt to the measured acceptance rate via
  `SchedulerPolicy.spec_window_hint` (the speculation analog of
  `horizon_hint`).

Consistency contract (tested): greedy engine output for every request
is token-identical to that request's solo `generate` run, regardless of
admission order, slot reuse, or which other requests share the batch —
and regardless of the SCHEDULER POLICY: scheduling (models/scheduler.py
— FIFO, priority classes, bounded-queue backpressure, per-step prefill
budget) only reorders admissions, never what an admitted row computes.

Telemetry (models/engine_metrics.py) timestamps every request through
queued → admitted → decoding → finished and exports queue-wait / TTFT /
TPOT / occupancy through the util.metrics Prometheus plane; `stats()`
snapshots it for the Serve path (serve.metrics.report_engine_stats).

Cites: reference Serve's dynamic batching seam
(python/ray/serve/batching.py:1) coalesces CALLS; this engine coalesces
DECODE STEPS — requests join and leave a running batch mid-flight.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu._private import sanitize as _sanitize
from ray_tpu.models.adapter_pool import AdapterPool
from ray_tpu.models.block_pool import BlockPool
from ray_tpu.models.engine_metrics import EngineMetrics, NullEngineMetrics
from ray_tpu.models.engine_trace import resolve_tracer
from ray_tpu.models.generate import (_check_sampling_knobs,
                                     _layer_body, forward_cached_rows,
                                     init_cache, sample_rows)
from ray_tpu.models.llama import (LlamaConfig, _rmsnorm,
                                  llama_param_specs)
from ray_tpu.models.prefix_cache import PrefixCacheIndex, block_bytes
from ray_tpu.ops.attention import paged_attention
from ray_tpu.ops.kv_quant import (KVQuantSpec, block_scale as
                                  _kv_block_scale, dequantize as
                                  _kv_dequantize, paged_quant_write,
                                  quantize as _kv_quantize,
                                  resolve_kv_quant)
from ray_tpu.models.scheduler import (EngineDraining, EngineOverloaded,
                                      FIFOPolicy, SchedulerPolicy,
                                      SubmitTimeout, make_policy)
from ray_tpu.parallel.mesh import create_mesh
from ray_tpu.parallel.sharding import (DEFAULT_RULES, named_sharding,
                                       prune_rules_for_mesh,
                                       shard_pytree)

Params = Dict[str, Any]


def _pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << (n - 1).bit_length()


def _key_data(key) -> np.ndarray:
    """Raw uint32[2] bits of a PRNG key (legacy array or typed key).

    Cold path (submit-time key normalisation, 8 bytes): the typed-key
    branch still routes its pull through the `_device_get` choke point so
    the sanitizer sees an expected transfer and telemetry counts it."""
    try:
        return np.asarray(key, np.uint32).reshape(2)
    except (TypeError, ValueError):
        return np.asarray(_device_get(jax.random.key_data(key)),
                          np.uint32).reshape(2)


def _device_get(x) -> np.ndarray:
    """The engine's ONLY device->host transfer. Every blocking fetch in
    the serving loop funnels through here so (a) the engine can count
    host syncs for telemetry (`host_syncs_per_token`) and (b) tests can
    wrap it to GATE the transfer budget — the fused decode path must
    stay at one pull per horizon, and an accidental per-token sync
    reintroduction fails tests/test_engine_horizon.py. Under the async
    pipeline the pull is usually a no-op wait: the block's
    `copy_to_host_async` was issued at dispatch, one or more fused
    steps earlier (tests/test_engine_pipeline.py gates that the next
    dispatch is issued BEFORE this fetch). When a runtime sanitizer is
    armed (RAY_TPU_SANITIZE=1 / DecodeEngine(sanitize=...)) the pull is
    marked EXPECTED — any device->host sync outside this funnel trips
    the sanitizer's ArrayImpl interposition."""
    san = _sanitize.active()
    if san is not None:
        return san.expected_get(x)
    return np.asarray(x)


def _host_async(x) -> None:
    """Start the sanctioned async device->host copy for a dispatched token
    block (pairs with the `_device_get` wait in `_drain_one`). Mirrors
    `_device_get`'s sanitizer contract for the non-blocking half."""
    san = _sanitize.active()
    if san is not None:
        san.expected_copy_async(x)
        return
    try:
        x.copy_to_host_async()
    except AttributeError:
        pass                       # non-jax.Array backends (tests)


@dataclasses.dataclass(frozen=True)
class _EngineShardings:
    """NamedShardings the tensor-parallel engine threads through its
    compiled programs as a STATIC jit argument (NamedSharding is
    hashable, so each mesh compiles its own program set and the
    unsharded engine — shardings=None — compiles exactly what it did
    before).

    ``cache``  [L, B, max_len, KV, D] — KV-head axis over "tp" (when
               the model's n_kv_heads divides tp; replicated otherwise)
    ``logits`` [B, vocab]             — vocab over "tp"
    ``pool``   [L, NB, T, KV, D]      — prefix pool, KV axis like the
               cache so copy-in/out gathers stay chip-local
    ``d_cache``/``d_pool`` — the DRAFT model's KV plane, pruned against
               the draft config's own dims (a nano draft often can't
               split its kv heads over the same mesh the target can).
               None on non-speculative engines, so every existing
               program signature hashes exactly as before.
    ``scale``/``d_scale`` [L, NB, KV] — the quantized pool's per-block
               per-kv-head scale slabs, sharded by the SAME pruned KV
               rules as the pool they dequantize. None when kv_quant
               is off (again: identical hashes for existing engines).
    """

    cache: NamedSharding
    logits: NamedSharding
    pool: NamedSharding
    d_cache: Optional[NamedSharding] = None
    d_pool: Optional[NamedSharding] = None
    scale: Optional[NamedSharding] = None
    d_scale: Optional[NamedSharding] = None

    @property
    def replicated(self) -> NamedSharding:
        """Fully-replicated sharding on the same mesh — the [H, B]
        token block is pinned to it so the single device->host transfer
        stays whole on every chip (no cross-chip fetch at drain)."""
        return NamedSharding(self.cache.mesh, P())


# ---------------------------------------------------------------------------
# Compiled programs
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("cfg", "shardings"),
                   donate_argnames=("cache", "last_logits"))
def _prefill_rows(params: Params, prompts: jax.Array, cache,
                  last_logits, rows: jax.Array, starts: jax.Array,
                  last_idx: jax.Array, cfg: LlamaConfig,
                  shardings: Optional[_EngineShardings] = None,
                  adapters: Optional[Params] = None,
                  row_slot: Optional[jax.Array] = None):
    """Batched admission/continuation prefill: write N same-bucket
    chunks' [N, Cb] K/V into N slots in ONE program — each row at its
    OWN cache offset ``starts[n]`` (0 for a cold admission; the cached
    prefix length for a warm one; the chunk frontier for a chunked
    continuation) — and scatter each row's last-real-token logits into
    the engine's device-resident `last_logits` [B, vocab]. Returns
    (cache, last_logits) — no logits ever cross to the host; the fused
    decode program samples the first token on device, so an admission
    costs zero host round-trips.

    Cb may exceed a chunk's true length (length-bucketed serving):
    trailing filler tokens' K/V land at slots >= the true frontier,
    which every later mask excludes (`slot <= q_slot` caps decode
    attention at the written frontier and the next chunk/decode write
    overwrites them) — only the logits at `last_idx` (true chunk length
    - 1) are read out, and only the FINAL chunk's scatter survives in
    `last_logits` (earlier chunks' scatters are overwritten before the
    row ever decodes). `rows` may contain duplicates (power-of-two
    group padding repeats the last admission verbatim): duplicate
    scatters write identical values, so the result is deterministic.

    Multi-LoRA: ``adapters``/``row_slot`` (the pool stacks + this
    chunk's PER-CHUNK slot lane [N], gathered from the engine's [B]
    lane at the dispatch site) thread to `_layer_body`'s per-row
    deltas; None (the default) adds no pytree leaves, so adapter-less
    engines trace the exact pre-LoRA program."""
    row_cache = {"k": cache["k"][:, rows], "v": cache["v"][:, rows]}
    logits, row_cache = forward_cached_rows(params, prompts, row_cache,
                                            starts, cfg,
                                            adapters=adapters,
                                            row_slot=row_slot)
    cache = {
        "k": cache["k"].at[:, rows].set(row_cache["k"]),
        "v": cache["v"].at[:, rows].set(row_cache["v"]),
    }
    n = prompts.shape[0]
    last = logits[jnp.arange(n), last_idx]              # [N, vocab]
    out_logits = last_logits.at[rows].set(last)
    if shardings is not None:
        # Donated buffers must leave with the sharding they arrived in.
        cache = jax.lax.with_sharding_constraint(cache, shardings.cache)
        out_logits = jax.lax.with_sharding_constraint(
            out_logits, shardings.logits)
    return cache, out_logits


@functools.partial(jax.jit,
                   static_argnames=("n_blocks", "block_tokens",
                                    "shardings"),
                   donate_argnames=("cache",))
def _prefix_copy_in(cache, pool_k, pool_v, block_ids: jax.Array,
                    rows: jax.Array, n_blocks: int, block_tokens: int,
                    shardings: Optional[_EngineShardings] = None):
    """Copy cached prefix blocks into engine slot rows: ONE gather
    program per step moves every warm admission's shared K/V from the
    device-resident pool into its slot — zero host round-trips, the
    same choke-point discipline as `_prefill_rows`.

    pool_k/v: [L, NB, T, KV, D]; block_ids [N, n_blocks]; rows [N].
    Row n's blocks land contiguously at slots [0, n_blocks*T). Both N
    and n_blocks are power-of-two padded by the caller (repeat the last
    row / the last block id), so a handful of compiles cover all chain
    lengths: duplicate row scatters write identical values, and padded
    trailing blocks write garbage BEYOND the row's matched prefix —
    slots the suffix prefill and decode overwrite before any mask ever
    admits them."""
    span = n_blocks * block_tokens
    blk_k = pool_k[:, block_ids]          # [L, N, nb, T, KV, D]
    blk_v = pool_v[:, block_ids]
    if shardings is not None:
        # Sharded gather: pool and cache carry the same KV-head
        # sharding, so pin the gathered blocks to it too — each chip
        # gathers ONLY its heads' slice of the pool and scatters it
        # into its own cache shard; no cross-chip block traffic.
        sp = shardings.pool.spec          # (l, nb, t, kv, d)
        blk_spec = NamedSharding(
            shardings.pool.mesh, P(sp[0], None, sp[1], sp[2], sp[3],
                                   sp[4]))
        blk_k = jax.lax.with_sharding_constraint(blk_k, blk_spec)
        blk_v = jax.lax.with_sharding_constraint(blk_v, blk_spec)
    L, N = blk_k.shape[:2]
    k = blk_k.reshape(L, N, span, *blk_k.shape[4:])
    v = blk_v.reshape(L, N, span, *blk_v.shape[4:])
    out = {
        "k": cache["k"].at[:, rows, :span].set(k.astype(cache["k"].dtype)),
        "v": cache["v"].at[:, rows, :span].set(v.astype(cache["v"].dtype)),
    }
    if shardings is not None:
        out = jax.lax.with_sharding_constraint(out, shardings.cache)
    return out


@functools.partial(jax.jit,
                   static_argnames=("n_blocks", "block_tokens",
                                    "shardings"),
                   donate_argnames=("pool_k", "pool_v"))
def _prefix_copy_out(cache_k, cache_v, pool_k, pool_v, row,
                     start_slot, block_ids: jax.Array, n_blocks: int,
                     block_tokens: int,
                     shardings: Optional[_EngineShardings] = None):
    """Insert a freshly prefilled prefix into the pool: slice
    [start_slot, start_slot + n_blocks*T) out of one slot row and
    scatter it into the pool at ``block_ids`` — one program per novel
    prefix segment, dispatched right after the chunk that produced it
    (dispatch order guarantees any copy-in already in flight still
    reads the blocks' OLD content). n_blocks is power-of-two padded
    with the reserved scratch block id 0: padding writes (clamped
    slices of whatever follows the segment) land in the scratch block,
    which the index never hands out."""
    span = n_blocks * block_tokens
    max_len = cache_k.shape[2]
    slots = jnp.minimum(start_slot + jnp.arange(span), max_len - 1)
    row_k = jnp.take(cache_k, row, axis=1)      # [L, max_len, KV, D]
    row_v = jnp.take(cache_v, row, axis=1)
    seg_k = jnp.take(row_k, slots, axis=1)      # [L, span, KV, D]
    seg_v = jnp.take(row_v, slots, axis=1)
    L = seg_k.shape[0]
    seg_k = seg_k.reshape(L, n_blocks, block_tokens, *seg_k.shape[2:])
    seg_v = seg_v.reshape(L, n_blocks, block_tokens, *seg_v.shape[2:])
    pool_k = pool_k.at[:, block_ids].set(seg_k.astype(pool_k.dtype))
    pool_v = pool_v.at[:, block_ids].set(seg_v.astype(pool_v.dtype))
    if shardings is not None:
        # Sharded scatter, the mirror of copy-in's gather: cache row
        # and pool share the KV-head sharding, so each chip writes its
        # own heads' slice of the block. Donated pools keep layout.
        pool_k = jax.lax.with_sharding_constraint(pool_k, shardings.pool)
        pool_v = jax.lax.with_sharding_constraint(pool_v, shardings.pool)
    return pool_k, pool_v


def _decode_layer_rows(h, layer, k_cache, v_cache, write_slots,
                       cfg: LlamaConfig, lora=None, lora_slots=None):
    """One decoder layer, one new token per row, each row writing its
    K/V at its own slot (scatter) and attending its own prefix.

    h: [B, 1, d]; caches [B, max_len, KV, D]; write_slots: [B].

    All the per-layer math lives in generate.py's `_layer_body` (one
    source of truth for both decode paths); only the cache-write
    strategy differs — per-row scatter here vs the contiguous chunk
    slice in `_cached_layer`. The per-prefix causal mask falls out of
    `_cached_attention` with q_slots = each row's own write slot and
    kv_valid_len = max_len (dead slots beyond a row's frontier are
    already excluded by `slot <= write_slot`)."""
    B = h.shape[0]
    bidx = jnp.arange(B)

    def write_kv(k_cache, v_cache, k, v):
        k_cache = k_cache.at[bidx, write_slots].set(
            k[:, 0].astype(k_cache.dtype))
        v_cache = v_cache.at[bidx, write_slots].set(
            v[:, 0].astype(v_cache.dtype))
        return k_cache, v_cache

    return _layer_body(h, layer, k_cache, v_cache,
                       write_slots[:, None], write_kv,
                       write_slots[:, None], k_cache.shape[1], cfg,
                       lora=lora, lora_slots=lora_slots)


def _decode_core(params: Params, toks: jax.Array, cache, row_len,
                 cfg: LlamaConfig, adapters=None, row_slot=None):
    """One decode step for ALL slots: row b's token `toks[b]` is
    written at slot `row_len[b]` and attends slots [0, row_len[b]].
    Dead/frozen rows compute discarded garbage at their frontier slot —
    it lands one past their real tokens (or at slot 0 for empty rows)
    and is overwritten by the next occupant's prefill, with every mask
    excluding it meanwhile. Returns (next-token logits [B, vocab] f32,
    cache). Plain function so `_decode_multi`'s scan can inline it."""
    write_slots = row_len                                   # [B]
    h = params["tok_embed"].astype(cfg.dtype)[toks[:, None]]

    def body(carry, xs):
        h = carry
        if adapters is None:
            layer, k_c, v_c = xs
            lora = None
        else:
            layer, k_c, v_c, lora = xs
        h, k_c, v_c = _decode_layer_rows(h, layer, k_c, v_c,
                                         write_slots, cfg, lora=lora,
                                         lora_slots=row_slot)
        return h, (k_c, v_c)

    xs = (params["layers"], cache["k"], cache["v"])
    if adapters is not None:
        xs = xs + (adapters,)
    h, (k_new, v_new) = jax.lax.scan(body, h, xs)
    h = _rmsnorm(h, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", h,
                        params["lm_head"].astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return logits[:, 0], {"k": k_new, "v": v_new}


@functools.partial(jax.jit,
                   static_argnames=("cfg", "horizon", "greedy",
                                    "top_k", "top_p", "eos_id",
                                    "shardings"),
                   donate_argnames=("cache", "last_logits"))
def _decode_multi(params: Params, cache, last_logits, row_len, active,
                  budget, tok_idx, row_keys, row_greedy, temperature,
                  cfg: LlamaConfig, horizon: int, greedy: bool,
                  top_k: Optional[int], top_p: Optional[float],
                  eos_id: Optional[int],
                  shardings: Optional[_EngineShardings] = None,
                  adapters: Optional[Params] = None,
                  row_slot: Optional[jax.Array] = None):
    """Fuse `horizon` decode iterations into ONE program: a `lax.scan`
    whose body samples every row's next token ON DEVICE from the
    carried `last_logits` (greedy argmax, or per-row rng streams — see
    generate.sample_rows), feeds it through `_decode_core`, and applies
    per-row eos/budget/room masking so rows that finish mid-horizon
    FREEZE: their row_len stops advancing, their `last_logits` stops
    updating, and their remaining emits are masked to -1. The host gets
    the whole [horizon, B] token block in a single transfer instead of
    one blocking sample per token.

    Per-iteration transition (bit-identical to the host replay in
    `DecodeEngine._emit_block`, which mirrors it without touching the
    device):
        tok      = sample(last_logits)          # emit if active
        budget  -= active;  tok_idx += active
        done     = budget <= 0 | row_len+1 >= max_len | tok == eos
        feed tok at slot row_len (all rows; frozen rows write garbage
        one slot past their content — masked everywhere, overwritten by
        the slot's next prefill)
        row_len += active & ~done;  last_logits updates where continuing

    Returns (toks [horizon, B] int32, cache, last_logits, row_len,
    active, budget, tok_idx) — the FULL scan carry, not just the token
    block. `last_logits` carries across calls, so the final iteration's
    decode is never wasted — the next horizon samples straight from it
    — and the carried row state lets the async pipeline chain a
    run-ahead dispatch directly off the previous one's device arrays,
    with zero host synchronization between dispatches (the host's own
    row_len/budget copies catch up when it drains the token block).

    `row_greedy` is the per-row DECODE-MODE lane (bool [B]): when the
    static `greedy` flag is False (some live row samples), rows whose
    lane is True still take the argmax so a mixed batch serves both
    modes in one program. When `greedy` is True the lane is dead code
    and XLA drops it — the all-greedy fast path compiles exactly what
    it always did."""
    max_len = cache["k"].shape[2]

    def body(carry, _):
        cache, last_logits, row_len, active, budget, tok_idx = carry
        tok = sample_rows(last_logits, row_keys, tok_idx,
                          greedy=greedy, temperature=temperature,
                          top_k=top_k, top_p=top_p)
        if not greedy:
            tok = jnp.where(
                row_greedy,
                jnp.argmax(last_logits, axis=-1).astype(tok.dtype),
                tok)
        emit = jnp.where(active, tok, -1)
        live = active.astype(jnp.int32)
        budget = budget - live
        tok_idx = tok_idx + live
        done_now = (budget <= 0) | (row_len + 1 >= max_len)
        if eos_id is not None:
            done_now = done_now | (tok == eos_id)
        cont = active & ~done_now
        logits, cache = _decode_core(params, tok, cache, row_len, cfg,
                                     adapters=adapters,
                                     row_slot=row_slot)
        row_len = row_len + cont.astype(jnp.int32)
        last_logits = jnp.where(cont[:, None], logits, last_logits)
        if shardings is not None:
            # Pin the scan carry to the engine's layout every
            # iteration: the KV write stays a chip-local scatter (each
            # chip owns its heads' cache shard) and the carried logits
            # stay vocab-sharded — XLA partitions attention heads and
            # MLP width instead of replicating the whole model.
            cache = jax.lax.with_sharding_constraint(
                cache, shardings.cache)
            last_logits = jax.lax.with_sharding_constraint(
                last_logits, shardings.logits)
        return (cache, last_logits, row_len, cont, budget,
                tok_idx), emit

    (cache, last_logits, row_len, active, budget, tok_idx), toks = \
        jax.lax.scan(
            body, (cache, last_logits, row_len, active, budget,
                   tok_idx),
            None, length=horizon)
    if shardings is not None:
        # The [H, B] block is the ONE device->host transfer: keep it
        # fully replicated so the drain reads whole from any chip —
        # host-sync bytes stay 4*H*B regardless of tp degree.
        toks = jax.lax.with_sharding_constraint(
            toks, shardings.replicated)
    return toks, cache, last_logits, row_len, active, budget, tok_idx


def _spec_accept(chunk, proposals, ver, v_logits, last_logits, row_len,
                 active, budget, tok_idx, d_tok, row_greedy, w_row,
                 window: int, eos_id: Optional[int], max_len: int):
    """On-device acceptance/correction/freeze shared by the dense and
    paged speculative rounds — the batched analog of the solo accept
    loop in models/speculative.py, fused so the host never sees logits.

    Per row: count the longest prefix of `proposals` matching the
    target's argmax continuation `ver` (capped at the row's adaptive
    width `w_row`; forced 0 on sampled rows — their lane emits just the
    t0 they sampled), emit `[t0, d_1..d_a, correction]` truncated by
    eos / budget / room exactly like `_decode_multi`'s per-iteration
    masking, and carry the corrected `last_logits` so the next round's
    t0 is this round's on-device correction. Returns the -1-trailing
    [window+1, B] emit block plus the advanced carry, including the
    draft-lag lane: after a FULLY accepted round the draft has already
    consumed d_1..d_{W-1} and only owes d_W (lag 1, pending token
    `d_tok`); any rejection resets the draft frontier to the emitted
    history (lag 0)."""
    B = row_len.shape[0]
    bidx = jnp.arange(B)
    jW = jnp.arange(window)
    match = (proposals == ver[:, :window]) \
        & (jW[None, :] < w_row[:, None]) & row_greedy[:, None]
    acc = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)
    pos = jnp.arange(window + 1)
    valid = pos[None, :] <= acc[:, None]
    if eos_id is not None:
        # Keep the first eos, cut everything after it (mid-window eos).
        iseos = ((chunk == eos_id) & valid).astype(jnp.int32)
        valid = valid & ((jnp.cumsum(iseos, axis=1) - iseos) == 0)
    valid = valid & (pos[None, :] < budget[:, None]) & active[:, None]
    n = valid.sum(axis=1).astype(jnp.int32)
    emits = jnp.where(valid, chunk, -1).T            # [window+1, B]

    budget = budget - n
    tok_idx = tok_idx + n
    last_tok = chunk[bidx, jnp.maximum(n - 1, 0)]
    done_now = (budget <= 0) | (row_len + n >= max_len)
    if eos_id is not None:
        done_now = done_now | ((n >= 1) & (last_tok == eos_id))
    cont = active & ~done_now
    row_len = row_len + n * cont.astype(jnp.int32)
    sel = v_logits[bidx, jnp.maximum(n - 1, 0)]
    last_logits = jnp.where(cont[:, None], sel, last_logits)
    full = cont & (n == window + 1)
    d_tok = jnp.where(full, chunk[:, window], d_tok)
    d_lag = jnp.where(active, full.astype(jnp.int32), 0)
    return emits, last_logits, row_len, cont, budget, tok_idx, \
        d_lag, d_tok


@functools.partial(jax.jit,
                   static_argnames=("cfg", "d_cfg", "window", "greedy",
                                    "top_k", "top_p", "eos_id",
                                    "shardings"),
                   donate_argnames=("cache", "d_cache", "last_logits"))
def _spec_round(params: Params, d_params: Params, cache, d_cache,
                last_logits, row_len, active, budget, tok_idx, d_lag,
                d_tok, row_keys, row_greedy, w_row, temperature,
                cfg: LlamaConfig, d_cfg: LlamaConfig, window: int,
                greedy: bool, top_k: Optional[int],
                top_p: Optional[float], eos_id: Optional[int],
                shardings: Optional[_EngineShardings] = None):
    """ONE batched draft-propose / target-verify round for every live
    row — the speculative replacement for a `_decode_multi` dispatch.

    Round structure (greedy rows; sampled rows ride the same program
    with acceptance forced to 0, so they advance exactly one sampled
    token per round — their solo stream):

      t0        = argmax(last_logits)         # last round's correction
      draft     consumes its 2-wide catch-up chunk at `row_len - d_lag`
                (the fixed-width lag trick: after a fully-accepted
                round the draft still owes its final proposal — carried
                in `d_tok` with `d_lag`=1 — so the consume chunk is
                always exactly [pend, t0] and the program never
                recompiles on acceptance length), then scans
                `window - 1` more greedy proposals at slots
                row_len+1+j.
      verify    ONE target pass over [t0, d_1..d_W] at `row_len` — the
                chunk-verify program that feeds the MXU.
      accept    `_spec_accept` on device; stale K/V from rejected
                candidates sits exactly where next round's writes land
                (write-before-attend, same argument as solo spec).

    Emitted tokens are ALWAYS the target's own argmax chain — a stale
    or cold draft plane can only shrink acceptance, never change
    output — which is what makes swap-in re-seeding and cold draft
    admissions safe. Returns the [window+1, B] -1-trailing emit block
    plus the full carry (incl. the draft plane and lag lane), so the
    async pipeline chains speculative run-ahead dispatches exactly like
    plain ones."""
    B = row_len.shape[0]
    bidx = jnp.arange(B)
    W = window
    max_len = cache["k"].shape[2]

    t_greedy = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
    if greedy:
        t0 = t_greedy
    else:
        t_samp = sample_rows(last_logits, row_keys, tok_idx,
                             greedy=False, temperature=temperature,
                             top_k=top_k, top_p=top_p)
        t0 = jnp.where(row_greedy, t_greedy, t_samp)

    # Draft: catch-up consume, then propose W greedy tokens.
    pend = jnp.where(d_lag == 1, d_tok, t0)
    chunk2 = jnp.stack([pend, t0], axis=1)           # [B, 2]
    d_logits, d_cache = forward_cached_rows(
        d_params, chunk2, d_cache, row_len - d_lag, d_cfg)
    first = jnp.argmax(d_logits[bidx, d_lag],
                       axis=-1).astype(jnp.int32)

    def dstep(carry, j):
        tok, d_cache = carry
        lg, d_cache = forward_cached_rows(
            d_params, tok[:, None], d_cache, row_len + 1 + j, d_cfg)
        nxt = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
        return (nxt, d_cache), tok

    (lastp, d_cache), dtoks = jax.lax.scan(
        dstep, (first, d_cache), jnp.arange(W - 1))
    proposals = jnp.concatenate([dtoks.T, lastp[:, None]], axis=1) \
        if W > 1 else lastp[:, None]                 # [B, W]

    # Target: one batched verify over [t0, d_1..d_W].
    chunk = jnp.concatenate([t0[:, None], proposals], axis=1)
    v_logits, cache = forward_cached_rows(params, chunk, cache,
                                          row_len, cfg)
    ver = jnp.argmax(v_logits, axis=-1).astype(jnp.int32)

    (emits, last_logits, row_len, active, budget, tok_idx, d_lag,
     d_tok) = _spec_accept(chunk, proposals, ver, v_logits,
                           last_logits, row_len, active, budget,
                           tok_idx, d_tok, row_greedy, w_row, W,
                           eos_id, max_len)
    if shardings is not None:
        cache = jax.lax.with_sharding_constraint(cache,
                                                 shardings.cache)
        d_cache = jax.lax.with_sharding_constraint(d_cache,
                                                   shardings.d_cache)
        last_logits = jax.lax.with_sharding_constraint(
            last_logits, shardings.logits)
        emits = jax.lax.with_sharding_constraint(emits,
                                                 shardings.replicated)
    return (emits, cache, d_cache, last_logits, row_len, active,
            budget, tok_idx, d_lag, d_tok)


# ---------------------------------------------------------------------------
# Compiled programs — paged KV mode
# ---------------------------------------------------------------------------
# The paged engine has NO dense per-slot cache: every request's K/V
# lives in fixed-size token blocks of ONE device pool
# [L, NB, T, KV, D] (the same pool the prefix cache commits into) and
# each program reaches it through the per-row block table bt [B, MB].
# MB * T == max_len is enforced at construction, so the gathered
# per-row view has EXACTLY the dense cache row's shape and every
# program below is the dense program evaluated on that view — which is
# what makes paged output bit-identical to the dense engine and to
# solo `generate` (tests/test_engine_paged.py). Block id 0 is the
# reserved null block: unallocated table entries point at it, padded
# gathers/scatters dump garbage into it, and no mask ever admits it.


@functools.partial(jax.jit, static_argnames=("cfg", "shardings",
                                             "qspec"),
                   donate_argnames=("pool_k", "pool_v", "scale_k",
                                    "scale_v", "last_logits"))
def _prefill_rows_paged(params: Params, prompts: jax.Array, pool_k,
                        pool_v, last_logits, bt: jax.Array,
                        rows: jax.Array, starts: jax.Array,
                        last_idx: jax.Array, cfg: LlamaConfig,
                        shardings: Optional[_EngineShardings] = None,
                        adapters: Optional[Params] = None,
                        row_slot: Optional[jax.Array] = None,
                        scale_k=None, scale_v=None,
                        qspec: Optional[KVQuantSpec] = None):
    """`_prefill_rows` for the block pool: gather each admission row's
    full [max_len] view through its block table, run the SAME
    `forward_cached_rows` math, scatter the view back block-by-block.
    One program per length bucket, zero host round-trips, and —
    because MB*T == max_len — the exact op sequence of the dense
    prefill on identical shapes.

    The whole-view write-back is safe by construction: each row only
    MODIFIES view slots [start, start+S) (its own private suffix
    blocks — shared prefix blocks sit strictly below `start`, so they
    are rewritten with the unmodified gathered bytes), duplicate
    block-table entries across rows are either shared blocks (same
    bytes) or the null block (garbage nobody reads), and duplicate
    padded rows repeat the last admission verbatim.

    Quantized pools (``qspec`` + the f32 ``scale_k``/``scale_v`` slabs)
    run the identical math on the DEQUANTIZED gathered view — kept f32
    end to end — then requantize the whole view on write-back with
    per-block scales recomputed over each row's valid slots (slots at or
    beyond ``starts + last_idx + 1`` are zeroed first so bucket-padding
    filler and stale previous-tenant garbage never poison a block's
    absmax). Shared prefix blocks survive this byte-identically:
    requantization of an unmodified dequantized block is byte-stable
    (see ops/kv_quant.py), which is what keeps zero-copy shares safe
    under the whole-view write-back."""
    blk_k = pool_k[:, bt]                  # [L, N, MB, T, KV, D]
    blk_v = pool_v[:, bt]
    if qspec is not None:
        blk_k = _kv_dequantize(
            blk_k, scale_k[:, bt][:, :, :, None, :, None])
        blk_v = _kv_dequantize(
            blk_v, scale_v[:, bt][:, :, :, None, :, None])
    if shardings is not None:
        # Same chip-local discipline as _prefix_copy_in: the gathered
        # view carries the pool's KV-head sharding.
        sp = shardings.pool.spec           # (l, nb, t, kv, d)
        blk_spec = NamedSharding(
            shardings.pool.mesh, P(sp[0], None, sp[1], sp[2], sp[3],
                                   sp[4]))
        blk_k = jax.lax.with_sharding_constraint(blk_k, blk_spec)
        blk_v = jax.lax.with_sharding_constraint(blk_v, blk_spec)
    L, N, MB, T = blk_k.shape[:4]
    row_cache = {
        "k": blk_k.reshape(L, N, MB * T, *blk_k.shape[4:]),
        "v": blk_v.reshape(L, N, MB * T, *blk_v.shape[4:]),
    }
    logits, row_cache = forward_cached_rows(params, prompts, row_cache,
                                            starts, cfg,
                                            adapters=adapters,
                                            row_slot=row_slot)
    k = row_cache["k"].reshape(L, N, MB, T, *blk_k.shape[4:])
    v = row_cache["v"].reshape(L, N, MB, T, *blk_v.shape[4:])
    if qspec is None:
        pool_k = pool_k.at[:, bt].set(k.astype(pool_k.dtype))
        pool_v = pool_v.at[:, bt].set(v.astype(pool_v.dtype))
    else:
        valid = starts + last_idx + 1                       # [N]
        live = (jnp.arange(MB * T)[None, :] < valid[:, None]) \
            .reshape(1, N, MB, T, 1, 1)

        def _writeback(pool, scales, x):
            x = jnp.where(live, x.astype(jnp.float32), 0.0)
            amax = jnp.max(jnp.abs(x), axis=(3, 5))         # [L,N,MB,KV]
            s = _kv_block_scale(amax, qspec)
            pool = pool.at[:, bt].set(
                _kv_quantize(x, s[:, :, :, None, :, None], qspec))
            return pool, scales.at[:, bt].set(s)

        pool_k, scale_k = _writeback(pool_k, scale_k, k)
        pool_v, scale_v = _writeback(pool_v, scale_v, v)
    n = prompts.shape[0]
    last = logits[jnp.arange(n), last_idx]              # [N, vocab]
    out_logits = last_logits.at[rows].set(last)
    if shardings is not None:
        pool_k = jax.lax.with_sharding_constraint(pool_k, shardings.pool)
        pool_v = jax.lax.with_sharding_constraint(pool_v, shardings.pool)
        if qspec is not None and shardings.scale is not None:
            scale_k = jax.lax.with_sharding_constraint(
                scale_k, shardings.scale)
            scale_v = jax.lax.with_sharding_constraint(
                scale_v, shardings.scale)
        out_logits = jax.lax.with_sharding_constraint(
            out_logits, shardings.logits)
    return pool_k, pool_v, scale_k, scale_v, out_logits


def _decode_layer_rows_paged(h, layer, k_pages, v_pages, bt,
                             write_slots, cfg: LlamaConfig,
                             lora=None, lora_slots=None,
                             qspec: Optional[KVQuantSpec] = None):
    """`_decode_layer_rows` against the pool: row b's new K/V scatter
    into physical block ``bt[b, slot//T]`` at offset ``slot%T`` and
    attention reads back through `ops.attention.paged_attention` (the
    block-table gather + `_cached_attention`'s exact op sequence).
    Frontier blocks are always private to their row — a shared block
    is never a write target (full-prompt prefix hits copy-on-write
    their tail block at admission) — so the scatter pairs are unique
    across live rows; retired/empty rows scatter garbage into the
    null block.

    Quantized pools thread ``k_pages``/``v_pages`` as (pages, scales)
    tuples — `_layer_body` only ever touches them through the closures
    below, which unpack/repack them around `paged_quant_write`'s
    frontier-block read-modify-write (gather + dequant + token write +
    stale-slot zero + requant) and hand `paged_attention` the scales so
    dequant happens inside its gather."""
    B = h.shape[0]
    bidx = jnp.arange(B)
    T = (k_pages[0] if qspec is not None else k_pages).shape[1]
    span = bt.shape[1] * T                 # == engine max_len
    blk = bt[bidx, write_slots // T]       # [B] physical frontier block
    off = write_slots % T

    if qspec is None:
        def write_kv(k_pages, v_pages, k, v):
            k_pages = k_pages.at[blk, off].set(
                k[:, 0].astype(k_pages.dtype))
            v_pages = v_pages.at[blk, off].set(
                v[:, 0].astype(v_pages.dtype))
            return k_pages, v_pages

        def attend(q, k_pages, v_pages):
            return paged_attention(q, k_pages, v_pages, bt,
                                   write_slots[:, None],
                                   kv_valid_len=span)
    else:
        def write_kv(kc, vc, k, v):
            kp, ks = paged_quant_write(kc[0], kc[1], bt, write_slots,
                                       k[:, :1], qspec)
            vp, vs = paged_quant_write(vc[0], vc[1], bt, write_slots,
                                       v[:, :1], qspec)
            return (kp, ks), (vp, vs)

        def attend(q, kc, vc):
            return paged_attention(q, kc[0], vc[0], bt,
                                   write_slots[:, None],
                                   kv_valid_len=span, k_scale=kc[1],
                                   v_scale=vc[1])

    return _layer_body(h, layer, k_pages, v_pages, write_slots[:, None],
                       write_kv, write_slots[:, None], span, cfg,
                       attend=attend, lora=lora, lora_slots=lora_slots)


def _decode_core_paged(params: Params, toks: jax.Array, pool_k, pool_v,
                       bt, row_len, cfg: LlamaConfig, adapters=None,
                       row_slot=None, scale_k=None, scale_v=None,
                       qspec: Optional[KVQuantSpec] = None):
    """`_decode_core` over the pool: the layer scan unstacks the pool's
    layer axis exactly as the dense scan unstacks the cache's (the
    quantized scale slabs ride the same scan as two extra xs entries).
    Plain function so `_decode_multi_paged`'s scan can inline it."""
    write_slots = row_len                                   # [B]
    h = params["tok_embed"].astype(cfg.dtype)[toks[:, None]]

    def body(carry, xs):
        h = carry
        lora = None
        if qspec is None:
            ks = vs = None
            if adapters is None:
                layer, k_p, v_p = xs
            else:
                layer, k_p, v_p, lora = xs
            kc, vc = k_p, v_p
        else:
            if adapters is None:
                layer, k_p, v_p, ks, vs = xs
            else:
                layer, k_p, v_p, ks, vs, lora = xs
            kc, vc = (k_p, ks), (v_p, vs)
        h, kc, vc = _decode_layer_rows_paged(h, layer, kc, vc, bt,
                                             write_slots, cfg,
                                             lora=lora,
                                             lora_slots=row_slot,
                                             qspec=qspec)
        if qspec is None:
            return h, (kc, vc)
        return h, (kc[0], vc[0], kc[1], vc[1])

    xs = (params["layers"], pool_k, pool_v)
    if qspec is not None:
        xs = xs + (scale_k, scale_v)
    if adapters is not None:
        xs = xs + (adapters,)
    h, ys = jax.lax.scan(body, h, xs)
    if qspec is None:
        (k_new, v_new), s_k, s_v = ys, None, None
    else:
        k_new, v_new, s_k, s_v = ys
    h = _rmsnorm(h, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", h,
                        params["lm_head"].astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return logits[:, 0], k_new, v_new, s_k, s_v


@functools.partial(jax.jit,
                   static_argnames=("cfg", "horizon", "greedy",
                                    "top_k", "top_p", "eos_id",
                                    "shardings", "qspec"),
                   donate_argnames=("pool_k", "pool_v", "scale_k",
                                    "scale_v", "last_logits"))
def _decode_multi_paged(params: Params, pool_k, pool_v, bt,
                        last_logits, row_len, active, budget, tok_idx,
                        row_keys, row_greedy, temperature,
                        cfg: LlamaConfig,
                        horizon: int, greedy: bool,
                        top_k: Optional[int], top_p: Optional[float],
                        eos_id: Optional[int],
                        shardings: Optional[_EngineShardings] = None,
                        adapters: Optional[Params] = None,
                        row_slot: Optional[jax.Array] = None,
                        scale_k=None, scale_v=None,
                        qspec: Optional[KVQuantSpec] = None):
    """`_decode_multi` with the pool + block tables standing in for
    the dense cache: identical scan body, identical per-iteration
    transition, identical [H, B] single-transfer contract — only the
    KV write (block scatter) and the attention read (block-table
    gather) differ, both inside `_decode_core_paged`. The block table
    is a step invariant: the host grows/rebuilds it between
    dispatches, never inside one. A quantized pool adds the scale
    slabs to the fused carry; qspec=None leaves every pytree and the
    traced program exactly as before."""
    max_len = bt.shape[1] * pool_k.shape[2]

    def body(carry, _):
        pool_k, pool_v, scale_k, scale_v, last_logits, row_len, \
            active, budget, tok_idx = carry
        tok = sample_rows(last_logits, row_keys, tok_idx,
                          greedy=greedy, temperature=temperature,
                          top_k=top_k, top_p=top_p)
        if not greedy:
            tok = jnp.where(
                row_greedy,
                jnp.argmax(last_logits, axis=-1).astype(tok.dtype),
                tok)
        emit = jnp.where(active, tok, -1)
        live = active.astype(jnp.int32)
        budget = budget - live
        tok_idx = tok_idx + live
        done_now = (budget <= 0) | (row_len + 1 >= max_len)
        if eos_id is not None:
            done_now = done_now | (tok == eos_id)
        cont = active & ~done_now
        logits, pool_k, pool_v, scale_k, scale_v = _decode_core_paged(
            params, tok, pool_k, pool_v, bt, row_len, cfg,
            adapters=adapters, row_slot=row_slot, scale_k=scale_k,
            scale_v=scale_v, qspec=qspec)
        row_len = row_len + cont.astype(jnp.int32)
        last_logits = jnp.where(cont[:, None], logits, last_logits)
        if shardings is not None:
            pool_k = jax.lax.with_sharding_constraint(
                pool_k, shardings.pool)
            pool_v = jax.lax.with_sharding_constraint(
                pool_v, shardings.pool)
            if qspec is not None and shardings.scale is not None:
                scale_k = jax.lax.with_sharding_constraint(
                    scale_k, shardings.scale)
                scale_v = jax.lax.with_sharding_constraint(
                    scale_v, shardings.scale)
            last_logits = jax.lax.with_sharding_constraint(
                last_logits, shardings.logits)
        return (pool_k, pool_v, scale_k, scale_v, last_logits, row_len,
                cont, budget, tok_idx), emit

    (pool_k, pool_v, scale_k, scale_v, last_logits, row_len, active,
     budget, tok_idx), toks = jax.lax.scan(
            body, (pool_k, pool_v, scale_k, scale_v, last_logits,
                   row_len, active, budget, tok_idx),
            None, length=horizon)
    if shardings is not None:
        toks = jax.lax.with_sharding_constraint(
            toks, shardings.replicated)
    return (toks, pool_k, pool_v, scale_k, scale_v, last_logits,
            row_len, active, budget, tok_idx)


def _spec_layer_rows_paged(h, layer, k_pages, v_pages, bt, slots,
                           cfg: LlamaConfig,
                           qspec: Optional[KVQuantSpec] = None):
    """S-wide `_decode_layer_rows_paged`: each row's S new K/V entries
    scatter through its block table and the S queries attend through
    it, with per-query causal masking inside `paged_attention`. Slots
    past a row's allocated chain map to the null block (write garbage
    nobody reads; only overshoot queries — whose results the accept
    mask discards — ever look that far). The quantized path hands
    `paged_quant_write` the whole S-wide window — its static
    window-block loop handles windows straddling block boundaries —
    with (pages, scales) tuples threaded through `_layer_body` exactly
    as in the decode layer."""
    if qspec is None:
        T = k_pages.shape[1]
    else:
        T = k_pages[0].shape[1]
    span = bt.shape[1] * T
    bidx = jnp.arange(slots.shape[0])[:, None]
    blk = bt[bidx, slots // T]             # [B, S]
    off = slots % T

    if qspec is None:
        def write_kv(k_pages, v_pages, k, v):
            k_pages = k_pages.at[blk, off].set(k.astype(k_pages.dtype))
            v_pages = v_pages.at[blk, off].set(v.astype(v_pages.dtype))
            return k_pages, v_pages

        def attend(q, k_pages, v_pages):
            return paged_attention(q, k_pages, v_pages, bt, slots,
                                   kv_valid_len=span)
    else:
        def write_kv(kc, vc, k, v):
            kp, ks = paged_quant_write(kc[0], kc[1], bt, slots[:, 0],
                                       k, qspec)
            vp, vs = paged_quant_write(vc[0], vc[1], bt, slots[:, 0],
                                       v, qspec)
            return (kp, ks), (vp, vs)

        def attend(q, kc, vc):
            return paged_attention(q, kc[0], vc[0], bt, slots,
                                   kv_valid_len=span, k_scale=kc[1],
                                   v_scale=vc[1])

    return _layer_body(h, layer, k_pages, v_pages, slots, write_kv,
                       slots, span, cfg, attend=attend)


def _spec_core_paged(params: Params, toks: jax.Array, pool_k, pool_v,
                     bt, starts, cfg: LlamaConfig, scale_k=None,
                     scale_v=None,
                     qspec: Optional[KVQuantSpec] = None):
    """S-wide `_decode_core_paged`: feed each row's [S] chunk at slots
    ``starts + arange(S)`` and return the full [B, S, vocab] logits —
    the draft consume/scan steps and the target verify pass are all
    this one shape family."""
    S = toks.shape[1]
    slots = starts[:, None] + jnp.arange(S)[None, :]
    h = params["tok_embed"].astype(cfg.dtype)[toks]

    def body(carry, xs):
        h = carry
        if qspec is None:
            layer, k_p, v_p = xs
            kc, vc = k_p, v_p
        else:
            layer, k_p, v_p, ks, vs = xs
            kc, vc = (k_p, ks), (v_p, vs)
        h, kc, vc = _spec_layer_rows_paged(h, layer, kc, vc, bt,
                                           slots, cfg, qspec=qspec)
        if qspec is None:
            return h, (kc, vc)
        return h, (kc[0], vc[0], kc[1], vc[1])

    xs = (params["layers"], pool_k, pool_v)
    if qspec is not None:
        xs = xs + (scale_k, scale_v)
    h, ys = jax.lax.scan(body, h, xs)
    if qspec is None:
        (k_new, v_new), s_k, s_v = ys, None, None
    else:
        k_new, v_new, s_k, s_v = ys
    h = _rmsnorm(h, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", h,
                        params["lm_head"].astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return logits, k_new, v_new, s_k, s_v


@functools.partial(jax.jit,
                   static_argnames=("cfg", "d_cfg", "window", "greedy",
                                    "top_k", "top_p", "eos_id",
                                    "shardings", "qspec"),
                   donate_argnames=("pool_k", "pool_v", "pool_dk",
                                    "pool_dv", "scale_k", "scale_v",
                                    "scale_dk", "scale_dv",
                                    "last_logits"))
def _spec_round_paged(params: Params, d_params: Params, pool_k, pool_v,
                      pool_dk, pool_dv, bt, bt_d, last_logits, row_len,
                      active, budget, tok_idx, d_lag, d_tok, row_keys,
                      row_greedy, w_row, temperature, cfg: LlamaConfig,
                      d_cfg: LlamaConfig, window: int, greedy: bool,
                      top_k: Optional[int], top_p: Optional[float],
                      eos_id: Optional[int],
                      shardings: Optional[_EngineShardings] = None,
                      scale_k=None, scale_v=None, scale_dk=None,
                      scale_dv=None,
                      qspec: Optional[KVQuantSpec] = None):
    """`_spec_round` over the block pools: the target plane reaches its
    K/V through `bt`, the draft plane through its own private table
    `bt_d` (draft blocks are never shared — the trie only indexes the
    target pool). Same round structure, same `_spec_accept`, same emit
    contract. With kv_quant BOTH planes are quantized — each pool
    carries its own scale slab; a rejected window's stale K/V is
    zeroed out of the next overlapping write's absmax by
    `paged_quant_write`, so no-rollback cache discipline still holds."""
    B = row_len.shape[0]
    bidx = jnp.arange(B)
    W = window
    max_len = bt.shape[1] * pool_k.shape[2]

    t_greedy = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
    if greedy:
        t0 = t_greedy
    else:
        t_samp = sample_rows(last_logits, row_keys, tok_idx,
                             greedy=False, temperature=temperature,
                             top_k=top_k, top_p=top_p)
        t0 = jnp.where(row_greedy, t_greedy, t_samp)

    pend = jnp.where(d_lag == 1, d_tok, t0)
    chunk2 = jnp.stack([pend, t0], axis=1)
    d_logits, pool_dk, pool_dv, scale_dk, scale_dv = _spec_core_paged(
        d_params, chunk2, pool_dk, pool_dv, bt_d, row_len - d_lag,
        d_cfg, scale_k=scale_dk, scale_v=scale_dv, qspec=qspec)
    first = jnp.argmax(d_logits[bidx, d_lag],
                       axis=-1).astype(jnp.int32)

    def dstep(carry, j):
        tok, pool_dk, pool_dv, scale_dk, scale_dv = carry
        lg, pool_dk, pool_dv, scale_dk, scale_dv = _spec_core_paged(
            d_params, tok[:, None], pool_dk, pool_dv, bt_d,
            row_len + 1 + j, d_cfg, scale_k=scale_dk, scale_v=scale_dv,
            qspec=qspec)
        nxt = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
        return (nxt, pool_dk, pool_dv, scale_dk, scale_dv), tok

    (lastp, pool_dk, pool_dv, scale_dk, scale_dv), dtoks = jax.lax.scan(
        dstep, (first, pool_dk, pool_dv, scale_dk, scale_dv),
        jnp.arange(W - 1))
    proposals = jnp.concatenate([dtoks.T, lastp[:, None]], axis=1) \
        if W > 1 else lastp[:, None]

    chunk = jnp.concatenate([t0[:, None], proposals], axis=1)
    v_logits, pool_k, pool_v, scale_k, scale_v = _spec_core_paged(
        params, chunk, pool_k, pool_v, bt, row_len, cfg,
        scale_k=scale_k, scale_v=scale_v, qspec=qspec)
    ver = jnp.argmax(v_logits, axis=-1).astype(jnp.int32)

    (emits, last_logits, row_len, active, budget, tok_idx, d_lag,
     d_tok) = _spec_accept(chunk, proposals, ver, v_logits,
                           last_logits, row_len, active, budget,
                           tok_idx, d_tok, row_greedy, w_row, W,
                           eos_id, max_len)
    if shardings is not None:
        pool_k = jax.lax.with_sharding_constraint(pool_k,
                                                  shardings.pool)
        pool_v = jax.lax.with_sharding_constraint(pool_v,
                                                  shardings.pool)
        pool_dk = jax.lax.with_sharding_constraint(pool_dk,
                                                   shardings.d_pool)
        pool_dv = jax.lax.with_sharding_constraint(pool_dv,
                                                   shardings.d_pool)
        if qspec is not None and shardings.scale is not None:
            scale_k = jax.lax.with_sharding_constraint(
                scale_k, shardings.scale)
            scale_v = jax.lax.with_sharding_constraint(
                scale_v, shardings.scale)
        if qspec is not None and shardings.d_scale is not None:
            scale_dk = jax.lax.with_sharding_constraint(
                scale_dk, shardings.d_scale)
            scale_dv = jax.lax.with_sharding_constraint(
                scale_dv, shardings.d_scale)
        last_logits = jax.lax.with_sharding_constraint(
            last_logits, shardings.logits)
        emits = jax.lax.with_sharding_constraint(emits,
                                                 shardings.replicated)
    return (emits, pool_k, pool_v, pool_dk, pool_dv, scale_k, scale_v,
            scale_dk, scale_dv, last_logits, row_len, active, budget,
            tok_idx, d_lag, d_tok)


@functools.partial(jax.jit, static_argnames=("shardings",),
                   donate_argnames=("pool_k", "pool_v", "scale_k",
                                    "scale_v"))
def _cow_blocks(pool_k, pool_v, src: jax.Array, dst: jax.Array,
                shardings: Optional[_EngineShardings] = None,
                scale_k=None, scale_v=None):
    """Copy-on-write block duplication: ONE program copies every
    (src -> dst) pair of this admission round. Dispatched when a warm
    admission matched its FULL prompt — the tail block must still grow
    the row's generated tokens, so the row gets a private copy instead
    of a share (every non-tail matched block stays zero-copy). src/dst
    are power-of-two padded with (0, 0): null -> null, harmless. A
    quantized pool copies its per-block scales alongside — the copy is
    byte-exact, never a requantization."""
    pool_k = pool_k.at[:, dst].set(pool_k[:, src])
    pool_v = pool_v.at[:, dst].set(pool_v[:, src])
    if scale_k is not None:
        scale_k = scale_k.at[:, dst].set(scale_k[:, src])
        scale_v = scale_v.at[:, dst].set(scale_v[:, src])
    if shardings is not None:
        pool_k = jax.lax.with_sharding_constraint(pool_k, shardings.pool)
        pool_v = jax.lax.with_sharding_constraint(pool_v, shardings.pool)
        if scale_k is not None and shardings.scale is not None:
            scale_k = jax.lax.with_sharding_constraint(
                scale_k, shardings.scale)
            scale_v = jax.lax.with_sharding_constraint(
                scale_v, shardings.scale)
    return pool_k, pool_v, scale_k, scale_v


@functools.partial(jax.jit, static_argnames=("shardings",))
def _swap_out_gather(pool_k, pool_v, block_ids: jax.Array,
                     shardings: Optional[_EngineShardings] = None,
                     scale_k=None, scale_v=None):
    """Gather a preemption victim's blocks [L, n, T, KV, D] out of the
    pool into fresh buffers. The caller issues `copy_to_host_async` on
    the result and drops the device reference once the host copy
    lands, so the victim's HBM is actually reclaimed. block_ids is
    power-of-two padded with the null block (its garbage rides along
    and is scattered straight back at swap-in). A quantized pool ships
    the QUANTIZED bytes plus the [L, n, KV] scales — roughly half the
    bf16 swap traffic — and the round trip is byte-exact by
    construction (no dequantization happens on either leg)."""
    if scale_k is None:
        return (pool_k[:, block_ids], pool_v[:, block_ids], None, None)
    return (pool_k[:, block_ids], pool_v[:, block_ids],
            scale_k[:, block_ids], scale_v[:, block_ids])


@functools.partial(jax.jit, static_argnames=("shardings",),
                   donate_argnames=("pool_k", "pool_v", "scale_k",
                                    "scale_v"))
def _swap_in_scatter(pool_k, pool_v, host_k, host_v,
                     block_ids: jax.Array,
                     shardings: Optional[_EngineShardings] = None,
                     scale_k=None, scale_v=None, host_sk=None,
                     host_sv=None):
    """Scatter a swapped-out request's host K/V into a freshly
    allocated block chain — the other half of preempt-and-swap. The
    new physical block ids need not match the old ones: the block
    table indirection is what makes the bytes land logically where
    they were. Quantized bytes + scales scatter back verbatim."""
    pool_k = pool_k.at[:, block_ids].set(host_k.astype(pool_k.dtype))
    pool_v = pool_v.at[:, block_ids].set(host_v.astype(pool_v.dtype))
    if scale_k is not None:
        scale_k = scale_k.at[:, block_ids].set(
            host_sk.astype(scale_k.dtype))
        scale_v = scale_v.at[:, block_ids].set(
            host_sv.astype(scale_v.dtype))
    if shardings is not None:
        pool_k = jax.lax.with_sharding_constraint(pool_k, shardings.pool)
        pool_v = jax.lax.with_sharding_constraint(pool_v, shardings.pool)
        if scale_k is not None and shardings.scale is not None:
            scale_k = jax.lax.with_sharding_constraint(
                scale_k, shardings.scale)
            scale_v = jax.lax.with_sharding_constraint(
                scale_v, shardings.scale)
    return pool_k, pool_v, scale_k, scale_v


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------

class _Request:
    __slots__ = ("req_id", "prompt", "max_new_tokens", "tokens", "done",
                 "priority", "seq", "rng", "deadline", "shed", "resume",
                 "greedy", "adapter_id", "handoff")

    def __init__(self, req_id: int, prompt: List[int],
                 max_new_tokens: int, priority: int = 0, seq: int = 0,
                 rng: Optional[np.ndarray] = None,
                 deadline: Optional[float] = None):
        self.req_id = req_id
        self.prompt = list(prompt)
        self.max_new_tokens = max_new_tokens
        self.tokens: List[int] = []
        self.done = False
        self.priority = priority    # lower = admitted first (priority policy)
        self.seq = seq              # submission order (FIFO tie-break)
        self.rng = rng              # [2] uint32 per-request key stream
        self.deadline = deadline    # absolute clock time; None = no SLO
        self.shed = False           # retired past-deadline, no prefill run
        self.resume = False         # preempted; re-queued to swap back in
        self.greedy = None          # per-request decode-mode override
        self.adapter_id = None      # LoRA adapter (None = base model)
        self.handoff = False        # imported from a prefill-class
        #                             replica, awaiting decode admission


class _PrefillState:
    """A slot row whose prompt suffix is still being written.

    ``pos`` is the row's prefill frontier: slots [0, pos) hold valid
    K/V (copied prefix + completed chunks). ``nodes`` are the PENDING
    trie nodes this row's prefill will fill — each is copied out to the
    pool and committed as soon as the frontier covers its block.
    ``prompt`` is the token sequence being prefilled — the request's
    prompt, except for a preempt="recompute" re-admission, which
    replays prompt + already-emitted tokens (same K/V, recomputed)."""

    __slots__ = ("req", "pos", "nodes", "prompt")

    def __init__(self, req: _Request, pos: int, nodes: list,
                 prompt: Optional[List[int]] = None):
        self.req = req
        self.pos = pos
        self.nodes = nodes
        self.prompt = req.prompt if prompt is None else prompt


class _SwapState:
    """A preempted request's spilled decode state (paged engine).

    ``k``/``v`` are HOST copies of the victim's gathered blocks
    [L, nbp, T, KV, D] — `copy_to_host_async` overlaps the pull, and
    dropping the device reference is what actually returns the HBM.
    They are None under preempt="recompute", where re-admission
    re-prefills prompt + emitted tokens instead of scattering bytes
    back. ``row_len``/``tok_idx``/``budget``/``logits`` restore the
    row exactly where it froze; the token stream then continues
    bit-identically because `step_rng_key` depends only on the
    request's key and tok_idx — never on which row or which step."""

    __slots__ = ("k", "v", "n_blocks", "row_len", "tok_idx", "budget",
                 "logits", "sk", "sv")

    def __init__(self, k, v, n_blocks: int, row_len: int, tok_idx: int,
                 budget: int, logits, sk=None, sv=None):
        self.k = k
        self.v = v
        self.n_blocks = n_blocks
        self.row_len = row_len
        self.tok_idx = tok_idx
        self.budget = budget
        self.logits = logits
        # quantized pools spill their per-block scales alongside the
        # (quantized) bytes; None for a dense-precision pool
        self.sk = sk
        self.sv = sv


class _InflightStep:
    """One dispatched-but-not-yet-drained fused decode step.

    ``toks`` is the step's [H, B] device token block — its
    `copy_to_host_async` was issued at dispatch, so by the time the
    host drains it (one or more steps later) the bytes are already on
    their way or landed. ``chain`` is the dispatch's returned device
    row state (row_len, active, budget, tok_idx): the NEXT run-ahead
    dispatch consumes it directly, so queued steps never synchronize
    with the host. ``run_ahead`` marks steps dispatched before the
    host had replayed the previous block — only those can contain
    overrun iterations for rows that had already finished."""

    __slots__ = ("toks", "H", "rows", "run_ahead", "chain", "spec",
                 "w_max", "w_row")

    def __init__(self, toks, H: int, rows: List[int], run_ahead: bool,
                 chain: tuple, spec: bool = False, w_max: int = 0,
                 w_row=None):
        self.toks = toks
        self.H = H
        self.rows = rows
        self.run_ahead = run_ahead
        self.chain = chain
        self.spec = spec            # speculative round: H == w_max + 1
        self.w_max = w_max          # dispatch draft width
        self.w_row = w_row          # per-row width snapshot [B] (np)


class DecodeEngine:
    """Slot-based continuous batching over a shared KV cache.

    `submit()` enqueues a request; `step()` admits queued requests into
    free slots (batched, same-bucket prefills share ONE program), then
    advances every live slot up to `decode_horizon` tokens with ONE
    fused device program and ONE device->host transfer (the [H, B]
    token block); `run()` drains everything. The horizon adapts each
    step via the scheduler's `horizon_hint`: 1 while queued requests
    could take a free slot next step (protect TTFT), the full
    `decode_horizon` once slots are saturated or the queue is empty
    (amortize dispatch overhead) — pass `step(horizon=...)` to pin it.

    `pipeline_depth` (default 2) bounds the async ring of fused steps
    kept in flight during pure-decode stretches: step N+1 is dispatched
    BEFORE step N's token block is pulled to the host (the block's
    `copy_to_host_async` overlaps N+1's compute), chained through the
    device-carried row state, and the host drains/replays one step
    behind. The ring flushes whenever the scheduler reports pending
    admissions or a row is mid-chunked-prefill, so scheduling decisions
    always see fully-replayed host state; depth 1 is the synchronous
    engine. Output is token-identical at every depth.

    Greedy by default; sampling mode (greedy=False) applies the same
    temperature/top_k/top_p semantics as `generate`, with a PER-REQUEST
    key stream: request r's i-th token uses
    ``step_rng_key(r.rng, i)`` — exactly solo `generate`'s schedule —
    so sampled output, like greedy output, is token-identical to that
    request's solo run (pass ``submit(..., rng=...)`` to pin a stream;
    the default derives one from the engine rng and request id).

    bucket_lens=True rounds each admission's prefill to the next power
    of two, so a handful of XLA compiles (one per length bucket x
    power-of-two admission-group size) cover all traffic; adaptive
    stepping rounds the horizon down to a power of two, so the fused
    decode program compiles at most log2(decode_horizon)+1 variants.

    Scheduling / admission control (models/scheduler.py):
      scheduler="fifo"|"priority"|SchedulerPolicy — which queued
        request takes the next freed slot (`submit(..., priority=)`
        orders the priority policy; lower admits first);
      max_queue + on_full ("reject"|"block") — bounded queue
        backpressure: reject raises EngineOverloaded, block drives
        step() until a queue slot frees;
      max_prefills_per_step — per-step prefill admission budget so a
        burst of long prompts cannot starve in-flight decode rows.

    Tensor parallelism: ``tp=n`` (or a prebuilt ``mesh=`` with a "tp"
    axis) shards the model weights, the KV cache, the prefix block
    pool and the fused programs' carried state across n chips via the
    model's logical axis rules — attention heads, MLP width and the
    vocab dimension split over ICI; KV heads split when ``n_kv_heads``
    divides tp and replicate otherwise (prune_rules_for_mesh). The
    host never notices: scheduling, chunked prefill, the async
    pipeline and the single [H, B] device->host block (kept fully
    replicated) are identical at every tp degree, and so is every
    emitted token (greedy and sampled) — gated by
    tests/test_engine_sharded.py.

    Telemetry: `self.metrics` (EngineMetrics) records queue-wait /
    TTFT / TPOT / occupancy through the util.metrics Prometheus plane;
    `stats()` returns the flat snapshot. enable_metrics=False swaps in
    a no-op recorder for benchmark inner loops.
    """

    def __init__(self, params: Params, cfg: LlamaConfig, *,
                 batch_slots: int = 8, max_len: Optional[int] = None,
                 greedy: bool = True, temperature: float = 1.0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 bucket_lens: bool = True,
                 rng: Optional[jax.Array] = None,
                 scheduler: Union[str, SchedulerPolicy] = "fifo",
                 max_queue: Optional[int] = None,
                 on_full: str = "reject",
                 block_timeout_s: Optional[float] = None,
                 max_prefills_per_step: Optional[int] = None,
                 decode_horizon: int = 8,
                 pipeline_depth: int = 2,
                 prefix_cache: bool = False,
                 prefix_block: int = 32,
                 prefix_cache_bytes: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 paged: bool = False,
                 kv_block_tokens: Optional[int] = None,
                 kv_pool_bytes: Optional[int] = None,
                 kv_quant: Optional[str] = None,
                 preempt: str = "swap",
                 draft_params: Optional[Params] = None,
                 draft_cfg: Optional[LlamaConfig] = None,
                 spec_window: int = 4,
                 lora: Optional["LoraConfig"] = None,
                 max_live_adapters: int = 4,
                 mesh: Optional[Mesh] = None,
                 tp: Optional[int] = None,
                 sharding_rules=None,
                 engine_id: Optional[str] = None,
                 enable_metrics: bool = True,
                 trace=None,
                 sanitize=None,
                 clock: Callable[[], float] = time.monotonic):
        _check_sampling_knobs(greedy, top_k, top_p)
        if on_full not in ("reject", "block"):
            raise ValueError(f"on_full must be 'reject' or 'block', "
                             f"got {on_full!r}")
        if max_queue is not None and max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if block_timeout_s is not None and block_timeout_s <= 0:
            raise ValueError("block_timeout_s must be > 0")
        if max_prefills_per_step is not None and max_prefills_per_step < 1:
            raise ValueError("max_prefills_per_step must be >= 1")
        if decode_horizon < 1:
            raise ValueError("decode_horizon must be >= 1")
        if pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        if prefix_block < 1:
            raise ValueError("prefix_block must be >= 1")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if preempt not in ("swap", "recompute"):
            raise ValueError(f"preempt must be 'swap' or 'recompute', "
                             f"got {preempt!r}")
        if kv_block_tokens is not None and kv_block_tokens < 1:
            raise ValueError("kv_block_tokens must be >= 1")
        self.kv_quant_spec = resolve_kv_quant(kv_quant)
        self.kv_quant = kv_quant if self.kv_quant_spec is not None \
            else None
        if self.kv_quant_spec is not None and not paged:
            raise ValueError(
                "kv_quant requires paged=True: quantization scales are "
                "per-block slabs of the paged KV pool")
        if draft_params is not None:
            if draft_cfg is None:
                raise ValueError("draft_params needs draft_cfg")
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_cfg.vocab_size} != target "
                    f"vocab {cfg.vocab_size}: speculative decoding "
                    "needs a shared tokenizer")
            if spec_window < 1:
                raise ValueError("spec_window must be >= 1")
        if lora is not None:
            if draft_params is not None:
                raise ValueError(
                    "lora= and draft_params= are mutually exclusive: "
                    "the speculative draft/verify programs do not "
                    "thread per-row adapter deltas (multi-LoRA "
                    "speculative decoding is follow-up work)")
            if max_live_adapters < 1:
                raise ValueError("max_live_adapters must be >= 1")
        self.params = params
        self.cfg = cfg
        self.B = batch_slots
        self.max_len = max_len or cfg.max_seq_len
        if self.max_len > cfg.max_seq_len:
            raise ValueError(f"max_len {self.max_len} exceeds "
                             f"max_seq_len {cfg.max_seq_len}")
        self.greedy = greedy
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self.bucket_lens = bucket_lens
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)

        self.scheduler = make_policy(scheduler)
        self.max_queue = max_queue
        self.on_full = on_full
        self.block_timeout_s = block_timeout_s
        self.max_prefills_per_step = max_prefills_per_step
        self.decode_horizon = decode_horizon
        self.pipeline_depth = pipeline_depth
        # One clock for telemetry AND deadline shedding — injectable so
        # hysteresis/expiry tests advance time without sleeping.
        self._clock = clock
        self.metrics = (EngineMetrics(engine_id=engine_id,
                                      batch_slots=self.B, clock=clock)
                        if enable_metrics else NullEngineMetrics())
        # Request-lifecycle tracer (engine_trace.py): `trace=` takes an
        # EngineTracer, True (build one), False (force off), or None —
        # defer to the RAY_TPU_TRACE env gate, else the no-op tracer.
        # Every hot-path call site guards on `self.trace.enabled`, so
        # the default costs one attribute read per seam.
        self.engine_id = engine_id or (self.metrics.engine_id
                                       if enable_metrics else "engine")
        self.trace = resolve_tracer(trace, engine_id=self.engine_id,
                                    clock=clock)
        # Runtime sanitizer (_private/sanitize.py): `sanitize=` takes a
        # Sanitizer, True (build a strict one), False (force off), or
        # None — defer to the RAY_TPU_SANITIZE env gate. When present it
        # auto-arms after RAY_TPU_SANITIZE_WARMUP steps (compiles are
        # expected during warmup); `arm_sanitizer()` arms it on demand.
        # The off path costs one module-global read in `_device_get`.
        self.sanitizer = _sanitize.resolve(sanitize)
        self._san_steps = 0
        self._san_warmup = _sanitize.warmup_steps()

        # Tensor parallelism over an ICI mesh: `tp=n` builds a
        # {"tp": n} mesh over the first n visible devices; `mesh=`
        # hands over a prebuilt mesh carrying a "tp" axis. Weights, the
        # KV cache, the prefix block pool and the fused programs' scan
        # state are sharded over it via the model's logical axis rules
        # (heads/mlp/vocab split across chips; KV heads split when
        # n_kv_heads divides tp, replicated otherwise — see
        # prune_rules_for_mesh). Host-side scheduling, the async
        # pipeline and the single [H, B] transfer are tp-blind.
        if tp is not None:
            if mesh is not None:
                raise ValueError("pass mesh= or tp=, not both")
            if tp < 1:
                raise ValueError("tp must be >= 1")
            devs = jax.devices()
            if tp > len(devs):
                raise ValueError(
                    f"tp={tp} exceeds the {len(devs)} visible "
                    "device(s); on CPU force a virtual world with "
                    "XLA_FLAGS=--xla_force_host_platform_device_count")
            mesh = create_mesh({"tp": tp}, devs[:tp])
        self.mesh = mesh
        if mesh is not None:
            if "tp" not in mesh.axis_names:
                raise ValueError(
                    "serving mesh needs a 'tp' axis, got axes "
                    f"{mesh.axis_names}")
            self.tp_degree = int(dict(mesh.shape)["tp"])
            dims = {"heads": cfg.n_heads, "qkv": cfg.n_heads,
                    "kv": cfg.n_kv_heads, "mlp": cfg.ffn_dim,
                    "vocab": cfg.vocab_size, "embed": cfg.dim,
                    "batch": self.B}
            base = dict(DEFAULT_RULES)
            base["kv"] = "tp"   # serving shards the KV-head axis; the
            #                     training table replicates it
            rules = (sharding_rules if sharding_rules is not None
                     else prune_rules_for_mesh(base, mesh, dims))
            self._rules = rules
            self.params = shard_pytree(
                params, llama_param_specs(cfg, rules), mesh)
            d_cache_sh = d_pool_sh = d_scale_sh = None
            self._d_shardings = None
            if draft_params is not None:
                # The draft shards over the SAME mesh, but its rules
                # prune against its OWN dims — a nano draft whose kv
                # heads don't divide tp replicates that axis while the
                # target still splits its.
                d_dims = {"heads": draft_cfg.n_heads,
                          "qkv": draft_cfg.n_heads,
                          "kv": draft_cfg.n_kv_heads,
                          "mlp": draft_cfg.ffn_dim,
                          "vocab": draft_cfg.vocab_size,
                          "embed": draft_cfg.dim, "batch": self.B}
                d_rules = prune_rules_for_mesh(dict(base), mesh, d_dims)
                draft_params = shard_pytree(
                    draft_params, llama_param_specs(draft_cfg, d_rules),
                    mesh)
                d_cache_sh = named_sharding(
                    mesh, "layers", "batch", "length", "kv", "head_dim",
                    rules=d_rules)
                d_pool_sh = named_sharding(
                    mesh, "layers", None, None, "kv", "head_dim",
                    rules=d_rules)
                if self.kv_quant_spec is not None:
                    d_scale_sh = named_sharding(
                        mesh, "layers", None, "kv", rules=d_rules)
                # A second shardings view with the DRAFT plane in the
                # primary slots, so `_prefill_rows(_paged)` runs
                # unchanged when seeding the draft cache.
                self._d_shardings = _EngineShardings(
                    cache=d_cache_sh,
                    logits=named_sharding(mesh, "batch", "vocab",
                                          rules=d_rules),
                    pool=d_pool_sh,
                    scale=d_scale_sh)
            scale_sh = None
            if self.kv_quant_spec is not None:
                # scale slab [L, NB, KV]: same pruned KV rules as the
                # pool it dequantizes, so gather stays chip-local
                scale_sh = named_sharding(mesh, "layers", None, "kv",
                                          rules=rules)
            self._shardings = _EngineShardings(
                cache=named_sharding(mesh, "layers", "batch", "length",
                                     "kv", "head_dim", rules=rules),
                logits=named_sharding(mesh, "batch", "vocab",
                                      rules=rules),
                pool=named_sharding(mesh, "layers", None, None, "kv",
                                    "head_dim", rules=rules),
                d_cache=d_cache_sh, d_pool=d_pool_sh,
                scale=scale_sh, d_scale=d_scale_sh)
        else:
            self.tp_degree = 1
            self._rules = None
            self._shardings = None
            self._d_shardings = None
        self.metrics.on_tp_degree(self.tp_degree)

        # Multi-LoRA serving plane (models/adapter_pool.py): device
        # stacks of up to max_live_adapters LoRA weight sets, one slot
        # lane mapping each batch row to its adapter (0 = base-only),
        # and a pending map carrying the slot reference taken at the
        # ADMISSION GATE to the row bind — the incref happens at the
        # gate, not at bind, so a later candidate's prefetch-commit in
        # the same admission round can never evict an adapter a
        # decision was already made against. lora=None engines carry
        # adapter_pool=None and every dispatch passes adapters=None
        # (zero extra pytree leaves -> byte-identical programs).
        self.lora_cfg = lora
        self.adapter_pool = None
        if lora is not None:
            self.adapter_pool = AdapterPool(
                cfg, lora, max_live_adapters=max_live_adapters,
                mesh=self.mesh, rules=self._rules,
                metrics=self.metrics, trace=self.trace)
            self.metrics.on_adapter_slots(max_live_adapters, 0, 0)
        self._row_slot = np.zeros((self.B,), np.int32)
        self._pending_slots: Dict[int, int] = {}
        self.adapter_deferrals = 0     # cold-adapter admission defers
        if self.adapter_pool is not None:
            attach = getattr(self.scheduler, "attach_adapter_probe",
                             None)
            if attach is not None:
                attach(self._adapter_probe)

        # Paged KV mode: no dense per-slot cache at all — every row's
        # K/V lives in pool blocks behind its block table (state built
        # below, after this shared row bookkeeping). The dense engine
        # keeps its [L, B, max_len, KV, D] cache unchanged.
        self.paged = paged
        self.preempt_mode = preempt
        self.kv_block_tokens = (kv_block_tokens
                                if kv_block_tokens is not None
                                else prefix_block)
        if paged and self.max_len % self.kv_block_tokens:
            raise ValueError(
                f"paged engine needs max_len ({self.max_len}) "
                f"divisible by kv_block_tokens "
                f"({self.kv_block_tokens}): the block view must span "
                "exactly the dense cache row so paged attention is "
                "bit-identical to the dense path")
        self.cache = None if paged else init_cache(
            cfg, self.B, self.max_len,
            sharding=None if self._shardings is None
            else self._shardings.cache)
        # Next-token logits per slot, DEVICE-resident: prefill scatters
        # into it, the fused decode samples from and re-carries it —
        # logits never cross the jit boundary to the host.
        self._last_logits = jnp.zeros((self.B, cfg.vocab_size),
                                      jnp.float32)
        if self._shardings is not None:
            self._last_logits = jax.device_put(self._last_logits,
                                               self._shardings.logits)
        self.row_len = np.zeros((self.B,), np.int32)   # written slots
        self.row_req: List[Optional[_Request]] = [None] * self.B
        self.row_budget = np.zeros((self.B,), np.int32)
        self._tok_idx = np.zeros((self.B,), np.int32)  # sampled so far
        self._row_keys = np.zeros((self.B, 2), np.uint32)
        self._base_key = _key_data(self._rng)
        self._next_id = 0
        self.results: Dict[int, _Request] = {}
        self.finished: set = set()      # done but not yet popped
        self.shed_ids: set = set()      # finished as past-deadline sheds
        self.requests_shed = 0          # plain int (enable_metrics=False)
        self.draining = False           # begin_drain(): no new submits
        self.halted = False             # halt(): state discarded (fleet
        #                                 failover abandoned this engine)
        # Dispatch/transfer accounting (plain ints so the benchmark's
        # enable_metrics=False engines still report them):
        self.decode_dispatches = 0     # fused decode program launches
        self.prefill_dispatches = 0    # batched prefill launches
        self.host_syncs = 0            # device->host transfers
        self.host_transfer_bytes = 0   # bytes those transfers moved
        self.tokens_out = 0            # tokens emitted, all requests
        # Prefill/prefix-reuse accounting (same plain-int discipline):
        self.prefill_real_tokens = 0   # true chunk tokens prefilled
        self.prefill_padded_tokens = 0  # bucket + pow2-group filler
        self.prefix_lookups = 0        # admissions probed in the trie
        self.prefix_hits = 0           # ... that matched >= 1 block
        self.prefix_reused_tokens = 0  # prompt tokens copied, not run
        self.prefix_evictions = 0      # LRU blocks recycled
        self.prefix_copy_dispatches = 0  # pool copy-in/out launches
        self.chunked_prefill_stalls = 0  # steps with a row mid-prefill
        # Paged-KV plane (plain ints; identically zero on the dense
        # engine so fleet rollups can sum them blindly):
        self.kv_blocks_shared = 0      # warm-admission zero-copy shares
        self.kv_block_cows = 0         # tail blocks duplicated on write
        self.preemptions = 0           # rows evicted mid-decode
        self.swap_ins = 0              # preempted rows re-admitted
        self.swap_outs = 0             # swap-mode spills to host
        self.swap_in_bytes = 0         # host->device swap traffic
        self.swap_out_bytes = 0        # device->host swap traffic
        # Disaggregated prefill/decode plane (plain ints; identically
        # zero on a colocated engine so fleet rollups sum blindly).
        # `prefill_only` is set by the fleet on prefill-class replicas:
        # step() then parks rows whose prefill frontier completed in
        # `_handoff_ready` instead of decoding them, and the fleet
        # export_request()s each one to a decode-class replica.
        self.prefill_only = False      # fleet-set replica-class switch
        self.replica_class = None      # "prefill" / "decode" / None
        self._handoff_ready: List[int] = []   # req_ids parked post-prefill
        self._handoff_ready_set: set = set()
        self.handoffs_out = 0          # requests exported post-prefill
        self.handoffs_in = 0           # requests imported for decode
        self.handoff_out_bytes = 0     # KV+logits bytes staged to host
        self.handoff_in_bytes = 0      # KV+logits bytes accepted
        # Async pipeline: dispatched-but-undrained fused steps, oldest
        # first. Same plain-int discipline for the counters so
        # enable_metrics=False benches still report the pipeline plane.
        self._ring: collections.deque = collections.deque()
        self.pipeline_flushes = 0      # forced full drains of the ring
        self.pipeline_overrun_tokens = 0  # masked run-ahead iterations
        self._pl_depth_sum = 0         # ring depth sampled at each drain
        self._pl_depth_n = 0

        # Chunked prefill: rows whose suffix is still being written,
        # row -> _PrefillState. A row in here is EXCLUDED from decode
        # (its last_logits are not final) and advances one chunk per
        # step via _advance_prefills().
        self.prefill_chunk = prefill_chunk
        self._row_prefill: Dict[int, _PrefillState] = {}

        # Shared-prefix KV cache: host-side radix index over committed
        # prompt blocks + a device-resident pool. Dense mode keeps the
        # PR-4 copy-in/copy-out pool, sized by prefix_cache_bytes
        # (default: room for 2 full batches of max_len tokens) plus
        # the reserved scratch block 0. Paged mode has ONE pool for
        # everything — live rows' K/V and the prefix cache are the
        # same refcounted blocks, so the trie indexes the pool
        # directly and a warm admission SHARES blocks instead of
        # copying them.
        self.prefix_block = (self.kv_block_tokens if paged
                             else prefix_block)
        self._prefix: Optional[PrefixCacheIndex] = None
        self.kv_pool: Optional[BlockPool] = None
        L, KV, D = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        kv_dtype = jnp.dtype(cfg.dtype)
        if paged:
            T = self.prefix_block
            if self.kv_quant_spec is not None:
                # Quantized pool: 1-byte values + the per-block scale
                # slab's footprint (2 slabs x L x KV f32 scales per
                # block) — the ~2x concurrency-per-HBM-byte lever.
                pool_dtype = self.kv_quant_spec.dtype
                bb = block_bytes(L, T, KV, D,
                                 self.kv_quant_spec.itemsize) \
                    + 2 * L * KV * 4
            else:
                pool_dtype = kv_dtype
                bb = block_bytes(L, T, KV, D, kv_dtype.itemsize)
            self.kv_bytes_per_block = float(bb)
            self.kv_bytes_per_token = bb / T
            budget_bytes = (kv_pool_bytes if kv_pool_bytes is not None
                            else prefix_cache_bytes)
            if budget_bytes is None:
                # Default: the dense engine's footprint — room for two
                # full batches of max_len tokens.
                n_blocks = 1 + (2 * self.B * self.max_len) // T
            else:
                n_blocks = 1 + budget_bytes // bb
            self._mb = self.max_len // T   # block-table width
            self.kv_pool = BlockPool(n_blocks)
            self._bt = np.zeros((self.B, self._mb), np.int32)
            self._row_blocks: List[List[int]] = [
                [] for _ in range(self.B)]
            self._swapped: Dict[int, _SwapState] = {}
            self._admit_seq = 0            # preemption recency order
            self._row_admit_seq = np.zeros((self.B,), np.int64)
            self._pool_k = jnp.zeros((L, n_blocks, T, KV, D),
                                     pool_dtype)
            self._pool_v = jnp.zeros((L, n_blocks, T, KV, D),
                                     pool_dtype)
            self._scale_k = self._scale_v = None
            if self.kv_quant_spec is not None:
                # zero scales: dequant of the zero-initialised pool
                # (incl. the null block) is exactly 0.0 everywhere
                self._scale_k = jnp.zeros((L, n_blocks, KV),
                                          jnp.float32)
                self._scale_v = jnp.zeros((L, n_blocks, KV),
                                          jnp.float32)
            if self._shardings is not None:
                self._pool_k = jax.device_put(self._pool_k,
                                              self._shardings.pool)
                self._pool_v = jax.device_put(self._pool_v,
                                              self._shardings.pool)
                if self._scale_k is not None:
                    self._scale_k = jax.device_put(
                        self._scale_k, self._shardings.scale)
                    self._scale_v = jax.device_put(
                        self._scale_v, self._shardings.scale)
            if prefix_cache:
                self._prefix = PrefixCacheIndex(
                    block_tokens=T, n_blocks=n_blocks,
                    on_evict=self._on_prefix_evict, pool=self.kv_pool)
        elif prefix_cache:
            self._scale_k = self._scale_v = None
            self.kv_bytes_per_block = float(block_bytes(
                L, prefix_block, KV, D, kv_dtype.itemsize))
            self.kv_bytes_per_token = self.kv_bytes_per_block \
                / prefix_block
            bb = block_bytes(L, prefix_block, KV, D, kv_dtype.itemsize)
            if prefix_cache_bytes is None:
                n_blocks = 1 + (2 * self.B * self.max_len) // prefix_block
            else:
                n_blocks = 1 + prefix_cache_bytes // bb
            self._prefix = PrefixCacheIndex(
                block_tokens=prefix_block, n_blocks=n_blocks,
                on_evict=self._on_prefix_evict)
            self._pool_k = jnp.zeros(
                (L, n_blocks, prefix_block, KV, D), kv_dtype)
            self._pool_v = jnp.zeros(
                (L, n_blocks, prefix_block, KV, D), kv_dtype)
            if self._shardings is not None:
                # Pool lives on the mesh with the cache's KV sharding:
                # each chip holds only its heads' slice of every block
                # (prefix_cache_bytes stays the GLOBAL pool footprint;
                # per-chip resident bytes are that / tp when KV
                # shards).
                self._pool_k = jax.device_put(self._pool_k,
                                              self._shardings.pool)
                self._pool_v = jax.device_put(self._pool_v,
                                              self._shardings.pool)
        else:
            self._pool_k = self._pool_v = None
            self._scale_k = self._scale_v = None
            # dense per-slot cache: 2 (K+V) x L x KV x D per token
            self.kv_bytes_per_token = float(
                2 * L * KV * D * kv_dtype.itemsize)
            self.kv_bytes_per_block = 0.0
        if self._prefix is not None:
            attach = getattr(self.scheduler, "attach_prefix_probe", None)
            if attach is not None:
                attach(self._prefix_probe)

        # Speculative plane: the DRAFT model's KV lives in a second
        # per-slot plane — a dense [L_d, B, max_len, KV_d, D_d] ring,
        # or its own private block pool + table in paged mode (draft
        # blocks are never shared or tried; sized so every slot can
        # hold a full row, the draft allocator can never run dry).
        # Host lanes mirror the device's draft-lag trick and feed the
        # adaptive per-row window from a sliding acceptance history.
        self.spec_enabled = draft_params is not None
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.spec_window = spec_window
        self.spec_dispatches = 0       # speculative program launches
        self.spec_rounds = 0           # per-row rounds replayed
        self.spec_proposed = 0         # draft tokens proposed (w_row)
        self.spec_accepted = 0         # draft tokens emitted
        self.spec_wasted = 0           # dispatch-width slots rejected
        self.spec_prefill_dispatches = 0   # draft-plane seeding programs
        self.spec_metrics = None
        if self.spec_enabled:
            if draft_cfg.max_seq_len < self.max_len:
                raise ValueError(
                    f"draft max_seq_len {draft_cfg.max_seq_len} < "
                    f"engine max_len {self.max_len}")
            self._d_lag = np.zeros((self.B,), np.int32)
            self._d_tok = np.zeros((self.B,), np.int32)
            self._spec_hist: List[collections.deque] = [
                collections.deque(maxlen=16) for _ in range(self.B)]
            self._d_last_logits = jnp.zeros(
                (self.B, draft_cfg.vocab_size), jnp.float32)
            if self._d_shardings is not None:
                self._d_last_logits = jax.device_put(
                    self._d_last_logits, self._d_shardings.logits)
            L_d, KV_d, D_d = (draft_cfg.n_layers, draft_cfg.n_kv_heads,
                              draft_cfg.head_dim)
            d_dtype = jnp.dtype(draft_cfg.dtype)
            if paged:
                T = self.prefix_block
                n_blocks_d = 1 + self.B * self._mb
                self.kv_pool_d = BlockPool(n_blocks_d,
                                           label="draft_kv")
                self._bt_d = np.zeros((self.B, self._mb), np.int32)
                self._row_blocks_d: List[List[int]] = [
                    [] for _ in range(self.B)]
                d_pool_dtype = (self.kv_quant_spec.dtype
                                if self.kv_quant_spec is not None
                                else d_dtype)
                self._pool_dk = jnp.zeros(
                    (L_d, n_blocks_d, T, KV_d, D_d), d_pool_dtype)
                self._pool_dv = jnp.zeros(
                    (L_d, n_blocks_d, T, KV_d, D_d), d_pool_dtype)
                self._scale_dk = self._scale_dv = None
                if self.kv_quant_spec is not None:
                    self._scale_dk = jnp.zeros(
                        (L_d, n_blocks_d, KV_d), jnp.float32)
                    self._scale_dv = jnp.zeros(
                        (L_d, n_blocks_d, KV_d), jnp.float32)
                if self._d_shardings is not None:
                    self._pool_dk = jax.device_put(
                        self._pool_dk, self._d_shardings.pool)
                    self._pool_dv = jax.device_put(
                        self._pool_dv, self._d_shardings.pool)
                    if self._scale_dk is not None:
                        self._scale_dk = jax.device_put(
                            self._scale_dk, self._d_shardings.scale)
                        self._scale_dv = jax.device_put(
                            self._scale_dv, self._d_shardings.scale)
                self._d_cache = None
            else:
                self.kv_pool_d = None
                self._d_cache = init_cache(
                    draft_cfg, self.B, self.max_len,
                    sharding=None if self._d_shardings is None
                    else self._d_shardings.cache)
                self._pool_dk = self._pool_dv = None
                self._scale_dk = self._scale_dv = None
            if enable_metrics:
                # llm_spec_* Prometheus counters share the engine's
                # tag, so fleet dashboards can join the spec plane onto
                # the engine's other series (satellite: telemetry
                # routed through the engine identity).
                from ray_tpu.models.speculative import SpecMetrics
                self.spec_metrics = SpecMetrics(spec_id=self.engine_id)
        # Per-row decode-mode lane: True = argmax, False = sampled.
        # Defaults to the engine-wide mode; submit(greedy=...) overrides
        # per request at bind time. Retirement resets to the default so
        # the all-greedy fast path recompiles nothing.
        self._row_greedy = np.full((self.B,), bool(greedy), bool)

        # Serving-state plane: wall-clock birth + a step counter that
        # survives enable_metrics=False (the metrics `steps` field
        # vanishes with NullEngineMetrics), then a WEAK registration in
        # the process-local state API so `ray_tpu.util.state`
        # list_engines()/list_requests() can find this engine without
        # holding it alive.
        self._start_t = clock()
        self.steps_total = 0
        from ray_tpu.util.state.serving import register_engine
        register_engine(self)

    # -- public API --------------------------------------------------------

    def submit(self, prompt: List[int], max_new_tokens: int = 32,
               priority: int = 0,
               rng: Optional[jax.Array] = None,
               deadline_s: Optional[float] = None,
               greedy: Optional[bool] = None,
               resume_tokens: Optional[List[int]] = None,
               adapter_id: Optional[str] = None) -> int:
        """Enqueue a request; returns its id (see `results`).

        ``priority`` (lower = sooner) orders admission under the
        priority policy; the FIFO policy ignores it. With a bounded
        queue (max_queue), a full queue either raises EngineOverloaded
        (on_full="reject") or drives the engine until a queue slot
        frees (on_full="block"). ``rng`` pins this request's sampling
        key stream (greedy=False engines): with the same key, the
        request's sampled tokens equal solo
        ``generate(..., rng=rng)``; by default a distinct stream is
        derived from the engine rng and request id.

        ``greedy`` overrides the engine-wide decode mode for THIS
        request (the per-row decode-mode lane): on a speculative
        engine, greedy rows ride the draft/verify fast path while
        sampled rows fall back to one plain sampled token per round —
        their streams are unchanged vs a non-speculative engine
        (rejection sampling for speculative sampled rows is follow-up
        work). ``None`` (default) inherits the engine mode.

        ``deadline_s`` is the request's admission SLO: a latency budget
        (seconds from now, on the engine clock) within which prefill
        must START. A request still queued when its deadline passes is
        SHED — retired with zero tokens, ``shed_ids`` membership, and
        the ``requests_shed`` counter — instead of burning prefill
        compute no caller is waiting for; requests already admitted
        always run to completion (killing mid-decode would waste the
        prefill already paid). ``deadline_s <= 0`` sheds immediately
        (reject-before-prefill). After ``begin_drain()`` submit raises
        EngineDraining — a draining replica finishes what it holds but
        takes nothing new.

        ``resume_tokens`` is the fleet-failover resume path: tokens
        this request ALREADY emitted on a replica that died. Admission
        replays prompt + resume_tokens as the prefill (recompute — the
        same discipline as paged preempt="recompute"), starts the
        budget and sampling-stream index at len(resume_tokens), and
        the request's final ``tokens`` list is resume_tokens plus
        everything decoded here — bit-identical to a run that never
        failed, because `step_rng_key(rng, i)` depends only on the
        request key and the token index, never on the engine, row, or
        step that samples it. Resumed requests are exempt from
        deadline shedding (they were admitted once already) and their
        replay is NOT registered in the prefix trie (emitted tokens
        are not a shareable prompt). Pass the SAME ``rng`` as the
        original submission — sampled identity is the caller's key
        discipline (the fleet pins one key per request for exactly
        this reason).

        ``adapter_id`` routes this request through a registered LoRA
        adapter (see `register_adapter`): its rows decode with that
        adapter's low-rank delta fused into the SAME batched program
        as every other row — heterogeneous-adapter batches are the
        point. A cold adapter defers the request at the admission gate
        while its weights prefetch host->device; None (default) is the
        base model, bit-identical to an engine without lora=."""
        if adapter_id is not None:
            if self.adapter_pool is None:
                raise ValueError(
                    "adapter_id= needs an engine built with lora= "
                    "(a LoraConfig enabling the multi-LoRA plane)")
            if not self.adapter_pool.registered(adapter_id):
                raise KeyError(
                    f"unknown adapter_id {adapter_id!r}: call "
                    "register_adapter first")
        if self.draining:
            raise EngineDraining(
                "engine is draining (begin_drain was called): it will "
                "finish in-flight work but accepts no new requests")
        # Normalise to plain ints: device arrays make unusable
        # prefix-trie keys (unhashable) and unreliable equality checks.
        prompt = [int(t) for t in prompt]
        if not len(prompt):
            raise ValueError("empty prompt: need at least one token "
                             "(prepend a BOS token)")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds engine max_len "
                f"{self.max_len}")
        if (self.spec_enabled and len(prompt) + max_new_tokens
                + self.spec_window > self.max_len):
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) + spec_window "
                f"({self.spec_window}) exceeds engine max_len "
                f"{self.max_len}: the verify chunk writes up to "
                "spec_window slots past the last emitted token, so "
                "speculative engines need that margin")
        resume = None
        if resume_tokens:
            resume = [int(t) for t in resume_tokens]
            if len(resume) >= max_new_tokens:
                raise ValueError(
                    f"resume_tokens ({len(resume)}) must be shorter "
                    f"than max_new_tokens ({max_new_tokens}): a "
                    "completed request has nothing to resume")
            if deadline_s is not None:
                raise ValueError(
                    "resume_tokens and deadline_s are mutually "
                    "exclusive: a resumed request was admitted once "
                    "and is exempt from deadline shedding")
        if self.paged:
            # A request must fit the pool ALONE in the worst case
            # (every other row preempted, every cold prefix block
            # evicted) or it could never complete.
            T = self.prefix_block
            need = -(-(len(prompt) + max_new_tokens) // T)
            if need > self.kv_pool.blocks_total:
                raise ValueError(
                    f"request needs {need} KV blocks ({len(prompt)} "
                    f"prompt + {max_new_tokens} new tokens at "
                    f"{T} tokens/block) but the pool holds only "
                    f"{self.kv_pool.blocks_total}; raise "
                    "kv_pool_bytes or shrink the request")
        deadline = (None if deadline_s is None
                    else self._clock() + deadline_s)
        if deadline is not None and self._clock() >= deadline:
            # Dead on arrival: shed before the bounded-queue check —
            # it will never occupy a queue slot, let alone a prefill.
            req = _Request(self._next_id, prompt, max_new_tokens,
                           priority=priority, seq=self._next_id,
                           rng=None if rng is None else _key_data(rng),
                           deadline=deadline)
            req.greedy = greedy
            req.adapter_id = adapter_id
            self._next_id += 1
            self.results[req.req_id] = req
            self.metrics.on_submit(req.req_id)
            if self.trace.enabled:
                self.trace.instant(
                    "submit", req.req_id,
                    {"prompt_tokens": len(prompt),
                     "max_new_tokens": max_new_tokens,
                     "priority": priority})
                self.trace.open("queue_wait", req.req_id)
            self._shed(req)
            return req.req_id
        if self.max_queue is not None and \
                len(self.scheduler) >= self.max_queue:
            if self.on_full == "reject":
                self.metrics.on_reject()
                raise EngineOverloaded(
                    f"queue full ({self.max_queue} queued requests); "
                    f"shed load or use on_full='block'")
            t_block = self._clock()
            while len(self.scheduler) >= self.max_queue:
                if self.block_timeout_s is not None and \
                        self._clock() - t_block >= self.block_timeout_s:
                    self.metrics.on_reject()
                    raise SubmitTimeout(
                        f"queue still full ({self.max_queue} queued "
                        f"requests) after blocking "
                        f"{self.block_timeout_s}s: the engine made no "
                        "room — wedged, or hopelessly oversubscribed")
                self.step()   # admissions + finishes drain the queue
        req = _Request(self._next_id, prompt, max_new_tokens,
                       priority=priority, seq=self._next_id,
                       rng=None if rng is None else _key_data(rng),
                       deadline=deadline)
        req.greedy = greedy
        req.adapter_id = adapter_id
        if resume is not None:
            # Fleet failover resume: the request continues, not
            # restarts — admission replays prompt + these tokens and
            # the sampling stream picks up at token len(resume).
            req.tokens = resume
            req.resume = True
            if self.paged:
                # Ride the existing recompute swap-in path: a k=None
                # ledger entry makes `_admit_rows_paged` replay
                # prompt + tokens exactly like a preempted row.
                self._swapped[req.req_id] = _SwapState(
                    None, None, 0, 0, len(resume),
                    max_new_tokens - len(resume), None)
        self._next_id += 1
        self.scheduler.push(req)
        self.results[req.req_id] = req
        self.metrics.on_submit(req.req_id)
        self.metrics.observe_queue_depth(len(self.scheduler))
        if self.trace.enabled:
            self.trace.instant(
                "submit", req.req_id,
                {"prompt_tokens": len(prompt),
                 "max_new_tokens": max_new_tokens,
                 "priority": priority})
            self.trace.open("queue_wait", req.req_id)
        return req.req_id

    def pending(self) -> bool:
        return bool(len(self.scheduler)) or any(
            r is not None for r in self.row_req)

    # -- multi-LoRA adapter table ------------------------------------------

    def register_adapter(self, adapter_id: str, lora_params: Params
                         ) -> None:
        """Admit a LoRA adapter's weights (a `lora_init`-shaped tree)
        to the engine's host-side adapter table. HBM is untouched
        until traffic warms the adapter through the prefetch path."""
        if self.adapter_pool is None:
            raise ValueError(
                "register_adapter needs an engine built with lora=")
        self.adapter_pool.register(adapter_id, lora_params)

    def unregister_adapter(self, adapter_id: str) -> bool:
        """Drop an adapter (deferred until its last live row retires
        if currently pinned; returns False then, True when immediate).
        Requests still QUEUED for it must not outlive the
        registration — the admission gate raises on unknown ids."""
        if self.adapter_pool is None:
            return True
        return self.adapter_pool.unregister(adapter_id)

    def adapter_resident(self, adapter_id: str) -> bool:
        """True when the adapter currently occupies an HBM slot — the
        fleet router's residency-affinity probe."""
        return (self.adapter_pool is not None
                and self.adapter_pool.resident(adapter_id))

    def _adapter_probe(self, adapter_id: Optional[str]
                       ) -> Tuple[bool, bool]:
        """(resident, fetching) for the adapter-affinity scheduler."""
        if adapter_id is None or self.adapter_pool is None:
            return True, False
        return (self.adapter_pool.resident(adapter_id),
                self.adapter_pool.fetching(adapter_id))

    # The fused entry points whose compile caches the sanitizer audits:
    # any growth after arm() is a steady-state retrace regression.
    _SANITIZER_JIT_ENTRY_POINTS = (
        "_prefill_rows", "_prefill_rows_paged", "_prefix_copy_in",
        "_prefix_copy_out", "_decode_multi", "_decode_multi_paged",
        "_spec_round", "_spec_round_paged", "_cow_blocks",
        "_swap_out_gather", "_swap_in_scatter")

    def arm_sanitizer(self):
        """Snapshot the jit caches and arm the runtime sanitizer: from
        this call on, any recompile of a fused entry point or any
        device->host pull outside `_device_get`/`_host_async` is a
        violation (raised in strict mode, tallied otherwise). Builds a
        strict sanitizer on the fly if the engine was constructed
        without one. Perf gates call this after warmup; under
        RAY_TPU_SANITIZE=1 it fires automatically after
        RAY_TPU_SANITIZE_WARMUP (default 8) steps."""
        if self.sanitizer is None:
            self.sanitizer = _sanitize.Sanitizer(label=self.engine_id)
        for name in self._SANITIZER_JIT_ENTRY_POINTS:
            self.sanitizer.watch(name, globals().get(name))
        if self.adapter_pool is not None:
            from ray_tpu.models import adapter_pool as _adapter_pool
            self.sanitizer.watch("_adapter_commit",
                                 _adapter_pool._adapter_commit)
        self.sanitizer.arm()
        return self.sanitizer

    def disarm_sanitizer(self) -> None:
        """Restore the un-sanitized fast path (interposition off)."""
        if self.sanitizer is not None:
            self.sanitizer.disarm()

    def sanitizer_stats(self) -> Dict[str, Any]:
        """Snapshot of the sanitizer plane; {} when sanitizing is off."""
        if self.sanitizer is None:
            return {}
        return self.sanitizer.stats()

    def step(self, horizon: Optional[int] = None) -> Dict[int, List[int]]:
        """Admit queued requests into free slots (at most
        max_prefills_per_step of them, same-bucket admissions batched
        into one prefill program each), then advance every live slot up
        to `horizon` tokens in ONE fused device program with ONE
        device->host transfer. Returns {req_id: [tokens]} emitted this
        step — up to `horizon` per request; a request that finishes
        mid-horizon (budget/eos/room) is frozen on device and retired
        here, and its slot admits a newcomer next step.

        ``horizon=None`` (the default) adapts: the scheduler's
        `horizon_hint` picks 1 while a queued request could take a free
        slot next step, else `decode_horizon`, capped at the largest
        remaining budget (no trailing iterations run fully frozen) and
        rounded down to a power of two (bounded compile count).

        With `pipeline_depth >= 2` and a pure-decode stretch (queue
        empty, nothing mid-prefill), the step dispatches ahead: it tops
        the in-flight ring up to `pipeline_depth` fused steps (each
        chained off the previous one's device row state) BEFORE pulling
        the oldest step's token block, so the device computes step N+1
        while the host replays step N. Per-call emissions are identical
        to the synchronous engine: each call still drains exactly one
        block, whose horizon follows the same budget arithmetic."""
        if horizon is not None and horizon < 1:
            raise ValueError("horizon must be >= 1")
        self.steps_total += 1
        if self.sanitizer is not None and not self.sanitizer.armed:
            self._san_steps += 1
            if self._san_steps > self._san_warmup:
                self.arm_sanitizer()
        emitted: Dict[int, List[int]] = {}
        # Flush the pipeline before any admission / prefill / prefix
        # copy: those paths mutate the cache from the host side and
        # read row/slot state, so every in-flight run-ahead block must
        # be replayed first (freed slots, retired requests) for the
        # admission decision to see true state.
        if self._ring and (self.scheduler.admissions_pending()
                           or self._row_prefill):
            self._flush_pipeline(emitted)
        budget = self.max_prefills_per_step or self.B
        admissions: List[Tuple[int, _Request]] = []
        begin = getattr(self.scheduler, "begin_admission_round", None)
        if begin is not None:
            begin()
        # Commit any landed adapter prefetches before gating: the
        # commit donates the stacks, so it must never race an
        # in-flight dispatch — with the ring empty (flushed above
        # whenever admissions were pending) nothing on device still
        # reads the old stack buffers.
        if self.adapter_pool is not None and not self._ring:
            self.adapter_pool.drain_prefetches()
        deferred = False
        for row in range(self.B):
            if budget <= 0 or deferred:
                break
            if self.row_req[row] is not None:
                continue
            req = None
            while len(self.scheduler):
                cand = self.scheduler.pop()
                if cand is None:
                    deferred = True  # prefix policy deferred the queue
                    break
                if cand.deadline is not None and \
                        self._clock() >= cand.deadline and \
                        not cand.resume:
                    # Expired mid-queue: shed at the admission gate —
                    # the last moment before prefill compute would be
                    # committed to a request nobody is waiting for.
                    # A PREEMPTED request is exempt: it was already
                    # admitted once, and admitted requests run to
                    # completion.
                    self._shed(cand)
                    continue
                if self.paged and not self._fits_now(cand):
                    # No room even counting evictable cold prefix
                    # blocks: capacity, not order, is the constraint —
                    # stop admitting this step and retry when decode
                    # retirements free blocks.
                    self._requeue_front(cand)
                    deferred = True
                    break
                if cand.adapter_id is not None:
                    # Adapter residency gate: acquire the slot HERE
                    # (refcount taken) so nothing admitted later this
                    # round can evict it; a cold adapter starts its
                    # async prefetch and the request waits at the
                    # queue front instead of stalling the step.
                    slot = self.adapter_pool.alloc(cand.adapter_id)
                    if slot is None:
                        self.adapter_pool.prefetch(cand.adapter_id)
                        self._requeue_front(cand)
                        self.adapter_deferrals += 1
                        self.metrics.on_adapter_defer()
                        deferred = True
                        break
                    self._pending_slots[cand.req_id] = slot
                req = cand
                break
            if req is None:
                continue       # queue drained to empty (or deferred)
            admissions.append((row, req))
            budget -= 1
        if deferred and self.trace.enabled:
            self.trace.instant("admission_defer", lane="events",
                               args={"queued": len(self.scheduler)})
        if admissions:
            self._admit_rows(admissions)
        self._advance_prefills()

        live = [b for b in range(self.B) if self.row_req[b] is not None]
        if not live:
            if self._ring:             # defensive: never strand blocks
                self._flush_pipeline(emitted)
            return emitted
        # Rows mid-chunked-prefill are NOT decodable: their last_logits
        # still hold an intermediate chunk's scatter. They ride along
        # frozen (active=False) and take their next chunk next step.
        decodable = [b for b in live if b not in self._row_prefill]
        if self.prefill_only:
            # Prefill-class replica (disaggregated fleet): a row whose
            # prefill frontier just completed holds final last_logits
            # and tok_idx=0 — exactly a preemption-at-first-token
            # state. Park it for export_request() instead of decoding;
            # the fleet hands it to a decode-class replica. Never
            # dispatch a decode program here, so the ring stays empty
            # and export never races an in-flight block.
            for b in decodable:
                rid = self.row_req[b].req_id
                if rid not in self._handoff_ready_set:
                    self._handoff_ready_set.add(rid)
                    self._handoff_ready.append(rid)
                    if self.trace.enabled:
                        self.trace.instant(
                            "handoff_ready", lane="events",
                            args={"req": rid,
                                  "prompt_tokens": int(self.row_len[b])})
            self.metrics.on_step(len(live), len(self.scheduler), 0)
            return emitted
        if len(decodable) < len(live):
            self.chunked_prefill_stalls += 1
            self.metrics.on_prefill_stall()
        if not decodable:
            self.metrics.on_step(len(live), len(self.scheduler), 0)
            return emitted

        if not self._ring:
            decodable = self._dispatch_primary(decodable, live, horizon)
        self._top_up_pipeline(decodable, horizon)
        self._drain_one(emitted)
        # End of stream: every request retired, but run-ahead blocks
        # may remain (all-masked overrun). Drain them now so pending()
        # reads true and the ring never outlives its requests.
        if self._ring and not any(r is not None for r in self.row_req):
            self._flush_pipeline(emitted)
        n_tokens = sum(len(t) for t in emitted.values())
        self.tokens_out += n_tokens
        self.metrics.on_step(
            sum(r is not None for r in self.row_req),
            len(self.scheduler), n_tokens)
        if self.paged:
            self.metrics.on_kv_pool(self.kv_pool.blocks_total,
                                    self.kv_pool.blocks_in_use,
                                    self.kv_pool.free_blocks,
                                    bytes_per_token=self.kv_bytes_per_token)
        return emitted

    # -- async pipeline ----------------------------------------------------

    def _dispatch_primary(self, decodable: List[int], live: List[int],
                          horizon: Optional[int]) -> List[int]:
        """Launch the step's PRIMARY dispatch (ring empty, host state
        fully replayed): a speculative draft/verify round when the
        engine has a draft plane and at least one decodable greedy row
        with budget to speculate into, else the plain fused horizon.
        Mid-chunked-prefill steps always take the plain H=1 path — the
        chunk cadence outranks speculation depth. Returns the possibly
        narrowed decodable set (paged reservation may preempt)."""
        if self.spec_enabled and len(decodable) == len(live):
            W, w_row = self._spec_plan(decodable)
            if W:
                if self.paged:
                    decodable, Hr = self._reserve_decode_blocks(
                        decodable, W + 1)
                    if Hr < W + 1:
                        # Pool too tight to cover the verify chunk even
                        # after preemption: decode plainly at whatever
                        # horizon the reservation could hold.
                        self._dispatch_decode(Hr, decodable, chain=None)
                        return decodable
                self._dispatch_spec(W, w_row, decodable, chain=None)
                return decodable
        H = horizon
        if H is None:
            free = self.B - len(live)
            H = self.scheduler.horizon_hint(
                free_slots=free, max_horizon=self.decode_horizon)
            if len(decodable) < len(live):
                H = 1      # keep the chunk cadence: a mid-prefill
                #            row must not wait a long horizon for
                #            its next chunk (bounded TTFT)
            # Cap at the largest remaining row budget (no trailing
            # iterations with every row frozen), rounded DOWN to a
            # power of two: the fused program recompiles per
            # distinct H, so adaptive serving touches at most
            # log2(horizon)+1 programs instead of one per budget
            # remainder.
            H = min(H, int(self.row_budget[decodable].max()))
            H = 1 << max(0, H.bit_length() - 1)
        if self.paged:
            # Grow every decodable row's chain to cover the
            # horizon, preempting victims if the pool runs dry —
            # admission capacity is pool bytes, not slots, so
            # over-admission is resolved here, not refused there.
            decodable, H = self._reserve_decode_blocks(decodable, H)
        self._dispatch_decode(H, decodable, chain=None)
        return decodable

    def _spec_plan(self, decodable: List[int]):
        """Pick this dispatch's draft width. Each greedy decodable
        row's sliding acceptance window (last 16 rounds) feeds
        `SchedulerPolicy.spec_window_hint`; the dispatch width W is the
        max hint rounded UP to a power of two (bounded compile count,
        like the horizon), capped at `spec_window`, and each row keeps
        its own hint as a traced acceptance cap (`w_row`) — a shrinking
        row narrows its drafting without recompiling anything. Returns
        (0, None) to decline speculation: no decodable greedy row, or
        every greedy row down to its last budgeted token (a plain step
        emits the same single token with a cheaper program)."""
        greedy_rows = [b for b in decodable if self._row_greedy[b]]
        if not greedy_rows:
            return 0, None
        if int(self.row_budget[greedy_rows].max()) <= 1:
            return 0, None
        rates: List[Optional[float]] = []
        for b in greedy_rows:
            prop = sum(p for p, _ in self._spec_hist[b])
            acc = sum(a for _, a in self._spec_hist[b])
            rates.append(acc / prop if prop else None)
        hints = self.scheduler.spec_window_hint(
            rates=rates, spec_window=self.spec_window)
        w_row = np.ones((self.B,), np.int32)
        wmax = 1
        for b, w in zip(greedy_rows, hints):
            w = max(1, min(int(w), self.spec_window))
            w_row[b] = w
            wmax = max(wmax, w)
        return min(self.spec_window, _pow2(wmax)), w_row

    def _dispatch_spec(self, W: int, w_row: np.ndarray,
                       rows: List[int],
                       chain: Optional[tuple]) -> None:
        """Launch ONE speculative draft/verify round — the spec twin of
        `_dispatch_decode`, same async contract: emit block's
        `copy_to_host_async` issued immediately, full device carry
        (including the draft-lag lane) stored for run-ahead chaining,
        ONE host pull later at drain. The ring entry's H is W+1 (the
        emit block height and the pessimistic in-flight token count)."""
        tr = self.trace
        t0 = tr.now() if tr.enabled else 0.0
        if chain is None:
            active = np.array([self.row_req[b] is not None
                               and b not in self._row_prefill
                               for b in range(self.B)])
            args = (jnp.asarray(self.row_len), jnp.asarray(active),
                    jnp.asarray(self.row_budget),
                    jnp.asarray(self._tok_idx),
                    jnp.asarray(self._d_lag),
                    jnp.asarray(self._d_tok))
        else:
            args = chain
        rg = jnp.asarray(self._row_greedy)
        all_greedy = bool(self._row_greedy.all())
        wr = jnp.asarray(w_row)
        if self.paged:
            bt_dev = jnp.asarray(self._bt)
            btd_dev = jnp.asarray(self._bt_d)
            if self._shardings is not None:
                bt_dev = jax.device_put(bt_dev,
                                        self._shardings.replicated)
                btd_dev = jax.device_put(btd_dev,
                                         self._shardings.replicated)
            (toks, self._pool_k, self._pool_v, self._pool_dk,
             self._pool_dv, self._scale_k, self._scale_v,
             self._scale_dk, self._scale_dv, self._last_logits, rl,
             ac, bu, ti, dl, dt) = _spec_round_paged(
                self.params, self.draft_params, self._pool_k,
                self._pool_v, self._pool_dk, self._pool_dv, bt_dev,
                btd_dev, self._last_logits, *args,
                jnp.asarray(self._row_keys), rg, wr, self.temperature,
                self.cfg, self.draft_cfg, W, all_greedy, self.top_k,
                self.top_p, self.eos_id, shardings=self._shardings,
                scale_k=self._scale_k, scale_v=self._scale_v,
                scale_dk=self._scale_dk, scale_dv=self._scale_dv,
                qspec=self.kv_quant_spec)
        else:
            (toks, self.cache, self._d_cache, self._last_logits, rl,
             ac, bu, ti, dl, dt) = _spec_round(
                self.params, self.draft_params, self.cache,
                self._d_cache, self._last_logits, *args,
                jnp.asarray(self._row_keys), rg, wr, self.temperature,
                self.cfg, self.draft_cfg, W, all_greedy, self.top_k,
                self.top_p, self.eos_id, shardings=self._shardings)
        _host_async(toks)
        self._ring.append(_InflightStep(
            toks, W + 1, list(rows), run_ahead=chain is not None,
            chain=(rl, ac, bu, ti, dl, dt), spec=True, w_max=W,
            w_row=np.array(w_row, np.int32)))
        self.decode_dispatches += 1
        self.spec_dispatches += 1
        self.metrics.on_dispatch(W + 1, host_syncs=0)
        if tr.enabled:
            # The draft scan and verify pass live inside ONE fused
            # program, so the dispatch seam carries the spec_draft
            # span (proposal width known here) and the drain seam
            # carries spec_verify (acceptance known there).
            tr.add("spec_draft", t0, tr.now() - t0, lane="dispatch",
                   args={"window": W,
                         "proposed": int(w_row[rows].sum()),
                         "rows": len(rows),
                         "run_ahead": chain is not None})

    def _dispatch_decode(self, H: int, rows: List[int],
                         chain: Optional[tuple]) -> None:
        """Launch ONE fused decode step without waiting on anything:
        from replayed host state after a flush (`chain=None`), or
        chained off the previous in-flight dispatch's device-carried
        row state (run-ahead). The token block's `copy_to_host_async`
        is issued immediately, so the transfer overlaps the device
        computing the block — and any queued successors."""
        tr = self.trace
        t0 = tr.now() if tr.enabled else 0.0
        if chain is None:
            active = np.array([self.row_req[b] is not None
                               and b not in self._row_prefill
                               for b in range(self.B)])
            args = (jnp.asarray(self.row_len), jnp.asarray(active),
                    jnp.asarray(self.row_budget),
                    jnp.asarray(self._tok_idx))
        else:
            args = chain
        # The static greedy flag is the all-greedy fast path: without
        # per-request overrides it equals the engine-wide mode exactly
        # (the lane resets to the default at retirement), so existing
        # engines compile the same two programs they always did.
        rg = jnp.asarray(self._row_greedy)
        all_greedy = bool(self._row_greedy.all())
        # Multi-LoRA lane: the pool stacks + the [B] slot lane ride
        # every dispatch (slot 0 = zero null adapter, so base-only
        # rows are untouched); adapter_pool=None passes None/None —
        # no extra pytree leaves, the exact pre-LoRA programs.
        if self.adapter_pool is not None:
            adapters = self.adapter_pool.stacks
            row_slot = jnp.asarray(self._row_slot)
        else:
            adapters = row_slot = None
        if self.paged:
            # Snapshot the block table at dispatch: jnp.asarray copies
            # it to device, so host-side growth between chained
            # dispatches only reaches FUTURE dispatches (in-flight
            # steps never read past the coverage they were reserved).
            bt_dev = jnp.asarray(self._bt)
            if self._shardings is not None:
                bt_dev = jax.device_put(bt_dev,
                                        self._shardings.replicated)
            (toks, self._pool_k, self._pool_v, self._scale_k,
             self._scale_v, self._last_logits,
             rl, ac, bu, ti) = _decode_multi_paged(
                self.params, self._pool_k, self._pool_v, bt_dev,
                self._last_logits, *args, jnp.asarray(self._row_keys),
                rg, self.temperature, self.cfg, H, all_greedy,
                self.top_k, self.top_p, self.eos_id,
                shardings=self._shardings, adapters=adapters,
                row_slot=row_slot, scale_k=self._scale_k,
                scale_v=self._scale_v, qspec=self.kv_quant_spec)
        else:
            toks, self.cache, self._last_logits, rl, ac, bu, ti = \
                _decode_multi(
                    self.params, self.cache, self._last_logits, *args,
                    jnp.asarray(self._row_keys), rg, self.temperature,
                    self.cfg, H, all_greedy, self.top_k, self.top_p,
                    self.eos_id, shardings=self._shardings,
                    adapters=adapters, row_slot=row_slot)
        _host_async(toks)
        self._ring.append(_InflightStep(toks, H, list(rows),
                                        run_ahead=chain is not None,
                                        chain=(rl, ac, bu, ti)))
        self.decode_dispatches += 1
        self.metrics.on_dispatch(H, host_syncs=0)
        if tr.enabled:
            tr.add("dispatch", t0, tr.now() - t0, lane="dispatch",
                   args={"horizon": H, "rows": len(rows),
                         "run_ahead": chain is not None})

    def _top_up_pipeline(self, rows: List[int],
                         horizon: Optional[int]) -> None:
        """Run ahead: keep up to `pipeline_depth` fused steps in flight
        while the engine is in a pure-decode stretch (no admission
        could change the batch, no row mid-prefill). Each queued step
        chains the previous dispatch's device row state, so no host
        sync happens between dispatches. Horizons are chosen from host
        budgets minus everything already in flight — pessimistic, so a
        queued step is never provably all-frozen; rows that finish
        mid-flight still mask their tail iterations on device
        (`pipeline_overrun_tokens`)."""
        if (self.pipeline_depth < 2 or self._row_prefill
                or self.scheduler.admissions_pending()):
            return
        while len(self._ring) < self.pipeline_depth:
            last = self._ring[-1]
            inflight = sum(e.H for e in self._ring)
            rem = int(self.row_budget[rows].max()) - inflight
            if rem <= 0:
                break              # every further iteration would be
                #                    overrun — nothing left to compute
            if last.spec:
                # Chain another speculative round at the SAME widths:
                # the adaptive window can only move once the host has
                # replayed acceptance, and an unchanged (W, w_row)
                # keeps the chained dispatch on the compiled program.
                # H accounting is pessimistic (every round could emit
                # w_max+1), same discipline as plain run-ahead.
                if self.paged and not self._ensure_decode_blocks(
                        rows, last.w_max + 1, inflight):
                    break
                self._dispatch_spec(last.w_max, last.w_row, rows,
                                    chain=last.chain)
                continue
            if horizon is not None:
                Hn = horizon
            else:
                Hn = self.scheduler.horizon_hint(
                    free_slots=self.B - sum(r is not None
                                            for r in self.row_req),
                    max_horizon=self.decode_horizon)
                Hn = min(Hn, rem)
                Hn = 1 << max(0, Hn.bit_length() - 1)
            if self.paged and not self._ensure_decode_blocks(
                    rows, Hn, inflight):
                # Pool dry: no run-ahead. Preemption needs replayed
                # host state, so it only runs on the primary dispatch
                # path once the ring empties.
                break
            self._dispatch_decode(Hn, rows,
                                  chain=self._ring[-1].chain)

    def _drain_one(self, emitted: Dict[int, List[int]]) -> None:
        """Pull the OLDEST in-flight token block to the host (its async
        copy has been in progress since dispatch) and replay it. With
        the ring topped up first, the device is already computing the
        next step(s) while this replay runs — the overlap that hides
        the host bookkeeping."""
        tr = self.trace
        t0 = tr.now() if tr.enabled else 0.0
        entry = self._ring.popleft()
        depth = len(self._ring) + 1    # steps in flight at this drain
        self._pl_depth_sum += depth
        self._pl_depth_n += 1
        block = _device_get(entry.toks)
        self.host_syncs += 1
        nbytes = int(getattr(block, "nbytes", block.size * 4))
        self.host_transfer_bytes += nbytes
        self.metrics.on_host_sync(nbytes=nbytes)
        sp_rounds, sp_prop, sp_acc = self._emit_block(
            block, entry, emitted)
        self.metrics.on_pipeline_drain(depth, len(self._ring))
        if entry.spec and sp_rounds:
            self.metrics.on_spec_round(sp_rounds, sp_prop, sp_acc)
            if self.spec_metrics is not None:
                from ray_tpu.models.speculative import SpecStats
                self.spec_metrics.observe(SpecStats(
                    rounds=sp_rounds, proposed=sp_prop,
                    accepted=sp_acc))
        if entry.spec and tr.enabled:
            # The draft scan and verify pass live inside ONE fused
            # program, so acceptance is only knowable here at drain:
            # spec_draft marks the dispatch seam, spec_verify the
            # drain seam where the accept counts land.
            tr.add("spec_verify", t0, tr.now() - t0, lane="drain",
                   args={"window": entry.w_max, "rounds": sp_rounds,
                         "proposed": sp_prop, "accepted": sp_acc})
        if tr.enabled:
            tr.add("host_drain", t0, tr.now() - t0, lane="drain",
                   args={"horizon": entry.H, "depth": depth,
                         "bytes": nbytes})

    def _flush_pipeline(self, emitted: Dict[int, List[int]]) -> None:
        """Drain EVERY in-flight step. Called before any admission /
        prefill / prefix copy, and at end of stream — the points where
        host state must be fully caught up with the device."""
        if not self._ring:
            return
        self.pipeline_flushes += 1
        self.metrics.on_pipeline_flush()
        tr = self.trace
        t0 = tr.now() if tr.enabled else 0.0
        steps = len(self._ring)
        while self._ring:
            self._drain_one(emitted)
        if tr.enabled:
            tr.add("pipeline_flush", t0, tr.now() - t0, lane="drain",
                   args={"steps": steps})

    def stats(self) -> Dict[str, float]:
        """Flat numeric telemetry snapshot (EngineMetrics.stats) plus
        the engine's instantaneous queue/slot state — safe to publish
        as gauges (serve.metrics.report_engine_stats)."""
        out = self.metrics.stats()
        out["queue_depth"] = float(len(self.scheduler))
        out["live_slots"] = float(
            sum(r is not None for r in self.row_req))
        out["slot_occupancy"] = out["live_slots"] / self.B
        # Fleet plane: the router scores replicas on these three plus
        # the TTFT/TPOT percentiles from EngineMetrics.stats().
        out["requests_shed"] = float(self.requests_shed)
        out["pending_prefill_tokens"] = float(
            self.pending_prefill_tokens())
        out["draining"] = 1.0 if self.draining else 0.0
        # Engine lifetime on the injectable clock + the plain-int step
        # counter (the metrics-plane `steps` field disappears under
        # enable_metrics=False; these two never do).
        out["uptime_s"] = max(0.0, self._clock() - self._start_t)
        out["steps_total"] = float(self.steps_total)
        # Engine-level dispatch accounting (kept even when metrics are
        # disabled — benchmarks read these to report syncs per token).
        # Every derived ratio guards its denominator: a fresh engine
        # (no token emitted, no prefill run) reports 0.0, never NaN.
        def _ratio(num: float, den: float) -> float:
            return num / den if den else 0.0

        out["decode_dispatches"] = float(self.decode_dispatches)
        out["prefill_dispatches"] = float(self.prefill_dispatches)
        out["host_syncs"] = float(self.host_syncs)
        out["host_syncs_per_token"] = _ratio(self.host_syncs,
                                             self.tokens_out)
        # Tensor-parallel plane: tp_degree is 1 for an unsharded
        # engine; transfer bytes count the [H, B] token blocks pulled
        # at drain — the replicated choke point, so bytes/token must
        # NOT grow with tp degree (microbench gates this).
        out["tp_degree"] = float(self.tp_degree)
        out["host_transfer_bytes"] = float(self.host_transfer_bytes)
        out["host_transfer_bytes_per_token"] = _ratio(
            self.host_transfer_bytes, self.tokens_out)
        out["dispatches_per_token"] = _ratio(self.decode_dispatches,
                                             self.tokens_out)
        # Prefill efficiency: real suffix tokens vs bucket/pow2 filler.
        out["prefill_real_tokens"] = float(self.prefill_real_tokens)
        out["prefill_padded_tokens"] = float(self.prefill_padded_tokens)
        out["prefill_padding_waste_frac"] = _ratio(
            self.prefill_padded_tokens,
            self.prefill_real_tokens + self.prefill_padded_tokens)
        # Prefix-reuse plane: reused = prompt tokens COPIED from the
        # pool; recomputed (= prefill_real_tokens) = prompt tokens the
        # prefill actually ran.
        out["prefix_lookups"] = float(self.prefix_lookups)
        out["prefix_hits"] = float(self.prefix_hits)
        out["prefix_hit_rate"] = _ratio(self.prefix_hits,
                                        self.prefix_lookups)
        out["prefix_reused_tokens"] = float(self.prefix_reused_tokens)
        out["prefix_reused_frac"] = _ratio(
            self.prefix_reused_tokens,
            self.prefix_reused_tokens + self.prefill_real_tokens)
        out["prefix_evictions"] = float(self.prefix_evictions)
        out["prefix_copy_dispatches"] = float(self.prefix_copy_dispatches)
        out["chunked_prefill_stalls"] = float(self.chunked_prefill_stalls)
        # Async-pipeline plane. depth_effective is the mean number of
        # fused steps in flight at each drain (1.0 = synchronous; ->
        # pipeline_depth when run-ahead is sustained); host_lag_steps
        # is the instantaneous ring length (dispatched, not yet
        # replayed); overrun tokens are masked device iterations run
        # ahead for rows that had already finished. Fresh engine: all
        # 0.0 (the _ratio guard).
        out["pipeline_depth"] = float(self.pipeline_depth)
        out["pipeline_depth_effective"] = _ratio(self._pl_depth_sum,
                                                 self._pl_depth_n)
        out["pipeline_flushes"] = float(self.pipeline_flushes)
        out["pipeline_overrun_tokens"] = float(
            self.pipeline_overrun_tokens)
        out["host_lag_steps"] = float(len(self._ring))
        if self._prefix is not None:
            out["prefix_blocks_in_use"] = float(self._prefix.blocks_in_use)
            out["prefix_blocks_total"] = float(self._prefix.blocks_total)
        # Paged-KV plane: zero-copy sharing, CoW, preempt-and-swap.
        # Counters are identically 0.0 on the dense engine so fleet
        # rollups sum them without mode checks.
        out["paged"] = 1.0 if self.paged else 0.0
        out["kv_blocks_shared"] = float(self.kv_blocks_shared)
        out["kv_block_cows"] = float(self.kv_block_cows)
        out["preemptions"] = float(self.preemptions)
        out["swap_ins"] = float(self.swap_ins)
        out["swap_outs"] = float(self.swap_outs)
        out["swap_in_bytes"] = float(self.swap_in_bytes)
        out["swap_out_bytes"] = float(self.swap_out_bytes)
        out["kv_used_fraction"] = self.kv_used_fraction()
        # Disaggregated-handoff plane: identically 0.0 on a colocated
        # engine (prefill_only never set, import never called) so
        # fleet rollups sum blindly.
        out["prefill_only"] = 1.0 if self.prefill_only else 0.0
        out["handoffs_out"] = float(self.handoffs_out)
        out["handoffs_in"] = float(self.handoffs_in)
        out["handoff_out_bytes"] = float(self.handoff_out_bytes)
        out["handoff_in_bytes"] = float(self.handoff_in_bytes)
        out["requests_handoff_ready"] = float(len(self._handoff_ready))
        # Quantized-KV plane: bytes/token is the concurrency lever the
        # fleet watches (see docs/serving.md); identically dense-sized
        # (and quant_enabled 0.0) on an unquantized engine.
        out["kv_quant_enabled"] = 1.0 if self.kv_quant else 0.0
        out["kv_bytes_per_token"] = float(self.kv_bytes_per_token)
        out["kv_bytes_per_block"] = float(self.kv_bytes_per_block)
        if self.paged:
            pool = self.kv_pool
            out["kv_pool_blocks_total"] = float(pool.blocks_total)
            out["kv_pool_blocks_in_use"] = float(pool.blocks_in_use)
            out["kv_pool_blocks_free"] = float(pool.free_blocks)
            out["kv_pool_occupancy"] = _ratio(pool.blocks_in_use,
                                              pool.blocks_total)
            out["kv_free_blocks"] = float(self.kv_free_blocks())
            out["requests_swapped"] = float(len(self._swapped))
        # Speculative plane: identically 0.0 with spec off, so fleet
        # rollups sum/weight them without mode checks. acceptance_rate
        # is accepted/proposed over the engine's lifetime;
        # window_effective is the mean per-round draft width the
        # adaptive policy actually dispatched (proposed/rounds).
        out["spec_enabled"] = 1.0 if self.spec_enabled else 0.0
        out["spec_window"] = float(self.spec_window
                                   if self.spec_enabled else 0)
        out["spec_dispatches"] = float(self.spec_dispatches)
        out["spec_rounds"] = float(self.spec_rounds)
        out["spec_proposed"] = float(self.spec_proposed)
        out["spec_accepted"] = float(self.spec_accepted)
        out["spec_acceptance_rate"] = _ratio(self.spec_accepted,
                                             self.spec_proposed)
        out["spec_window_effective"] = _ratio(self.spec_proposed,
                                              self.spec_rounds)
        out["spec_draft_tokens_wasted"] = float(self.spec_wasted)
        out["spec_prefill_dispatches"] = float(
            self.spec_prefill_dispatches)
        if self.spec_enabled and self.paged:
            out["spec_kv_pool_blocks_in_use"] = float(
                self.kv_pool_d.blocks_in_use)
        # Multi-LoRA plane: identically 0.0 with no adapter pool, so
        # fleet rollups (and the perf gate's zero check) need no mode
        # branch. Pool fields come from AdapterPool.stats().
        out["adapter_enabled"] = 1.0 if self.adapter_pool else 0.0
        out["adapter_prefetch_deferrals"] = float(self.adapter_deferrals)
        if self.adapter_pool is not None:
            out.update(self.adapter_pool.stats())
        else:
            out.update({
                "adapters_registered": 0.0, "adapter_slots": 0.0,
                "adapter_slots_resident": 0.0,
                "adapter_slots_pinned": 0.0, "adapter_lookups": 0.0,
                "adapter_hits": 0.0, "adapter_hit_rate": 0.0,
                "adapter_prefetches": 0.0, "adapter_evictions": 0.0,
            })
        return out

    def run(self) -> Dict[int, List[int]]:
        """Drain queue + slots; returns {req_id: generated tokens} for
        every finished request and POPS them from the engine (a
        long-running server that never popped would leak one _Request
        per call served)."""
        while self.pending():
            self.step()
        return {rid: self.pop_result(rid) for rid in list(self.finished)}

    def dump_trace(self, path: Optional[str] = None) -> List[dict]:
        """chrome://tracing export of this engine's request-lifecycle
        spans (pid = engine_id, tid = one lane per request plus
        `engine:dispatch` / `engine:drain` step lanes). Writes JSON to
        `path` (falling back to the RAY_TPU_TRACE dump path) and
        returns the event list — empty with tracing off."""
        return self.trace.dump(path, pid=self.engine_id)

    def pop_result(self, req_id: int) -> List[int]:
        """Remove a FINISHED request from the engine and return its
        generated tokens. Long-running callers driving step() directly
        must pop each request as it finishes (see `finished`). A shed
        request pops an empty list — check `shed_ids` BEFORE popping
        to distinguish a shed from a zero-token finish."""
        if req_id not in self.finished:
            raise KeyError(f"request {req_id} unknown or not finished")
        self.finished.discard(req_id)
        self.shed_ids.discard(req_id)
        return self.results.pop(req_id).tokens

    # -- fleet integration: drain hook + router load probes ----------------

    def begin_drain(self) -> None:
        """Stop accepting new requests; everything already submitted
        (queued or in-flight) still runs to completion. This is the
        flush-before-removal half of fleet scale-down: the fleet stops
        routing to a DRAINING replica, keeps stepping it until
        `pending()` reads False, then removes it — so an admitted
        token is never lost to a scale decision. Idempotent."""
        if self.trace.enabled and not self.draining:
            self.trace.instant("drain", lane="events",
                               args={"queued": len(self.scheduler)})
        self.draining = True

    def drain(self) -> Dict[int, List[int]]:
        """`begin_drain()` + run to empty: flushes the async pipeline,
        finishes every queued/in-flight request, and returns
        {req_id: tokens} for all of them (popping, like `run()`)."""
        self.begin_drain()
        return self.run()

    def halt(self) -> None:
        """Abandon this engine's work WITHOUT completing it — the
        fleet's failure path (the opposite of drain's flush-before-
        removal). Discards the async pipeline ring (in-flight device
        steps are never replayed), releases every live row's paged KV
        blocks (refcount hygiene: trie-shared blocks survive through
        the trie's own references, private blocks free), drops the
        swap ledger and the queue, and refuses new submits. Host-side
        request bookkeeping (`results`: prompt, emitted tokens,
        priority) is deliberately KEPT — it is what the fleet
        reconstructs failover resubmissions from. Idempotent; never
        raises (the engine may be arbitrarily broken when called)."""
        if self.halted:
            return
        self.halted = True
        self.draining = True
        if self.trace.enabled:
            self.trace.instant(
                "halt", lane="events",
                args={"queued": len(self.scheduler),
                      "live_rows": sum(r is not None
                                       for r in self.row_req),
                      "inflight_steps": len(self._ring)})
        self._ring.clear()
        self._row_prefill.clear()
        for row in range(self.B):
            if self.paged:
                try:
                    self._release_row_blocks(row)
                except Exception:
                    pass
            if self._row_slot[row] and self.adapter_pool is not None:
                try:
                    self.adapter_pool.decref(int(self._row_slot[row]))
                except Exception:
                    pass
            self._row_slot[row] = 0
            self.row_req[row] = None
            self.row_len[row] = 0
            self.row_budget[row] = 0
            self._tok_idx[row] = 0
        if self.adapter_pool is not None:
            for slot in self._pending_slots.values():
                try:
                    self.adapter_pool.decref(slot)
                except Exception:
                    pass
        self._pending_slots.clear()
        self._handoff_ready.clear()
        self._handoff_ready_set.clear()
        if self.paged:
            self._swapped.clear()
        # Drop the queue wholesale (a fresh empty policy, not N pops:
        # a deferring policy could legally return None forever once
        # its probe's world is gone). The queued _Request objects stay
        # reachable through `results` for failover reconstruction.
        self.scheduler = FIFOPolicy()

    def pending_prefill_tokens(self) -> int:
        """Prompt tokens this engine has accepted but not yet
        prefilled: every queued request's full prompt plus the
        uncovered suffix of every row mid-chunked-prefill. A pure host
        count (zero device syncs) — the fleet router's per-replica
        cost signal: a replica may show free slots yet owe seconds of
        prefill to requests ahead of the newcomer."""
        n = sum(len(st.prompt) - st.pos
                for st in self._row_prefill.values())
        queued = getattr(self.scheduler, "queued_requests", None)
        if queued is not None:
            try:
                for r in queued():
                    swap = (self._swapped.get(r.req_id)
                            if self.paged else None)
                    if swap is not None and swap.k is not None:
                        continue   # swap-in is a scatter, no prefill owed
                    if swap is not None:
                        n += len(r.prompt) + len(r.tokens)  # replay
                        continue
                    n += len(r.prompt)
            except NotImplementedError:
                pass     # custom policy without the probe: slots-only
        return n

    def kv_free_blocks(self) -> int:
        """KV blocks an admission could claim right now: free +
        evictable cold prefix blocks. 0 for the dense engine (no
        pool) — the router falls back to `kv_used_fraction`. Pure
        host arithmetic, zero device syncs."""
        if not self.paged:
            return 0
        n = self.kv_pool.free_blocks
        if self._prefix is not None:
            n += self._prefix.evictable_blocks()
        return n

    def kv_used_fraction(self) -> float:
        """Unreclaimable KV pressure in [0, 1] — the fleet router's
        occupancy signal. Paged: fraction of pool blocks neither free
        nor evictable-cold. Dense: live slots / batch slots (each
        live slot pins a full max_len cache row, so slot occupancy IS
        KV occupancy there)."""
        if self.paged:
            total = self.kv_pool.blocks_total
            if not total:
                return 1.0
            return max(0.0, 1.0 - self.kv_free_blocks() / total)
        return sum(r is not None for r in self.row_req) / self.B

    def prefix_match_tokens(self, prompt: List[int]) -> int:
        """Prompt tokens this engine could COPY from its prefix pool
        instead of prefilling, right now (0 without a prefix cache).
        A pure host trie walk with peek=True: probing every replica
        per routing decision must not perturb any replica's LRU
        recency — only the replica that WINS the request touches its
        trie (at admission)."""
        if self._prefix is None:
            return 0
        ids, _ = self._prefix.match(prompt, peek=True)
        return len(ids) * self.prefix_block

    # -- internals ---------------------------------------------------------

    def _bucket(self, n: int) -> int:
        if not self.bucket_lens:
            return n
        return min(1 << (n - 1).bit_length(), self.max_len)

    def _req_key(self, req: _Request) -> np.ndarray:
        """Per-request sampling stream: the submitted key verbatim, or
        a distinct stream mixed host-side from the engine key and the
        request id (no device dispatch per admission)."""
        if req.rng is not None:
            return req.rng
        mix0 = (req.req_id * 0x9E3779B9 + 1) & 0xFFFFFFFF
        mix1 = (req.req_id * 0x85EBCA6B + 1) & 0xFFFFFFFF
        return np.array([int(self._base_key[0]) ^ mix0,
                         int(self._base_key[1]) ^ mix1], np.uint32)

    def _shed(self, req: _Request) -> None:
        """Retire a past-deadline request WITHOUT admitting it: no
        slot, no prefill, no tokens. It lands in `finished` (and
        `shed_ids`) like a normal completion so callers polling
        finished/pop_result need no special path."""
        req.done = True
        req.shed = True
        self.finished.add(req.req_id)
        self.shed_ids.add(req.req_id)
        self.requests_shed += 1
        self.metrics.on_shed(req.req_id)
        if self.trace.enabled:
            self.trace.close("queue_wait", req.req_id, {"shed": True})
            self.trace.finish(req.req_id, {"shed": True}, name="shed")

    def _on_prefix_evict(self, n: int) -> None:
        self.prefix_evictions += n
        self.metrics.on_prefix_evictions(n)

    def _prefix_probe(self, prompt) -> Tuple[int, Optional[tuple],
                                             bool]:
        """(matched_tokens, prefix_group_key, next_block_pending) for
        the prefix-affinity scheduler — a pure host trie walk, zero
        device dispatches. The group key (the prompt's first block) is
        None for prompts too short to ever share a block."""
        ids, pending = self._prefix.match(prompt)
        T = self.prefix_block
        key = tuple(prompt[:T]) if len(prompt) > T else None
        return len(ids) * T, key, pending

    def _admit_rows(self, admissions: List[Tuple[int, _Request]]) -> None:
        """Bind this step's admissions to their rows and start their
        prefills. With the prefix cache on, each admission first probes
        the trie: a warm prompt's matched blocks are COPIED from the
        device pool into the row (grouped so same-chain-length copies
        share ONE `_prefix_copy_in` program) and only the suffix is
        prefilled; novel full blocks are registered PENDING and copied
        out to the pool as the row's prefill covers them. The actual
        prefill work — whole suffix, or `prefill_chunk`-sized pieces
        across steps — runs in `_advance_prefills`. First tokens are
        NOT sampled here: each row's last-prompt logits stay on device
        in `_last_logits` and the fused decode samples them — admission
        costs zero host round-trips. The paged engine admits through
        `_admit_rows_paged` instead: matched blocks are SHARED (incref,
        zero copies), not copied."""
        if self.paged:
            self._admit_rows_paged(admissions)
            return
        copy_groups: Dict[int, List[Tuple[int, List[int]]]] = {}
        draft_seeds: List[Tuple[int, List[int]]] = []
        for row, req in admissions:
            self.metrics.on_admit(req.req_id)   # queue wait ends here
            if self.trace.enabled:
                self.trace.close("queue_wait", req.req_id)
                self.trace.instant("admit", req.req_id, {"row": row})
            if req.resume and req.tokens:
                # Fleet-failover resume (dense engine): replay
                # prompt + already-emitted tokens as the prefill —
                # mathematically the K/V the dead replica held — and
                # continue the stream at the saved token index. No
                # trie traffic: emitted tokens are not a shareable
                # prompt, and this replica may never have seen the
                # prompt's blocks.
                replay = list(req.prompt) + list(req.tokens)
                self.row_req[row] = req
                self.row_len[row] = 0
                self.row_budget[row] = (req.max_new_tokens
                                        - len(req.tokens))
                self._tok_idx[row] = len(req.tokens)
                self._row_keys[row] = self._req_key(req)
                self._row_greedy[row] = (self.greedy
                                         if req.greedy is None
                                         else bool(req.greedy))
                self._row_slot[row] = self._pending_slots.pop(
                    req.req_id, 0)
                self._row_prefill[row] = _PrefillState(req, 0, [],
                                                       prompt=replay)
                if self.spec_enabled:
                    draft_seeds.append((row, replay))
                continue
            start = 0
            nodes: list = []
            # Adapter rows BYPASS the prefix trie entirely: their K/V
            # depends on the adapter's deltas, so a block produced
            # under adapter X must never be matched by (or registered
            # for) a request under adapter Y or the base model.
            if self._prefix is not None and req.adapter_id is None:
                ids, _ = self._prefix.match(req.prompt)
                self.prefix_lookups += 1
                T = self.prefix_block
                if ids:
                    self.prefix_hits += 1
                    start = len(ids) * T
                    self.prefix_reused_tokens += start
                    # Pad the chain to a power of two (repeat the last
                    # block: its rewrite is overwritten by the suffix
                    # prefill / never attended) so a handful of copy-in
                    # compiles cover every chain length.
                    nbp = _pow2(len(ids))
                    if nbp * T > self.max_len:
                        nbp = len(ids)
                    ids_p = list(ids) + [ids[-1]] * (nbp - len(ids))
                    copy_groups.setdefault(nbp, []).append((row, ids_p))
                nodes = self._prefix.extend(req.prompt)
                self.metrics.on_prefix(hit=bool(ids), reused_tokens=start)
                if self.trace.enabled:
                    self.trace.instant(
                        "prefix_match", req.req_id,
                        {"hit": bool(ids), "matched_tokens": start})
            self.row_req[row] = req
            self.row_len[row] = start          # frontier: copied prefix
            self.row_budget[row] = req.max_new_tokens
            self._tok_idx[row] = 0
            self._row_keys[row] = self._req_key(req)
            self._row_greedy[row] = (self.greedy if req.greedy is None
                                     else bool(req.greedy))
            self._row_slot[row] = self._pending_slots.pop(req.req_id, 0)
            self._row_prefill[row] = _PrefillState(req, start, nodes)
            if self.spec_enabled:
                # The draft plane has no prefix cache: even a warm
                # target admission seeds the draft with the FULL
                # prompt, piggybacked on this admission step.
                draft_seeds.append((row, list(req.prompt)))
        for nbp in sorted(copy_groups):
            grp = copy_groups[nbp]
            n = len(grp)
            n_pad = _pow2(n)
            rows = np.zeros((n_pad,), np.int32)
            bids = np.zeros((n_pad, nbp), np.int32)
            for i, (row, ids_p) in enumerate(grp):
                rows[i] = row
                bids[i] = ids_p
            rows[n:] = rows[n - 1]     # duplicate scatters: identical
            bids[n:] = bids[n - 1]     # values, deterministic result
            self.cache = _prefix_copy_in(
                self.cache, self._pool_k, self._pool_v,
                jnp.asarray(bids), jnp.asarray(rows), nbp,  # graftlint: disable=jit-hygiene -- one compile per chain-length bucket is deliberate; nbp is bounded by max_len/prefix_block
                self.prefix_block, shardings=self._shardings)
            self.prefix_copy_dispatches += 1
        self._seed_draft_rows(draft_seeds)

    # -- paged KV: admission, block accounting, preempt-and-swap -----------

    def _admit_rows_paged(
            self, admissions: List[Tuple[int, _Request]]) -> None:
        """Paged admission: bind each request to a BLOCK CHAIN instead
        of a cache row. A warm prompt's matched blocks are shared by
        incref — zero bytes move, the PR-4 `_prefix_copy_in` gather
        does not exist on this path. A FULL-prompt match keeps all but
        the tail block shared and copies the tail once (copy-on-write:
        the row's first generated token must extend it). Novel prompt
        blocks are freshly allocated, registered PENDING in the trie
        (the row's prefill writes them in place — commit needs no copy
        either), and the suffix prefills exactly as in dense mode."""
        T = self.prefix_block
        cow_pairs: List[Tuple[int, int]] = []
        draft_seeds: List[Tuple[int, List[int]]] = []
        for row, req in admissions:
            self.metrics.on_admit(req.req_id)
            swap = self._swapped.pop(req.req_id, None)
            if swap is not None:
                if not self._swap_in_row(row, req, swap):
                    # The admission gate's estimate went stale (an
                    # earlier admission this step took the headroom):
                    # requeue; the slot stays empty this round.
                    self._swapped[req.req_id] = swap
                    self._drop_pending_slot(req)
                    self._requeue_front(req)
                elif self.spec_enabled:
                    # The swap ledger never carries the draft plane:
                    # re-seed it from prompt + emitted tokens (the
                    # exact sequence the target's restored K/V
                    # encodes), so acceptance recovers immediately.
                    draft_seeds.append(
                        (row, list(req.prompt) + list(req.tokens)))
                continue
            if self.trace.enabled:
                self.trace.close("queue_wait", req.req_id)
                self.trace.instant("admit", req.req_id, {"row": row})
            start = 0
            shared: List[int] = []
            cow_src: Optional[int] = None
            nodes: list = []
            # Adapter rows bypass the trie (see _admit_rows): shared
            # K/V must not cross adapter boundaries.
            if self._prefix is not None and req.adapter_id is None:
                ids, _ = self._prefix.match(req.prompt, allow_full=True)
                self.prefix_lookups += 1
                if ids and len(ids) * T == len(req.prompt):
                    # Full-prompt hit: share every block but the tail,
                    # which the row must grow — that one is duplicated
                    # by `_cow_blocks` (the round's single batched
                    # copy) and the prefill recomputes ONLY the last
                    # prompt token to land its true next-token logits.
                    cow_src = int(ids[-1])
                    shared = [int(i) for i in ids[:-1]]
                    start = len(req.prompt) - 1
                elif ids:
                    shared = [int(i) for i in ids]
                    start = len(shared) * T
            n_total = -(-len(req.prompt) // T)
            # Pin the shared blocks FIRST: holding the row's reference
            # means the eviction fallback inside _pool_alloc can never
            # recycle them out from under this admission.
            self.kv_pool.incref(shared)
            new_ids = self._pool_alloc(n_total - len(shared))
            if new_ids is None:
                self.kv_pool.decref(shared)
                self._drop_pending_slot(req)
                if self.trace.enabled:
                    # Back to the queue: re-open queue_wait so the
                    # retry wait stays a span, not a trace gap.
                    self.trace.open("queue_wait", req.req_id)
                self._requeue_front(req)
                continue
            if cow_src is not None:
                cow_pairs.append((cow_src, new_ids[0]))
                self.kv_block_cows += 1
                self.metrics.on_kv_cow()
            chain = shared + new_ids
            if self._prefix is not None and req.adapter_id is None:
                hit = bool(shared) or cow_src is not None
                if hit:
                    self.prefix_hits += 1
                self.prefix_reused_tokens += start
                self.kv_blocks_shared += len(shared)
                if shared:
                    self.metrics.on_kv_shared(len(shared))
                self.metrics.on_prefix(hit=hit, reused_tokens=start)
                if self.trace.enabled:
                    self.trace.instant(
                        "prefix_match", req.req_id,
                        {"hit": hit, "matched_tokens": start,
                         "shared_blocks": len(shared),
                         "cow": cow_src is not None})
                nodes = self._prefix.register(req.prompt, chain)
            self._bind_row(row, req, chain, start)
            self._row_prefill[row] = _PrefillState(req, start, nodes)
            if self.spec_enabled:
                draft_seeds.append((row, list(req.prompt)))
        if cow_pairs:
            n = len(cow_pairs)
            n_pad = _pow2(n)
            src = np.zeros((n_pad,), np.int32)   # pad = null block:
            dst = np.zeros((n_pad,), np.int32)   # 0 -> 0 is a no-op
            for i, (s, d) in enumerate(cow_pairs):
                src[i] = s
                dst[i] = d
            (self._pool_k, self._pool_v, self._scale_k,
             self._scale_v) = _cow_blocks(
                self._pool_k, self._pool_v, jnp.asarray(src),
                jnp.asarray(dst), shardings=self._shardings,
                scale_k=self._scale_k, scale_v=self._scale_v)
        self._seed_draft_rows(draft_seeds)

    def _seed_draft_rows(
            self, seeds: List[Tuple[int, List[int]]]) -> None:
        """Seed the DRAFT KV plane for freshly (re)bound rows: one
        full-sequence draft prefill per length bucket, piggybacked on
        the admission step (the draft is cheap enough that chunking it
        buys nothing — the target's chunked prefill still paces TTFT).
        Each seeded row also resets its draft-lag lane and acceptance
        history. A failed draft-chain alloc skips the seed: a cold
        draft only lowers acceptance, never changes emitted tokens."""
        if not self.spec_enabled or not seeds:
            return
        T = self.prefix_block
        groups: Dict[int, List[Tuple[int, List[int]]]] = {}
        for row, toks in seeds:
            self._d_lag[row] = 0
            self._d_tok[row] = 0
            self._spec_hist[row].clear()
            if not toks:
                continue
            if self.paged and not self._ensure_draft_blocks(
                    row, -(-len(toks) // T)):
                continue
            Cb = min(self._bucket(len(toks)), self.max_len)
            groups.setdefault(Cb, []).append((row, toks))
        for Cb in sorted(groups):
            grp = groups[Cb]
            n = len(grp)
            t0 = self.trace.now() if self.trace.enabled else 0.0
            n_pad = _pow2(n)
            prompts = np.zeros((n_pad, Cb), np.int32)
            rows = np.zeros((n_pad,), np.int32)
            starts = np.zeros((n_pad,), np.int32)
            last_idx = np.zeros((n_pad,), np.int32)
            for i, (row, toks) in enumerate(grp):
                prompts[i, :len(toks)] = toks
                rows[i] = row
                last_idx[i] = len(toks) - 1
            prompts[n:] = prompts[n - 1]    # filler: repeat last row —
            rows[n:] = rows[n - 1]          # duplicate scatters write
            last_idx[n:] = last_idx[n - 1]  # identical values
            if self.paged:
                bt_grp = self._bt_d[rows]
                (self._pool_dk, self._pool_dv, self._scale_dk,
                 self._scale_dv,
                 self._d_last_logits) = _prefill_rows_paged(
                    self.draft_params, jnp.asarray(prompts),
                    self._pool_dk, self._pool_dv, self._d_last_logits,
                    jnp.asarray(bt_grp), jnp.asarray(rows),
                    jnp.asarray(starts), jnp.asarray(last_idx),
                    self.draft_cfg, shardings=self._d_shardings,
                    scale_k=self._scale_dk, scale_v=self._scale_dv,
                    qspec=self.kv_quant_spec)
            else:
                self._d_cache, self._d_last_logits = _prefill_rows(
                    self.draft_params, jnp.asarray(prompts),
                    self._d_cache, self._d_last_logits,
                    jnp.asarray(rows), jnp.asarray(starts),
                    jnp.asarray(last_idx), self.draft_cfg,
                    shardings=self._d_shardings)
            self.spec_prefill_dispatches += 1
            if self.trace.enabled:
                self.trace.add(
                    "spec_draft_prefill", t0, self.trace.now() - t0,
                    lane="dispatch", args={"bucket": Cb, "rows": n})

    def _bind_row(self, row: int, req: _Request, chain: List[int],
                  start: int) -> None:
        """Point a slot row at its block chain and reset its decode
        state (budget/tok_idx overridden after the call by the swap-in
        path, which restores rather than restarts)."""
        self._row_blocks[row] = list(chain)
        self._bt[row, :] = 0
        self._bt[row, :len(chain)] = chain
        self.row_req[row] = req
        self.row_len[row] = start
        self.row_budget[row] = req.max_new_tokens
        self._tok_idx[row] = 0
        self._row_keys[row] = self._req_key(req)
        self._row_greedy[row] = (self.greedy if req.greedy is None
                                 else bool(req.greedy))
        self._row_slot[row] = self._pending_slots.pop(req.req_id, 0)
        self._row_admit_seq[row] = self._admit_seq
        self._admit_seq += 1

    def _requeue_front(self, req: _Request) -> None:
        pf = getattr(self.scheduler, "push_front", None)
        (pf if pf is not None else self.scheduler.push)(req)
        self.metrics.observe_queue_depth(len(self.scheduler))

    def _drop_pending_slot(self, req: _Request) -> None:
        """Return the adapter-slot reference the admission gate took
        for a request that is being requeued AFTER the gate (stale
        capacity estimate, swap-in failure). The request re-allocs —
        re-increfs — at the gate on its next admission round, so the
        pending reference must be dropped here or the slot leaks a
        count and can never evict."""
        slot = self._pending_slots.pop(req.req_id, 0)
        if slot and self.adapter_pool is not None:
            self.adapter_pool.decref(slot)

    def _pool_alloc(self, n: int) -> Optional[List[int]]:
        """n fresh blocks, evicting cold committed prefix blocks
        LRU-first when the free list runs short (the trie's eviction
        honors refcounts: a block any row still shares is never a
        victim). None when nothing more can be evicted — the caller
        preempts a row or defers the admission."""
        if n <= 0:
            return []
        ids = self.kv_pool.alloc(n)
        while ids is None:
            if self._prefix is None or not self._prefix.evict_one():
                return None
            ids = self.kv_pool.alloc(n)
        return ids

    def _ensure_decode_blocks(self, rows: List[int], H: int,
                              inflight: int) -> bool:
        """Grow each row's chain to cover ``row_len + inflight + H``
        slots (capped at the row's own completion point — prompt +
        budget — and at max_len). Growth appends to the host block
        table only; in-flight dispatches hold their own device
        snapshot. False when the pool (plus evictable prefix blocks)
        cannot cover it; rows already grown keep their blocks — no
        leak, the retry after preemption re-walks them as no-ops."""
        T = self.prefix_block
        for b in rows:
            req = self.row_req[b]
            lim = min(len(req.prompt) + req.max_new_tokens,
                      self.max_len)
            need_slots = min(int(self.row_len[b]) + inflight + H, lim)
            nb = -(-need_slots // T)
            have = len(self._row_blocks[b])
            if nb > have:
                got = self._pool_alloc(nb - have)
                if got is None:
                    return False
                self._row_blocks[b].extend(got)
                self._bt[b, have:have + len(got)] = got
            if self.spec_enabled and not self._ensure_draft_blocks(b, nb):
                return False
        return True

    def _ensure_draft_blocks(self, b: int, nb: int) -> bool:
        """Grow row ``b``'s DRAFT chain to ``nb`` blocks. The draft
        pool is sized so every slot can hold a full-length chain, so
        this cannot fail for live rows in steady state; False is
        returned defensively (the caller treats it like target-pool
        exhaustion). Draft coverage is a performance nicety, not a
        correctness requirement: an overshooting draft write past the
        chain lands in table entry 0 — the null block — whose garbage
        is never attended (``kv_valid_len`` masks it), and a garbage
        draft only lowers acceptance, never changes emitted tokens."""
        have = len(self._row_blocks_d[b])
        if nb <= have:
            return True
        got = self.kv_pool_d.alloc(nb - have)
        if got is None:
            return False
        self._row_blocks_d[b].extend(got)
        self._bt_d[b, have:have + len(got)] = got
        return True

    def _reserve_decode_blocks(self, decodable: List[int],
                               H: int) -> Tuple[List[int], int]:
        """Make the coming fused step safe: every decodable row must
        own the blocks its next H tokens will write. When the pool
        runs dry, PREEMPT victims (newest admission first — oldest
        rows are closest to finishing and have the most sunk compute)
        until the survivors fit. Only called with the pipeline ring
        empty: preemption reads host row state, which must be fully
        replayed."""
        decodable = list(decodable)
        while not self._ensure_decode_blocks(decodable, H, 0):
            if len(decodable) <= 1:
                if H > 1:
                    H = 1      # shrink the horizon before giving up
                    continue
                raise RuntimeError(
                    "paged KV pool exhausted with a single decodable "
                    "row at horizon 1 — kv_pool_bytes is too small "
                    "for this request shape (mid-prefill rows may be "
                    "holding the remainder)")
            victim = self._choose_victim(decodable)
            self._preempt_row(victim)
            decodable.remove(victim)
        return decodable, H

    def _choose_victim(self, rows: List[int]) -> int:
        """Which decodable row to preempt. Rows are offered to the
        scheduler's `choose_victim` hook oldest-admission-first; the
        default (and every built-in policy) takes the LAST-admitted
        row — LIFO preemption, the vLLM discipline that protects sunk
        compute."""
        ordered = sorted(rows, key=lambda b: self._row_admit_seq[b])
        hook = getattr(self.scheduler, "choose_victim", None)
        if hook is not None:
            return hook(ordered, self.row_req)
        return ordered[-1]

    def _preempt_row(self, row: int) -> None:
        """Evict a live decodable row mid-decode. swap mode gathers
        its blocks into fresh buffers, starts `copy_to_host_async`,
        and frees the blocks once the host copy lands — HBM is
        reclaimed, and re-admission scatters the bytes back into
        whatever physical blocks are free then (the block table makes
        them logically identical). recompute mode just drops the
        blocks and replays prompt + emitted tokens at re-admission.
        Either way the request returns to the FRONT of the queue with
        `resume` set: its deadline no longer applies (it was admitted
        once) and the prefix-affinity policy skips its probe."""
        assert not self._ring, "preemption needs a drained pipeline"
        req = self.row_req[row]
        ids = self._row_blocks[row]
        if self.preempt_mode == "swap":
            n = len(ids)
            nbp = _pow2(max(1, n))
            bids = np.zeros((nbp,), np.int32)
            bids[:n] = ids
            k, v, sk, sv = _swap_out_gather(
                self._pool_k, self._pool_v, jnp.asarray(bids),
                shardings=self._shardings, scale_k=self._scale_k,
                scale_v=self._scale_v)
            lg = self._last_logits[row]
            for x in (k, v, lg, sk, sv):
                if x is not None:
                    _host_async(x)
            k = _device_get(k)
            v = _device_get(v)
            lg = _device_get(lg)
            if sk is not None:
                sk = _device_get(sk)
                sv = _device_get(sv)
            self._swapped[req.req_id] = _SwapState(
                k, v, n, int(self.row_len[row]),
                int(self._tok_idx[row]), int(self.row_budget[row]), lg,
                sk=sk, sv=sv)
            nbytes = k.nbytes + v.nbytes + lg.nbytes
            if sk is not None:
                nbytes += sk.nbytes + sv.nbytes
            self.swap_outs += 1
            self.swap_out_bytes += nbytes
            self.metrics.on_swap_out(nbytes)
            swap_bytes = nbytes
        else:
            swap_bytes = 0
            self._swapped[req.req_id] = _SwapState(
                None, None, len(ids), int(self.row_len[row]),
                int(self._tok_idx[row]), int(self.row_budget[row]),
                None)
        self._release_row_blocks(row)
        if self._row_slot[row]:
            # The row's adapter reference dies with the row; the gate
            # re-allocs (and may have to re-prefetch) at re-admission.
            self.adapter_pool.decref(int(self._row_slot[row]))
            self._row_slot[row] = 0
        self.row_req[row] = None
        self.row_len[row] = 0
        self.row_budget[row] = 0
        self._tok_idx[row] = 0
        self.preemptions += 1
        self.metrics.on_preempt()
        if self.trace.enabled:
            self.trace.span_since_mark(
                "preempt_swap_out", req.req_id,
                {"mode": self.preempt_mode, "blocks": len(ids),
                 "bytes": swap_bytes})
        req.resume = True
        self._requeue_front(req)

    def _swap_in_row(self, row: int, req: _Request,
                     swap: _SwapState) -> bool:
        """Re-admit a preempted request. swap mode scatters its host
        K/V into a fresh chain and restores the row EXACTLY where it
        froze — decodable this very step, no prefill. recompute mode
        re-prefills prompt + emitted tokens (mathematically the same
        K/V) and continues the token stream at the saved tok_idx.
        False if the pool cannot cover it right now (caller requeues)."""
        T = self.prefix_block
        if swap.k is None:
            replay = list(req.prompt) + list(req.tokens)
            ids = self._pool_alloc(-(-len(replay) // T))
            if ids is None:
                return False
            self._bind_row(row, req, ids, 0)
            self.row_budget[row] = req.max_new_tokens - len(req.tokens)
            self._tok_idx[row] = len(req.tokens)
            # No trie registration: emitted tokens are not a shared
            # prompt, and the prompt's own blocks were registered (and
            # possibly still live) on first admission.
            self._row_prefill[row] = _PrefillState(req, 0, [],
                                                   prompt=replay)
            self.swap_ins += 1
            if self.trace.enabled:
                self.trace.span_since_mark(
                    "swap_in", req.req_id,
                    {"mode": "recompute",
                     "replay_tokens": len(replay)})
            return True
        ids = self._pool_alloc(swap.n_blocks)
        if ids is None:
            return False
        nbp = _pow2(max(1, swap.n_blocks))
        bids = np.zeros((nbp,), np.int32)      # pad = null block: the
        bids[:swap.n_blocks] = ids             # gather's padding lands
        #                                        back where it came from
        (self._pool_k, self._pool_v, self._scale_k,
         self._scale_v) = _swap_in_scatter(
            self._pool_k, self._pool_v, jnp.asarray(swap.k),
            jnp.asarray(swap.v), jnp.asarray(bids),
            shardings=self._shardings, scale_k=self._scale_k,
            scale_v=self._scale_v,
            host_sk=None if swap.sk is None else jnp.asarray(swap.sk),
            host_sv=None if swap.sv is None else jnp.asarray(swap.sv))
        self._last_logits = self._last_logits.at[row].set(
            jnp.asarray(swap.logits))
        if self._shardings is not None:
            self._last_logits = jax.device_put(self._last_logits,
                                               self._shardings.logits)
        self._bind_row(row, req, ids, swap.row_len)
        self.row_budget[row] = swap.budget
        self._tok_idx[row] = swap.tok_idx
        nbytes = swap.k.nbytes + swap.v.nbytes + swap.logits.nbytes
        if swap.sk is not None:
            nbytes += swap.sk.nbytes + swap.sv.nbytes
        self.swap_ins += 1
        self.swap_in_bytes += nbytes
        self.metrics.on_swap_in(nbytes)
        if self.trace.enabled:
            self.trace.span_since_mark(
                "swap_in", req.req_id,
                {"mode": "swap", "bytes": nbytes,
                 "blocks": swap.n_blocks})
        return True

    # -- disaggregated prefill/decode handoff ------------------------------

    def handoff_ready(self) -> List[int]:
        """Request ids parked post-prefill on a prefill-only engine,
        oldest first — each is waiting for the fleet to
        `export_request` it to a decode-class replica. Always empty on
        a colocated engine."""
        return list(self._handoff_ready)

    def export_request(self, req_id: int) -> dict:
        """Extract a request whose prefill frontier has completed —
        the engine half of the disaggregated prefill→decode handoff.

        The request must be bound to a live row that is NOT
        mid-chunked-prefill, with the async pipeline empty (on a
        prefill-only engine the ring is always empty: it never
        dispatches a decode program). A paged engine gathers the row's
        KV blocks to host via the preempt-and-swap `_swap_out_gather`
        path — quantized bytes plus their scale rows move verbatim —
        together with the row's last-prompt-token logits; a dense
        engine exports no bytes and the importer re-prefills
        (recompute handoff). Either way the row's blocks are decref'd,
        its adapter pin released, and the request leaves this engine
        entirely (`results` included): it now lives wherever
        `import_request` lands it.

        Token identity holds because a completed prefill IS a
        preemption at tok_idx=0: the first decode token is sampled
        from the carried logits with `step_rng_key(rng, 0)`, exactly
        what this engine would have done next."""
        row = None
        for b in range(self.B):
            r = self.row_req[b]
            if r is not None and r.req_id == req_id:
                row = b
                break
        if row is None:
            raise RuntimeError(
                f"export_request: request {req_id} is not bound to a "
                "row (still queued, already finished, or unknown)")
        if row in self._row_prefill:
            raise RuntimeError(
                f"export_request: request {req_id} is still "
                "mid-chunked-prefill; export only after its frontier "
                "completes (see handoff_ready())")
        if self._ring:
            raise RuntimeError(
                "export_request needs a drained pipeline (in-flight "
                "fused decode blocks still reference row state); "
                "step() flushes before admissions — export between "
                "steps")
        # Drained-ring dominator for the row-state writes below (the
        # raise above enforces it with a typed error; flush-order
        # wants the guard in assert form).
        assert not self._ring
        req = self.row_req[row]
        kv = None
        nbytes = 0
        if self.paged:
            ids = self._row_blocks[row]
            n = len(ids)
            nbp = _pow2(max(1, n))
            bids = np.zeros((nbp,), np.int32)
            bids[:n] = ids
            k, v, sk, sv = _swap_out_gather(
                self._pool_k, self._pool_v, jnp.asarray(bids),
                shardings=self._shardings, scale_k=self._scale_k,
                scale_v=self._scale_v)
            lg = self._last_logits[row]
            for x in (k, v, lg, sk, sv):
                if x is not None:
                    _host_async(x)
            k = _device_get(k)
            v = _device_get(v)
            lg = _device_get(lg)
            if sk is not None:
                sk = _device_get(sk)
                sv = _device_get(sv)
            nbytes = k.nbytes + v.nbytes + lg.nbytes
            if sk is not None:
                nbytes += sk.nbytes + sv.nbytes
            kv = {"k": k, "v": v, "sk": sk, "sv": sv,
                  "n_blocks": n,
                  "row_len": int(self.row_len[row]),
                  "tok_idx": int(self._tok_idx[row]),
                  "budget": int(self.row_budget[row]),
                  "logits": lg,
                  "block_tokens": self.prefix_block,
                  "quant": self.kv_quant,
                  "pool_shape": tuple(self._pool_k.shape[i]
                                      for i in (0, 3, 4))}
            self._release_row_blocks(row)
        if self._row_slot[row]:
            # The exporting row's adapter pin dies here; the importing
            # engine's admission gate re-pins (and prefetches a cold
            # adapter) on its own pool.
            self.adapter_pool.decref(int(self._row_slot[row]))
            self._row_slot[row] = 0
        handoff = {"req_id": req.req_id,
                   "prompt": list(req.prompt),
                   "max_new_tokens": req.max_new_tokens,
                   "priority": req.priority,
                   "greedy": req.greedy,
                   "rng": req.rng,
                   "adapter_id": req.adapter_id,
                   "tokens": list(req.tokens),
                   "kv": kv}
        self.row_req[row] = None
        self.row_len[row] = 0
        self.row_budget[row] = 0
        self._tok_idx[row] = 0
        self.results.pop(req.req_id, None)
        if req.req_id in self._handoff_ready_set:
            self._handoff_ready_set.discard(req.req_id)
            self._handoff_ready.remove(req.req_id)
        self.handoffs_out += 1
        self.handoff_out_bytes += nbytes
        self.metrics.on_handoff_out(req.req_id, nbytes)
        if self.trace.enabled:
            self.trace.span_since_mark(
                "handoff_export", req.req_id,
                {"bytes": nbytes,
                 "blocks": 0 if kv is None else kv["n_blocks"],
                 "tokens": len(req.tokens)})
        return handoff

    def import_request(self, handoff: dict) -> int:
        """Admit a request exported from another engine — the decode
        half of the handoff. Re-submits it under THIS engine's queue
        discipline (same rng key, greedy mode, priority, adapter), and
        when the exported KV payload is compatible with this engine's
        pool (paged, same block size, same quantization, same KV
        geometry) pre-seeds the paged swap ledger with it: admission
        then scatters the bytes back via `_swap_in_scatter` and the
        row is decodable immediately — no re-prefill. Incompatible or
        dense payloads fall back to recompute (prompt + any emitted
        tokens replay), which is slower but bit-identical. Returns the
        request id on this engine."""
        kv = handoff.get("kv")
        toks = handoff.get("tokens") or []
        rng = handoff.get("rng")
        rid = self.submit(
            handoff["prompt"], handoff["max_new_tokens"],
            priority=handoff.get("priority", 0),
            rng=rng,
            greedy=handoff.get("greedy"),
            resume_tokens=toks or None,
            adapter_id=handoff.get("adapter_id"))
        req = self.results[rid]
        req.handoff = True
        compatible = (
            kv is not None and self.paged
            and kv["block_tokens"] == self.prefix_block
            and kv["quant"] == self.kv_quant
            and kv["pool_shape"] == tuple(self._pool_k.shape[i]
                                          for i in (0, 3, 4)))
        if compatible:
            # Pre-seed the swap ledger with the exported bytes: the
            # recompute entry submit() may have planted (resume path)
            # is replaced by the byte-carrying state, and
            # `_admit_rows_paged` scatters it back like any preempted
            # row returning home.
            self._swapped[rid] = _SwapState(
                kv["k"], kv["v"], kv["n_blocks"], kv["row_len"],
                kv["tok_idx"], kv["budget"], kv["logits"],
                sk=kv["sk"], sv=kv["sv"])
            req.resume = True
            nbytes = kv["k"].nbytes + kv["v"].nbytes \
                + kv["logits"].nbytes
            if kv["sk"] is not None:
                nbytes += kv["sk"].nbytes + kv["sv"].nbytes
        else:
            nbytes = 0
        self.handoffs_in += 1
        self.handoff_in_bytes += nbytes
        self.metrics.on_handoff_in(nbytes)
        if self.trace.enabled:
            self.trace.span_since_mark(
                "handoff_import", rid,
                {"bytes": nbytes, "mode":
                 "swap" if compatible else "recompute"})
        return rid

    def _release_row_blocks(self, row: int) -> None:
        """Drop the row's reference on its chain (trie-shared blocks
        survive via the trie's own reference) and point the table back
        at the null block."""
        ids = self._row_blocks[row]
        if ids:
            self.kv_pool.decref(ids)
        self._row_blocks[row] = []
        self._bt[row, :] = 0
        if self.spec_enabled:
            # Draft chains are private (never trie-shared), so decref
            # frees them outright; the plane is re-seeded from scratch
            # at (re-)admission.
            d_ids = self._row_blocks_d[row]
            if d_ids:
                self.kv_pool_d.decref(d_ids)
            self._row_blocks_d[row] = []
            self._bt_d[row, :] = 0

    def _fits_now(self, req: _Request) -> bool:
        """Admission gate: would this request's NEW blocks fit the
        pool right now, counting evictable cold trie blocks as
        reclaimable? Pure host probe (peek=True) — deferring an
        admission must not perturb LRU recency. An optimistic stale
        answer is safe: `_admit_rows_paged` re-checks and requeues."""
        T = self.prefix_block
        swap = self._swapped.get(req.req_id)
        if swap is not None:
            if swap.k is not None:
                need = swap.n_blocks
            else:
                need = -(-(len(req.prompt) + len(req.tokens)) // T)
        else:
            need = -(-len(req.prompt) // T)
            # Adapter rows take no prefix credit: they bypass the trie.
            if self._prefix is not None and req.adapter_id is None:
                ids, _ = self._prefix.match(req.prompt, peek=True,
                                            allow_full=True)
                if ids and len(ids) * T == len(req.prompt):
                    need -= len(ids) - 1   # tail block is CoW'd
                else:
                    need -= len(ids)
        return need <= self.kv_free_blocks()

    def _commit_covered(self, row: int, st: _PrefillState) -> None:
        """Paged twin of `_flush_copy_out`: the row's prefill writes
        the trie's blocks DIRECTLY (they ARE the row's chain), so a
        pending block the frontier has covered just commits — zero
        copy dispatches, which is the whole point."""
        T = self.prefix_block
        while st.nodes and (st.nodes[0][0] + 1) * T <= st.pos:
            _, node = st.nodes.pop(0)
            self._prefix.commit(node)

    def _advance_prefills(self) -> None:
        """Advance every mid-prefill row by one chunk (the whole
        remaining suffix when `prefill_chunk` is None), same-bucket
        chunks batched into ONE `_prefill_rows` program. A row whose
        frontier reaches its prompt length leaves `_row_prefill` and is
        decodable THIS step (its last chunk scattered the true
        last-prompt logits). Completed prefix blocks are flushed to the
        pool and committed as the frontier passes them."""
        if not self._row_prefill:
            return
        groups: Dict[int, List[Tuple[int, _PrefillState, int]]] = {}
        for row, st in self._row_prefill.items():
            C = len(st.prompt) - st.pos
            if self.prefill_chunk is not None:
                C = min(C, self.prefill_chunk)
            # Bucket the chunk, capped so the scatter never runs past
            # max_len (starts differ per row; the cap is per-row).
            Cb = min(self._bucket(C), self.max_len - st.pos)
            groups.setdefault(Cb, []).append((row, st, C))
        for Cb in sorted(groups):
            grp = groups[Cb]
            n = len(grp)
            t0 = self.trace.now() if self.trace.enabled else 0.0
            n_pad = _pow2(n)
            prompts = np.zeros((n_pad, Cb), np.int32)
            rows = np.zeros((n_pad,), np.int32)
            starts = np.zeros((n_pad,), np.int32)
            last_idx = np.zeros((n_pad,), np.int32)
            real = 0
            for i, (row, st, C) in enumerate(grp):
                prompts[i, :C] = st.prompt[st.pos:st.pos + C]
                rows[i] = row
                starts[i] = st.pos
                last_idx[i] = C - 1
                real += C
            prompts[n:] = prompts[n - 1]    # filler: repeat last row —
            rows[n:] = rows[n - 1]          # duplicate scatters write
            starts[n:] = starts[n - 1]      # identical values
            last_idx[n:] = last_idx[n - 1]
            # Per-chunk adapter-slot lane gathered from the engine's
            # [B] lane (filler rows repeat the last real row, so the
            # gather stays well-defined).
            if self.adapter_pool is not None:
                adapters = self.adapter_pool.stacks
                row_slot = jnp.asarray(self._row_slot[rows])
            else:
                adapters = row_slot = None
            if self.paged:
                bt_grp = self._bt[rows]            # [n_pad, MB]
                (self._pool_k, self._pool_v, self._scale_k,
                 self._scale_v,
                 self._last_logits) = _prefill_rows_paged(
                    self.params, jnp.asarray(prompts), self._pool_k,
                    self._pool_v, self._last_logits,
                    jnp.asarray(bt_grp), jnp.asarray(rows),
                    jnp.asarray(starts), jnp.asarray(last_idx),
                    self.cfg, shardings=self._shardings,
                    adapters=adapters, row_slot=row_slot,
                    scale_k=self._scale_k, scale_v=self._scale_v,
                    qspec=self.kv_quant_spec)
            else:
                self.cache, self._last_logits = _prefill_rows(
                    self.params, jnp.asarray(prompts), self.cache,
                    self._last_logits, jnp.asarray(rows),
                    jnp.asarray(starts), jnp.asarray(last_idx),
                    self.cfg, shardings=self._shardings,
                    adapters=adapters, row_slot=row_slot)
            self.prefill_dispatches += 1
            padded = n_pad * Cb - real
            self.prefill_real_tokens += real
            self.prefill_padded_tokens += padded
            self.metrics.on_prefill_batch(real, padded)
            if self.trace.enabled:
                self.trace.add("prefill_dispatch", t0,
                               self.trace.now() - t0, lane="dispatch",
                               args={"bucket": Cb, "rows": n,
                                     "real": real, "padded": padded})
        done_rows = []
        for grp in groups.values():
            for row, st, C in grp:
                st.pos += C
                self.row_len[row] = st.pos
                if self.trace.enabled:
                    self.trace.span_since_mark(
                        "prefill_chunk", st.req.req_id,
                        {"pos": st.pos, "tokens": C,
                         "prompt_tokens": len(st.prompt)})
                if self._prefix is not None:
                    if self.paged:
                        self._commit_covered(row, st)
                    else:
                        self._flush_copy_out(row, st)
                if st.pos >= len(st.prompt):
                    done_rows.append(row)
        for row in done_rows:
            del self._row_prefill[row]

    def _flush_copy_out(self, row: int, st: _PrefillState) -> None:
        """Copy every pending prefix block the row's frontier now
        covers out to the pool (one program per consecutive run,
        chain length padded to a power of two with the scratch block)
        and COMMIT it — from the next admission round on, `match` will
        hand the block to warm requests."""
        T = self.prefix_block
        while st.nodes and (st.nodes[0][0] + 1) * T <= st.pos:
            run = [st.nodes.pop(0)]
            while st.nodes and st.nodes[0][0] == run[-1][0] + 1 and \
                    (st.nodes[0][0] + 1) * T <= st.pos:
                run.append(st.nodes.pop(0))
            nbp = _pow2(len(run))
            bids = np.zeros((nbp,), np.int32)   # pad = scratch block 0
            for i, (_, node) in enumerate(run):
                bids[i] = node.block_id
            self._pool_k, self._pool_v = _prefix_copy_out(
                self.cache["k"], self.cache["v"], self._pool_k,
                self._pool_v, row,
                run[0][0] * T, jnp.asarray(bids), nbp, T,  # graftlint: disable=jit-hygiene -- nbp is power-of-two bucketed (_pow2), distinct static values are log-bounded
                shardings=self._shardings)
            self.prefix_copy_dispatches += 1
            for _, node in run:
                self._prefix.commit(node)

    def _emit_block(self, block: np.ndarray, entry: _InflightStep,
                    emitted: Dict[int, List[int]]
                    ) -> Tuple[int, int, int]:
        """VECTORIZED host replay of one [H, B] token block: mirrors
        `_decode_multi`'s per-iteration transition without touching the
        device, but in one numpy slice + one arithmetic pass per ROW
        instead of a Python iteration per token.

        The device masks every emit after a row freezes to -1, and
        `active` only ever transitions True->False inside a block, so
        each column is a prefix of real tokens followed by -1s: the
        count of != -1 entries IS the number of emitted tokens, and
        replaying the transition once with that count is bit-identical
        to replaying it token by token —
            budget   -= count;  tok_idx += count
            done      = budget <= 0
                        | row_len + count >= max_len   (room check at
                          the LAST emitted token's pre-advance row_len)
                        | last_tok == eos
            row_len  += count if continuing (a finishing row's state is
                        reset on retirement, so its advance is moot)
        Emission order is unchanged from the scalar loop: every live
        row emits from iteration 0, so `emitted` insertion order — and
        therefore retire-on-eos ordering — is identical.

        Rows found already retired (`row_req is None`) only occur in
        run-ahead blocks dispatched before the host replayed the
        retiring block; their columns are all-masked on device and
        accounted as `pipeline_overrun_tokens`.

        Returns `(rounds, proposed, accepted)` speculative accounting
        for this block — all zero for a plain decode block — so
        `_drain_one` can feed SpecMetrics and the `spec_verify` span
        without rescanning the columns. For a spec block each live
        greedy row is one ROUND: it proposed `w_row[b]` draft tokens
        and had `count - 1` of them accepted (the +1 is the verify
        pass's own token, which is free). The host `_d_lag/_d_tok`
        lanes mirror the device's draft-lag carry: a fully-accepted
        round leaves the last accepted token un-fed to the DRAFT
        (lag 1), anything else leaves the draft exactly at the
        frontier (lag 0)."""
        tr = self.trace
        sp_rounds = sp_prop = sp_acc = 0
        for b in entry.rows:
            req = self.row_req[b]
            if req is None:
                if entry.run_ahead:
                    self.pipeline_overrun_tokens += entry.H
                    self.metrics.on_pipeline_overrun(entry.H)
                continue
            col = block[:, b]
            count = int((col != -1).sum())
            if count == 0:
                continue
            toks = col[:count].tolist()
            req.tokens.extend(toks)
            emitted.setdefault(req.req_id, []).extend(toks)
            self.metrics.on_tokens(req.req_id, count)
            if tr.enabled:
                tr.span_since_mark(
                    "decode_block", req.req_id,
                    {"tokens": count, "horizon": entry.H,
                     "batch": len(entry.rows)})
            if entry.spec and self._row_greedy[b]:
                proposed_b = int(entry.w_row[b])
                accepted_b = count - 1
                sp_rounds += 1
                sp_prop += proposed_b
                sp_acc += accepted_b
                self.spec_rounds += 1
                self.spec_proposed += proposed_b
                self.spec_accepted += accepted_b
                self.spec_wasted += proposed_b - accepted_b
                self._spec_hist[b].append((proposed_b, accepted_b))
            self.row_budget[b] -= count
            self._tok_idx[b] += count
            out_of_room = self.row_len[b] + count >= self.max_len
            if (self.row_budget[b] <= 0 or out_of_room
                    or (self.eos_id is not None
                        and toks[-1] == self.eos_id)):
                req.done = True
                self.finished.add(req.req_id)
                self.metrics.on_finish(req.req_id)
                if tr.enabled:
                    tr.finish(req.req_id,
                              {"tokens": len(req.tokens)})
                self.row_req[b] = None
                self.row_len[b] = 0      # slot free for the next prefill
                self.row_budget[b] = 0
                self._tok_idx[b] = 0
                # Lane reset: the slot's next tenant starts from the
                # engine default, so an override-free engine keeps its
                # all-greedy fast path (one static compile).
                self._row_greedy[b] = bool(self.greedy)
                if self.spec_enabled:
                    self._d_lag[b] = 0
                    self._d_tok[b] = 0
                if self.paged:
                    # Blocks the trie shares stay resident (its ref);
                    # everything else returns to the pool NOW — this
                    # is what lets admission capacity track finished
                    # tokens instead of max-live slots.
                    self._release_row_blocks(b)
                if self._row_slot[b]:
                    # Retirement drops the row's adapter pin; a
                    # refcount-0 slot stays RESIDENT (LRU) so the next
                    # same-adapter request is a hit, it just becomes
                    # evictable.
                    self.adapter_pool.decref(int(self._row_slot[b]))
                    self._row_slot[b] = 0
            else:
                self.row_len[b] += count  # the fed tokens took their slots
                if entry.spec:
                    full = count == entry.H
                    self._d_lag[b] = 1 if full else 0
                    self._d_tok[b] = int(toks[-1]) if full else 0
        return sp_rounds, sp_prop, sp_acc
