"""Continuous-batching decode engine, TPU-first.

The reference has no serving engine for LLMs (Serve hosts arbitrary
torch callables; continuous batching lives outside it in vLLM-class
engines). Serving an LM is this framework's flagship deployment, so
slot-based continuous batching is first-class here, built the XLA way:

- ONE fused decode program for the whole engine: B fixed decode slots
  advance together, every row at its OWN cache offset (per-row scatter
  writes + per-row masks — no recompilation as requests come and go,
  no left-padding). H decode iterations run inside a single program
  (`_decode_multi`: lax.scan + on-device sampling + per-row eos/budget
  freezing), so the host pays ONE dispatch and ONE device->host
  transfer per H tokens instead of a blocking sample per token — the
  vLLM/Orca lesson that the decode inner loop must be free of host
  synchronization, applied the XLA way.
- Admission is a per-length-bucket BATCHED prefill program
  (`_prefill_rows`): all same-bucket admissions of a step write their
  prompts' K/V into freed slots' cache rows in one dispatch while the
  other rows' state rides along untouched (donated buffers, in-place
  in HBM). First tokens are sampled on device by the fused decode from
  the device-resident `last_logits` — admission costs zero host
  round-trips.
- A finished row's slot is reused immediately: its stale K/V need no
  clearing because every mask is `slot < row_len`, and the next
  occupant's prefill overwrites from slot 0. Rows finishing
  mid-horizon freeze on device (row_len stops, emits masked to -1)
  and are retired by the host replay of the token block.
- The decode loop is ASYNC double-buffered (`pipeline_depth`, default
  2): during pure-decode stretches (queue empty, nothing mid-prefill)
  the engine keeps a bounded ring of fused steps in flight, chaining
  each run-ahead dispatch off the previous one's device-carried row
  state and issuing `copy_to_host_async` on every token block, so the
  host replays step N's tokens while the device computes step N+1.
  The ring is flushed before any admission/prefill/prefix copy (those
  mutate the donated cache from the host side), and run-ahead
  iterations on rows that finished mid-flight are masked on device and
  accounted as `pipeline_overrun_tokens`.

Consistency contract (tested): greedy engine output for every request
is token-identical to that request's solo `generate` run, regardless of
admission order, slot reuse, or which other requests share the batch —
and regardless of the SCHEDULER POLICY: scheduling (models/scheduler.py
— FIFO, priority classes, bounded-queue backpressure, per-step prefill
budget) only reorders admissions, never what an admitted row computes.

Telemetry (models/engine_metrics.py) timestamps every request through
queued → admitted → decoding → finished and exports queue-wait / TTFT /
TPOT / occupancy through the util.metrics Prometheus plane; `stats()`
snapshots it for the Serve path (serve.metrics.report_engine_stats).

Cites: reference Serve's dynamic batching seam
(python/ray/serve/batching.py:1) coalesces CALLS; this engine coalesces
DECODE STEPS — requests join and leave a running batch mid-flight.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models.engine_metrics import EngineMetrics, NullEngineMetrics
from ray_tpu.models.generate import (_check_sampling_knobs,
                                     _layer_body, forward_cached_rows,
                                     init_cache, sample_rows)
from ray_tpu.models.llama import (LlamaConfig, _rmsnorm,
                                  llama_param_specs)
from ray_tpu.models.prefix_cache import PrefixCacheIndex, block_bytes
from ray_tpu.models.scheduler import (EngineDraining, EngineOverloaded,
                                      SchedulerPolicy, make_policy)
from ray_tpu.parallel.mesh import create_mesh
from ray_tpu.parallel.sharding import (DEFAULT_RULES, named_sharding,
                                       prune_rules_for_mesh,
                                       shard_pytree)

Params = Dict[str, Any]


def _pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << (n - 1).bit_length()


def _key_data(key) -> np.ndarray:
    """Raw uint32[2] bits of a PRNG key (legacy array or typed key)."""
    try:
        return np.asarray(key, np.uint32).reshape(2)
    except (TypeError, ValueError):
        return np.asarray(jax.random.key_data(key),
                          np.uint32).reshape(2)


def _device_get(x) -> np.ndarray:
    """The engine's ONLY device->host transfer. Every blocking fetch in
    the serving loop funnels through here so (a) the engine can count
    host syncs for telemetry (`host_syncs_per_token`) and (b) tests can
    wrap it to GATE the transfer budget — the fused decode path must
    stay at one pull per horizon, and an accidental per-token sync
    reintroduction fails tests/test_engine_horizon.py. Under the async
    pipeline the pull is usually a no-op wait: the block's
    `copy_to_host_async` was issued at dispatch, one or more fused
    steps earlier (tests/test_engine_pipeline.py gates that the next
    dispatch is issued BEFORE this fetch)."""
    return np.asarray(x)


@dataclasses.dataclass(frozen=True)
class _EngineShardings:
    """NamedShardings the tensor-parallel engine threads through its
    compiled programs as a STATIC jit argument (NamedSharding is
    hashable, so each mesh compiles its own program set and the
    unsharded engine — shardings=None — compiles exactly what it did
    before).

    ``cache``  [L, B, max_len, KV, D] — KV-head axis over "tp" (when
               the model's n_kv_heads divides tp; replicated otherwise)
    ``logits`` [B, vocab]             — vocab over "tp"
    ``pool``   [L, NB, T, KV, D]      — prefix pool, KV axis like the
               cache so copy-in/out gathers stay chip-local
    """

    cache: NamedSharding
    logits: NamedSharding
    pool: NamedSharding

    @property
    def replicated(self) -> NamedSharding:
        """Fully-replicated sharding on the same mesh — the [H, B]
        token block is pinned to it so the single device->host transfer
        stays whole on every chip (no cross-chip fetch at drain)."""
        return NamedSharding(self.cache.mesh, P())


# ---------------------------------------------------------------------------
# Compiled programs
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("cfg", "shardings"),
                   donate_argnames=("cache", "last_logits"))
def _prefill_rows(params: Params, prompts: jax.Array, cache,
                  last_logits, rows: jax.Array, starts: jax.Array,
                  last_idx: jax.Array, cfg: LlamaConfig,
                  shardings: Optional[_EngineShardings] = None):
    """Batched admission/continuation prefill: write N same-bucket
    chunks' [N, Cb] K/V into N slots in ONE program — each row at its
    OWN cache offset ``starts[n]`` (0 for a cold admission; the cached
    prefix length for a warm one; the chunk frontier for a chunked
    continuation) — and scatter each row's last-real-token logits into
    the engine's device-resident `last_logits` [B, vocab]. Returns
    (cache, last_logits) — no logits ever cross to the host; the fused
    decode program samples the first token on device, so an admission
    costs zero host round-trips.

    Cb may exceed a chunk's true length (length-bucketed serving):
    trailing filler tokens' K/V land at slots >= the true frontier,
    which every later mask excludes (`slot <= q_slot` caps decode
    attention at the written frontier and the next chunk/decode write
    overwrites them) — only the logits at `last_idx` (true chunk length
    - 1) are read out, and only the FINAL chunk's scatter survives in
    `last_logits` (earlier chunks' scatters are overwritten before the
    row ever decodes). `rows` may contain duplicates (power-of-two
    group padding repeats the last admission verbatim): duplicate
    scatters write identical values, so the result is deterministic."""
    row_cache = {"k": cache["k"][:, rows], "v": cache["v"][:, rows]}
    logits, row_cache = forward_cached_rows(params, prompts, row_cache,
                                            starts, cfg)
    cache = {
        "k": cache["k"].at[:, rows].set(row_cache["k"]),
        "v": cache["v"].at[:, rows].set(row_cache["v"]),
    }
    n = prompts.shape[0]
    last = logits[jnp.arange(n), last_idx]              # [N, vocab]
    out_logits = last_logits.at[rows].set(last)
    if shardings is not None:
        # Donated buffers must leave with the sharding they arrived in.
        cache = jax.lax.with_sharding_constraint(cache, shardings.cache)
        out_logits = jax.lax.with_sharding_constraint(
            out_logits, shardings.logits)
    return cache, out_logits


@functools.partial(jax.jit,
                   static_argnames=("n_blocks", "block_tokens",
                                    "shardings"),
                   donate_argnames=("cache",))
def _prefix_copy_in(cache, pool_k, pool_v, block_ids: jax.Array,
                    rows: jax.Array, n_blocks: int, block_tokens: int,
                    shardings: Optional[_EngineShardings] = None):
    """Copy cached prefix blocks into engine slot rows: ONE gather
    program per step moves every warm admission's shared K/V from the
    device-resident pool into its slot — zero host round-trips, the
    same choke-point discipline as `_prefill_rows`.

    pool_k/v: [L, NB, T, KV, D]; block_ids [N, n_blocks]; rows [N].
    Row n's blocks land contiguously at slots [0, n_blocks*T). Both N
    and n_blocks are power-of-two padded by the caller (repeat the last
    row / the last block id), so a handful of compiles cover all chain
    lengths: duplicate row scatters write identical values, and padded
    trailing blocks write garbage BEYOND the row's matched prefix —
    slots the suffix prefill and decode overwrite before any mask ever
    admits them."""
    span = n_blocks * block_tokens
    blk_k = pool_k[:, block_ids]          # [L, N, nb, T, KV, D]
    blk_v = pool_v[:, block_ids]
    if shardings is not None:
        # Sharded gather: pool and cache carry the same KV-head
        # sharding, so pin the gathered blocks to it too — each chip
        # gathers ONLY its heads' slice of the pool and scatters it
        # into its own cache shard; no cross-chip block traffic.
        sp = shardings.pool.spec          # (l, nb, t, kv, d)
        blk_spec = NamedSharding(
            shardings.pool.mesh, P(sp[0], None, sp[1], sp[2], sp[3],
                                   sp[4]))
        blk_k = jax.lax.with_sharding_constraint(blk_k, blk_spec)
        blk_v = jax.lax.with_sharding_constraint(blk_v, blk_spec)
    L, N = blk_k.shape[:2]
    k = blk_k.reshape(L, N, span, *blk_k.shape[4:])
    v = blk_v.reshape(L, N, span, *blk_v.shape[4:])
    out = {
        "k": cache["k"].at[:, rows, :span].set(k.astype(cache["k"].dtype)),
        "v": cache["v"].at[:, rows, :span].set(v.astype(cache["v"].dtype)),
    }
    if shardings is not None:
        out = jax.lax.with_sharding_constraint(out, shardings.cache)
    return out


@functools.partial(jax.jit,
                   static_argnames=("n_blocks", "block_tokens",
                                    "shardings"),
                   donate_argnames=("pool_k", "pool_v"))
def _prefix_copy_out(cache_k, cache_v, pool_k, pool_v, row,
                     start_slot, block_ids: jax.Array, n_blocks: int,
                     block_tokens: int,
                     shardings: Optional[_EngineShardings] = None):
    """Insert a freshly prefilled prefix into the pool: slice
    [start_slot, start_slot + n_blocks*T) out of one slot row and
    scatter it into the pool at ``block_ids`` — one program per novel
    prefix segment, dispatched right after the chunk that produced it
    (dispatch order guarantees any copy-in already in flight still
    reads the blocks' OLD content). n_blocks is power-of-two padded
    with the reserved scratch block id 0: padding writes (clamped
    slices of whatever follows the segment) land in the scratch block,
    which the index never hands out."""
    span = n_blocks * block_tokens
    max_len = cache_k.shape[2]
    slots = jnp.minimum(start_slot + jnp.arange(span), max_len - 1)
    row_k = jnp.take(cache_k, row, axis=1)      # [L, max_len, KV, D]
    row_v = jnp.take(cache_v, row, axis=1)
    seg_k = jnp.take(row_k, slots, axis=1)      # [L, span, KV, D]
    seg_v = jnp.take(row_v, slots, axis=1)
    L = seg_k.shape[0]
    seg_k = seg_k.reshape(L, n_blocks, block_tokens, *seg_k.shape[2:])
    seg_v = seg_v.reshape(L, n_blocks, block_tokens, *seg_v.shape[2:])
    pool_k = pool_k.at[:, block_ids].set(seg_k.astype(pool_k.dtype))
    pool_v = pool_v.at[:, block_ids].set(seg_v.astype(pool_v.dtype))
    if shardings is not None:
        # Sharded scatter, the mirror of copy-in's gather: cache row
        # and pool share the KV-head sharding, so each chip writes its
        # own heads' slice of the block. Donated pools keep layout.
        pool_k = jax.lax.with_sharding_constraint(pool_k, shardings.pool)
        pool_v = jax.lax.with_sharding_constraint(pool_v, shardings.pool)
    return pool_k, pool_v


def _decode_layer_rows(h, layer, k_cache, v_cache, write_slots,
                       cfg: LlamaConfig):
    """One decoder layer, one new token per row, each row writing its
    K/V at its own slot (scatter) and attending its own prefix.

    h: [B, 1, d]; caches [B, max_len, KV, D]; write_slots: [B].

    All the per-layer math lives in generate.py's `_layer_body` (one
    source of truth for both decode paths); only the cache-write
    strategy differs — per-row scatter here vs the contiguous chunk
    slice in `_cached_layer`. The per-prefix causal mask falls out of
    `_cached_attention` with q_slots = each row's own write slot and
    kv_valid_len = max_len (dead slots beyond a row's frontier are
    already excluded by `slot <= write_slot`)."""
    B = h.shape[0]
    bidx = jnp.arange(B)

    def write_kv(k_cache, v_cache, k, v):
        k_cache = k_cache.at[bidx, write_slots].set(
            k[:, 0].astype(k_cache.dtype))
        v_cache = v_cache.at[bidx, write_slots].set(
            v[:, 0].astype(v_cache.dtype))
        return k_cache, v_cache

    return _layer_body(h, layer, k_cache, v_cache,
                       write_slots[:, None], write_kv,
                       write_slots[:, None], k_cache.shape[1], cfg)


def _decode_core(params: Params, toks: jax.Array, cache, row_len,
                 cfg: LlamaConfig):
    """One decode step for ALL slots: row b's token `toks[b]` is
    written at slot `row_len[b]` and attends slots [0, row_len[b]].
    Dead/frozen rows compute discarded garbage at their frontier slot —
    it lands one past their real tokens (or at slot 0 for empty rows)
    and is overwritten by the next occupant's prefill, with every mask
    excluding it meanwhile. Returns (next-token logits [B, vocab] f32,
    cache). Plain function so `_decode_multi`'s scan can inline it."""
    write_slots = row_len                                   # [B]
    h = params["tok_embed"].astype(cfg.dtype)[toks[:, None]]

    def body(carry, xs):
        h = carry
        layer, k_c, v_c = xs
        h, k_c, v_c = _decode_layer_rows(h, layer, k_c, v_c,
                                         write_slots, cfg)
        return h, (k_c, v_c)

    h, (k_new, v_new) = jax.lax.scan(
        body, h, (params["layers"], cache["k"], cache["v"]))
    h = _rmsnorm(h, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", h,
                        params["lm_head"].astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return logits[:, 0], {"k": k_new, "v": v_new}


@functools.partial(jax.jit,
                   static_argnames=("cfg", "horizon", "greedy",
                                    "top_k", "top_p", "eos_id",
                                    "shardings"),
                   donate_argnames=("cache", "last_logits"))
def _decode_multi(params: Params, cache, last_logits, row_len, active,
                  budget, tok_idx, row_keys, temperature,
                  cfg: LlamaConfig, horizon: int, greedy: bool,
                  top_k: Optional[int], top_p: Optional[float],
                  eos_id: Optional[int],
                  shardings: Optional[_EngineShardings] = None):
    """Fuse `horizon` decode iterations into ONE program: a `lax.scan`
    whose body samples every row's next token ON DEVICE from the
    carried `last_logits` (greedy argmax, or per-row rng streams — see
    generate.sample_rows), feeds it through `_decode_core`, and applies
    per-row eos/budget/room masking so rows that finish mid-horizon
    FREEZE: their row_len stops advancing, their `last_logits` stops
    updating, and their remaining emits are masked to -1. The host gets
    the whole [horizon, B] token block in a single transfer instead of
    one blocking sample per token.

    Per-iteration transition (bit-identical to the host replay in
    `DecodeEngine._emit_block`, which mirrors it without touching the
    device):
        tok      = sample(last_logits)          # emit if active
        budget  -= active;  tok_idx += active
        done     = budget <= 0 | row_len+1 >= max_len | tok == eos
        feed tok at slot row_len (all rows; frozen rows write garbage
        one slot past their content — masked everywhere, overwritten by
        the slot's next prefill)
        row_len += active & ~done;  last_logits updates where continuing

    Returns (toks [horizon, B] int32, cache, last_logits, row_len,
    active, budget, tok_idx) — the FULL scan carry, not just the token
    block. `last_logits` carries across calls, so the final iteration's
    decode is never wasted — the next horizon samples straight from it
    — and the carried row state lets the async pipeline chain a
    run-ahead dispatch directly off the previous one's device arrays,
    with zero host synchronization between dispatches (the host's own
    row_len/budget copies catch up when it drains the token block)."""
    max_len = cache["k"].shape[2]

    def body(carry, _):
        cache, last_logits, row_len, active, budget, tok_idx = carry
        tok = sample_rows(last_logits, row_keys, tok_idx,
                          greedy=greedy, temperature=temperature,
                          top_k=top_k, top_p=top_p)
        emit = jnp.where(active, tok, -1)
        live = active.astype(jnp.int32)
        budget = budget - live
        tok_idx = tok_idx + live
        done_now = (budget <= 0) | (row_len + 1 >= max_len)
        if eos_id is not None:
            done_now = done_now | (tok == eos_id)
        cont = active & ~done_now
        logits, cache = _decode_core(params, tok, cache, row_len, cfg)
        row_len = row_len + cont.astype(jnp.int32)
        last_logits = jnp.where(cont[:, None], logits, last_logits)
        if shardings is not None:
            # Pin the scan carry to the engine's layout every
            # iteration: the KV write stays a chip-local scatter (each
            # chip owns its heads' cache shard) and the carried logits
            # stay vocab-sharded — XLA partitions attention heads and
            # MLP width instead of replicating the whole model.
            cache = jax.lax.with_sharding_constraint(
                cache, shardings.cache)
            last_logits = jax.lax.with_sharding_constraint(
                last_logits, shardings.logits)
        return (cache, last_logits, row_len, cont, budget,
                tok_idx), emit

    (cache, last_logits, row_len, active, budget, tok_idx), toks = \
        jax.lax.scan(
            body, (cache, last_logits, row_len, active, budget,
                   tok_idx),
            None, length=horizon)
    if shardings is not None:
        # The [H, B] block is the ONE device->host transfer: keep it
        # fully replicated so the drain reads whole from any chip —
        # host-sync bytes stay 4*H*B regardless of tp degree.
        toks = jax.lax.with_sharding_constraint(
            toks, shardings.replicated)
    return toks, cache, last_logits, row_len, active, budget, tok_idx


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------

class _Request:
    __slots__ = ("req_id", "prompt", "max_new_tokens", "tokens", "done",
                 "priority", "seq", "rng", "deadline", "shed")

    def __init__(self, req_id: int, prompt: List[int],
                 max_new_tokens: int, priority: int = 0, seq: int = 0,
                 rng: Optional[np.ndarray] = None,
                 deadline: Optional[float] = None):
        self.req_id = req_id
        self.prompt = list(prompt)
        self.max_new_tokens = max_new_tokens
        self.tokens: List[int] = []
        self.done = False
        self.priority = priority    # lower = admitted first (priority policy)
        self.seq = seq              # submission order (FIFO tie-break)
        self.rng = rng              # [2] uint32 per-request key stream
        self.deadline = deadline    # absolute clock time; None = no SLO
        self.shed = False           # retired past-deadline, no prefill run


class _PrefillState:
    """A slot row whose prompt suffix is still being written.

    ``pos`` is the row's prefill frontier: slots [0, pos) hold valid
    K/V (copied prefix + completed chunks). ``nodes`` are the PENDING
    trie nodes this row's prefill will fill — each is copied out to the
    pool and committed as soon as the frontier covers its block."""

    __slots__ = ("req", "pos", "nodes")

    def __init__(self, req: _Request, pos: int, nodes: list):
        self.req = req
        self.pos = pos
        self.nodes = nodes


class _InflightStep:
    """One dispatched-but-not-yet-drained fused decode step.

    ``toks`` is the step's [H, B] device token block — its
    `copy_to_host_async` was issued at dispatch, so by the time the
    host drains it (one or more steps later) the bytes are already on
    their way or landed. ``chain`` is the dispatch's returned device
    row state (row_len, active, budget, tok_idx): the NEXT run-ahead
    dispatch consumes it directly, so queued steps never synchronize
    with the host. ``run_ahead`` marks steps dispatched before the
    host had replayed the previous block — only those can contain
    overrun iterations for rows that had already finished."""

    __slots__ = ("toks", "H", "rows", "run_ahead", "chain")

    def __init__(self, toks, H: int, rows: List[int], run_ahead: bool,
                 chain: tuple):
        self.toks = toks
        self.H = H
        self.rows = rows
        self.run_ahead = run_ahead
        self.chain = chain


class DecodeEngine:
    """Slot-based continuous batching over a shared KV cache.

    `submit()` enqueues a request; `step()` admits queued requests into
    free slots (batched, same-bucket prefills share ONE program), then
    advances every live slot up to `decode_horizon` tokens with ONE
    fused device program and ONE device->host transfer (the [H, B]
    token block); `run()` drains everything. The horizon adapts each
    step via the scheduler's `horizon_hint`: 1 while queued requests
    could take a free slot next step (protect TTFT), the full
    `decode_horizon` once slots are saturated or the queue is empty
    (amortize dispatch overhead) — pass `step(horizon=...)` to pin it.

    `pipeline_depth` (default 2) bounds the async ring of fused steps
    kept in flight during pure-decode stretches: step N+1 is dispatched
    BEFORE step N's token block is pulled to the host (the block's
    `copy_to_host_async` overlaps N+1's compute), chained through the
    device-carried row state, and the host drains/replays one step
    behind. The ring flushes whenever the scheduler reports pending
    admissions or a row is mid-chunked-prefill, so scheduling decisions
    always see fully-replayed host state; depth 1 is the synchronous
    engine. Output is token-identical at every depth.

    Greedy by default; sampling mode (greedy=False) applies the same
    temperature/top_k/top_p semantics as `generate`, with a PER-REQUEST
    key stream: request r's i-th token uses
    ``step_rng_key(r.rng, i)`` — exactly solo `generate`'s schedule —
    so sampled output, like greedy output, is token-identical to that
    request's solo run (pass ``submit(..., rng=...)`` to pin a stream;
    the default derives one from the engine rng and request id).

    bucket_lens=True rounds each admission's prefill to the next power
    of two, so a handful of XLA compiles (one per length bucket x
    power-of-two admission-group size) cover all traffic; adaptive
    stepping rounds the horizon down to a power of two, so the fused
    decode program compiles at most log2(decode_horizon)+1 variants.

    Scheduling / admission control (models/scheduler.py):
      scheduler="fifo"|"priority"|SchedulerPolicy — which queued
        request takes the next freed slot (`submit(..., priority=)`
        orders the priority policy; lower admits first);
      max_queue + on_full ("reject"|"block") — bounded queue
        backpressure: reject raises EngineOverloaded, block drives
        step() until a queue slot frees;
      max_prefills_per_step — per-step prefill admission budget so a
        burst of long prompts cannot starve in-flight decode rows.

    Tensor parallelism: ``tp=n`` (or a prebuilt ``mesh=`` with a "tp"
    axis) shards the model weights, the KV cache, the prefix block
    pool and the fused programs' carried state across n chips via the
    model's logical axis rules — attention heads, MLP width and the
    vocab dimension split over ICI; KV heads split when ``n_kv_heads``
    divides tp and replicate otherwise (prune_rules_for_mesh). The
    host never notices: scheduling, chunked prefill, the async
    pipeline and the single [H, B] device->host block (kept fully
    replicated) are identical at every tp degree, and so is every
    emitted token (greedy and sampled) — gated by
    tests/test_engine_sharded.py.

    Telemetry: `self.metrics` (EngineMetrics) records queue-wait /
    TTFT / TPOT / occupancy through the util.metrics Prometheus plane;
    `stats()` returns the flat snapshot. enable_metrics=False swaps in
    a no-op recorder for benchmark inner loops.
    """

    def __init__(self, params: Params, cfg: LlamaConfig, *,
                 batch_slots: int = 8, max_len: Optional[int] = None,
                 greedy: bool = True, temperature: float = 1.0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 bucket_lens: bool = True,
                 rng: Optional[jax.Array] = None,
                 scheduler: Union[str, SchedulerPolicy] = "fifo",
                 max_queue: Optional[int] = None,
                 on_full: str = "reject",
                 max_prefills_per_step: Optional[int] = None,
                 decode_horizon: int = 8,
                 pipeline_depth: int = 2,
                 prefix_cache: bool = False,
                 prefix_block: int = 32,
                 prefix_cache_bytes: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 mesh: Optional[Mesh] = None,
                 tp: Optional[int] = None,
                 sharding_rules=None,
                 engine_id: Optional[str] = None,
                 enable_metrics: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        _check_sampling_knobs(greedy, top_k, top_p)
        if on_full not in ("reject", "block"):
            raise ValueError(f"on_full must be 'reject' or 'block', "
                             f"got {on_full!r}")
        if max_queue is not None and max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if max_prefills_per_step is not None and max_prefills_per_step < 1:
            raise ValueError("max_prefills_per_step must be >= 1")
        if decode_horizon < 1:
            raise ValueError("decode_horizon must be >= 1")
        if pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        if prefix_block < 1:
            raise ValueError("prefix_block must be >= 1")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.params = params
        self.cfg = cfg
        self.B = batch_slots
        self.max_len = max_len or cfg.max_seq_len
        if self.max_len > cfg.max_seq_len:
            raise ValueError(f"max_len {self.max_len} exceeds "
                             f"max_seq_len {cfg.max_seq_len}")
        self.greedy = greedy
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self.bucket_lens = bucket_lens
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)

        self.scheduler = make_policy(scheduler)
        self.max_queue = max_queue
        self.on_full = on_full
        self.max_prefills_per_step = max_prefills_per_step
        self.decode_horizon = decode_horizon
        self.pipeline_depth = pipeline_depth
        # One clock for telemetry AND deadline shedding — injectable so
        # hysteresis/expiry tests advance time without sleeping.
        self._clock = clock
        self.metrics = (EngineMetrics(engine_id=engine_id,
                                      batch_slots=self.B, clock=clock)
                        if enable_metrics else NullEngineMetrics())

        # Tensor parallelism over an ICI mesh: `tp=n` builds a
        # {"tp": n} mesh over the first n visible devices; `mesh=`
        # hands over a prebuilt mesh carrying a "tp" axis. Weights, the
        # KV cache, the prefix block pool and the fused programs' scan
        # state are sharded over it via the model's logical axis rules
        # (heads/mlp/vocab split across chips; KV heads split when
        # n_kv_heads divides tp, replicated otherwise — see
        # prune_rules_for_mesh). Host-side scheduling, the async
        # pipeline and the single [H, B] transfer are tp-blind.
        if tp is not None:
            if mesh is not None:
                raise ValueError("pass mesh= or tp=, not both")
            if tp < 1:
                raise ValueError("tp must be >= 1")
            devs = jax.devices()
            if tp > len(devs):
                raise ValueError(
                    f"tp={tp} exceeds the {len(devs)} visible "
                    "device(s); on CPU force a virtual world with "
                    "XLA_FLAGS=--xla_force_host_platform_device_count")
            mesh = create_mesh({"tp": tp}, devs[:tp])
        self.mesh = mesh
        if mesh is not None:
            if "tp" not in mesh.axis_names:
                raise ValueError(
                    "serving mesh needs a 'tp' axis, got axes "
                    f"{mesh.axis_names}")
            self.tp_degree = int(dict(mesh.shape)["tp"])
            dims = {"heads": cfg.n_heads, "qkv": cfg.n_heads,
                    "kv": cfg.n_kv_heads, "mlp": cfg.ffn_dim,
                    "vocab": cfg.vocab_size, "embed": cfg.dim,
                    "batch": self.B}
            base = dict(DEFAULT_RULES)
            base["kv"] = "tp"   # serving shards the KV-head axis; the
            #                     training table replicates it
            rules = (sharding_rules if sharding_rules is not None
                     else prune_rules_for_mesh(base, mesh, dims))
            self._rules = rules
            self.params = shard_pytree(
                params, llama_param_specs(cfg, rules), mesh)
            self._shardings = _EngineShardings(
                cache=named_sharding(mesh, "layers", "batch", "length",
                                     "kv", "head_dim", rules=rules),
                logits=named_sharding(mesh, "batch", "vocab",
                                      rules=rules),
                pool=named_sharding(mesh, "layers", None, None, "kv",
                                    "head_dim", rules=rules))
        else:
            self.tp_degree = 1
            self._rules = None
            self._shardings = None
        self.metrics.on_tp_degree(self.tp_degree)

        self.cache = init_cache(
            cfg, self.B, self.max_len,
            sharding=None if self._shardings is None
            else self._shardings.cache)
        # Next-token logits per slot, DEVICE-resident: prefill scatters
        # into it, the fused decode samples from and re-carries it —
        # logits never cross the jit boundary to the host.
        self._last_logits = jnp.zeros((self.B, cfg.vocab_size),
                                      jnp.float32)
        if self._shardings is not None:
            self._last_logits = jax.device_put(self._last_logits,
                                               self._shardings.logits)
        self.row_len = np.zeros((self.B,), np.int32)   # written slots
        self.row_req: List[Optional[_Request]] = [None] * self.B
        self.row_budget = np.zeros((self.B,), np.int32)
        self._tok_idx = np.zeros((self.B,), np.int32)  # sampled so far
        self._row_keys = np.zeros((self.B, 2), np.uint32)
        self._base_key = _key_data(self._rng)
        self._next_id = 0
        self.results: Dict[int, _Request] = {}
        self.finished: set = set()      # done but not yet popped
        self.shed_ids: set = set()      # finished as past-deadline sheds
        self.requests_shed = 0          # plain int (enable_metrics=False)
        self.draining = False           # begin_drain(): no new submits
        # Dispatch/transfer accounting (plain ints so the benchmark's
        # enable_metrics=False engines still report them):
        self.decode_dispatches = 0     # fused decode program launches
        self.prefill_dispatches = 0    # batched prefill launches
        self.host_syncs = 0            # device->host transfers
        self.host_transfer_bytes = 0   # bytes those transfers moved
        self.tokens_out = 0            # tokens emitted, all requests
        # Prefill/prefix-reuse accounting (same plain-int discipline):
        self.prefill_real_tokens = 0   # true chunk tokens prefilled
        self.prefill_padded_tokens = 0  # bucket + pow2-group filler
        self.prefix_lookups = 0        # admissions probed in the trie
        self.prefix_hits = 0           # ... that matched >= 1 block
        self.prefix_reused_tokens = 0  # prompt tokens copied, not run
        self.prefix_evictions = 0      # LRU blocks recycled
        self.prefix_copy_dispatches = 0  # pool copy-in/out launches
        self.chunked_prefill_stalls = 0  # steps with a row mid-prefill
        # Async pipeline: dispatched-but-undrained fused steps, oldest
        # first. Same plain-int discipline for the counters so
        # enable_metrics=False benches still report the pipeline plane.
        self._ring: collections.deque = collections.deque()
        self.pipeline_flushes = 0      # forced full drains of the ring
        self.pipeline_overrun_tokens = 0  # masked run-ahead iterations
        self._pl_depth_sum = 0         # ring depth sampled at each drain
        self._pl_depth_n = 0

        # Chunked prefill: rows whose suffix is still being written,
        # row -> _PrefillState. A row in here is EXCLUDED from decode
        # (its last_logits are not final) and advances one chunk per
        # step via _advance_prefills().
        self.prefill_chunk = prefill_chunk
        self._row_prefill: Dict[int, _PrefillState] = {}

        # Shared-prefix KV cache: host-side radix index over committed
        # prompt blocks + a device-resident pool the copy programs
        # gather from / scatter into. Sized by prefix_cache_bytes
        # (default: room for 2 full batches of max_len tokens), plus
        # the reserved scratch block 0.
        self.prefix_block = prefix_block
        if prefix_cache:
            L, _, _, KV, D = self.cache["k"].shape
            kv_dtype = self.cache["k"].dtype
            bb = block_bytes(L, prefix_block, KV, D,
                             jnp.dtype(kv_dtype).itemsize)
            if prefix_cache_bytes is None:
                n_blocks = 1 + (2 * self.B * self.max_len) // prefix_block
            else:
                n_blocks = 1 + prefix_cache_bytes // bb
            self._prefix: Optional[PrefixCacheIndex] = PrefixCacheIndex(
                block_tokens=prefix_block, n_blocks=n_blocks,
                on_evict=self._on_prefix_evict)
            self._pool_k = jnp.zeros(
                (L, n_blocks, prefix_block, KV, D), kv_dtype)
            self._pool_v = jnp.zeros(
                (L, n_blocks, prefix_block, KV, D), kv_dtype)
            if self._shardings is not None:
                # Pool lives on the mesh with the cache's KV sharding:
                # each chip holds only its heads' slice of every block
                # (prefix_cache_bytes stays the GLOBAL pool footprint;
                # per-chip resident bytes are that / tp when KV
                # shards).
                self._pool_k = jax.device_put(self._pool_k,
                                              self._shardings.pool)
                self._pool_v = jax.device_put(self._pool_v,
                                              self._shardings.pool)
            attach = getattr(self.scheduler, "attach_prefix_probe", None)
            if attach is not None:
                attach(self._prefix_probe)
        else:
            self._prefix = None
            self._pool_k = self._pool_v = None

    # -- public API --------------------------------------------------------

    def submit(self, prompt: List[int], max_new_tokens: int = 32,
               priority: int = 0,
               rng: Optional[jax.Array] = None,
               deadline_s: Optional[float] = None) -> int:
        """Enqueue a request; returns its id (see `results`).

        ``priority`` (lower = sooner) orders admission under the
        priority policy; the FIFO policy ignores it. With a bounded
        queue (max_queue), a full queue either raises EngineOverloaded
        (on_full="reject") or drives the engine until a queue slot
        frees (on_full="block"). ``rng`` pins this request's sampling
        key stream (greedy=False engines): with the same key, the
        request's sampled tokens equal solo
        ``generate(..., rng=rng)``; by default a distinct stream is
        derived from the engine rng and request id.

        ``deadline_s`` is the request's admission SLO: a latency budget
        (seconds from now, on the engine clock) within which prefill
        must START. A request still queued when its deadline passes is
        SHED — retired with zero tokens, ``shed_ids`` membership, and
        the ``requests_shed`` counter — instead of burning prefill
        compute no caller is waiting for; requests already admitted
        always run to completion (killing mid-decode would waste the
        prefill already paid). ``deadline_s <= 0`` sheds immediately
        (reject-before-prefill). After ``begin_drain()`` submit raises
        EngineDraining — a draining replica finishes what it holds but
        takes nothing new."""
        if self.draining:
            raise EngineDraining(
                "engine is draining (begin_drain was called): it will "
                "finish in-flight work but accepts no new requests")
        if not len(prompt):
            raise ValueError("empty prompt: need at least one token "
                             "(prepend a BOS token)")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds engine max_len "
                f"{self.max_len}")
        deadline = (None if deadline_s is None
                    else self._clock() + deadline_s)
        if deadline is not None and self._clock() >= deadline:
            # Dead on arrival: shed before the bounded-queue check —
            # it will never occupy a queue slot, let alone a prefill.
            req = _Request(self._next_id, prompt, max_new_tokens,
                           priority=priority, seq=self._next_id,
                           rng=None if rng is None else _key_data(rng),
                           deadline=deadline)
            self._next_id += 1
            self.results[req.req_id] = req
            self.metrics.on_submit(req.req_id)
            self._shed(req)
            return req.req_id
        if self.max_queue is not None and \
                len(self.scheduler) >= self.max_queue:
            if self.on_full == "reject":
                self.metrics.on_reject()
                raise EngineOverloaded(
                    f"queue full ({self.max_queue} queued requests); "
                    f"shed load or use on_full='block'")
            while len(self.scheduler) >= self.max_queue:
                self.step()   # admissions + finishes drain the queue
        req = _Request(self._next_id, prompt, max_new_tokens,
                       priority=priority, seq=self._next_id,
                       rng=None if rng is None else _key_data(rng),
                       deadline=deadline)
        self._next_id += 1
        self.scheduler.push(req)
        self.results[req.req_id] = req
        self.metrics.on_submit(req.req_id)
        self.metrics.observe_queue_depth(len(self.scheduler))
        return req.req_id

    def pending(self) -> bool:
        return bool(len(self.scheduler)) or any(
            r is not None for r in self.row_req)

    def step(self, horizon: Optional[int] = None) -> Dict[int, List[int]]:
        """Admit queued requests into free slots (at most
        max_prefills_per_step of them, same-bucket admissions batched
        into one prefill program each), then advance every live slot up
        to `horizon` tokens in ONE fused device program with ONE
        device->host transfer. Returns {req_id: [tokens]} emitted this
        step — up to `horizon` per request; a request that finishes
        mid-horizon (budget/eos/room) is frozen on device and retired
        here, and its slot admits a newcomer next step.

        ``horizon=None`` (the default) adapts: the scheduler's
        `horizon_hint` picks 1 while a queued request could take a free
        slot next step, else `decode_horizon`, capped at the largest
        remaining budget (no trailing iterations run fully frozen) and
        rounded down to a power of two (bounded compile count).

        With `pipeline_depth >= 2` and a pure-decode stretch (queue
        empty, nothing mid-prefill), the step dispatches ahead: it tops
        the in-flight ring up to `pipeline_depth` fused steps (each
        chained off the previous one's device row state) BEFORE pulling
        the oldest step's token block, so the device computes step N+1
        while the host replays step N. Per-call emissions are identical
        to the synchronous engine: each call still drains exactly one
        block, whose horizon follows the same budget arithmetic."""
        if horizon is not None and horizon < 1:
            raise ValueError("horizon must be >= 1")
        emitted: Dict[int, List[int]] = {}
        # Flush the pipeline before any admission / prefill / prefix
        # copy: those paths mutate the cache from the host side and
        # read row/slot state, so every in-flight run-ahead block must
        # be replayed first (freed slots, retired requests) for the
        # admission decision to see true state.
        if self._ring and (self.scheduler.admissions_pending()
                           or self._row_prefill):
            self._flush_pipeline(emitted)
        budget = self.max_prefills_per_step or self.B
        admissions: List[Tuple[int, _Request]] = []
        begin = getattr(self.scheduler, "begin_admission_round", None)
        if begin is not None:
            begin()
        deferred = False
        for row in range(self.B):
            if budget <= 0 or deferred:
                break
            if self.row_req[row] is not None:
                continue
            req = None
            while len(self.scheduler):
                cand = self.scheduler.pop()
                if cand is None:
                    deferred = True  # prefix policy deferred the queue
                    break
                if cand.deadline is not None and \
                        self._clock() >= cand.deadline:
                    # Expired mid-queue: shed at the admission gate —
                    # the last moment before prefill compute would be
                    # committed to a request nobody is waiting for.
                    self._shed(cand)
                    continue
                req = cand
                break
            if req is None:
                continue       # queue drained to empty (or deferred)
            admissions.append((row, req))
            budget -= 1
        if admissions:
            self._admit_rows(admissions)
        self._advance_prefills()

        live = [b for b in range(self.B) if self.row_req[b] is not None]
        if not live:
            if self._ring:             # defensive: never strand blocks
                self._flush_pipeline(emitted)
            return emitted
        # Rows mid-chunked-prefill are NOT decodable: their last_logits
        # still hold an intermediate chunk's scatter. They ride along
        # frozen (active=False) and take their next chunk next step.
        decodable = [b for b in live if b not in self._row_prefill]
        if len(decodable) < len(live):
            self.chunked_prefill_stalls += 1
            self.metrics.on_prefill_stall()
        if not decodable:
            self.metrics.on_step(len(live), len(self.scheduler), 0)
            return emitted

        if not self._ring:
            H = horizon
            if H is None:
                free = self.B - len(live)
                H = self.scheduler.horizon_hint(
                    free_slots=free, max_horizon=self.decode_horizon)
                if len(decodable) < len(live):
                    H = 1      # keep the chunk cadence: a mid-prefill
                    #            row must not wait a long horizon for
                    #            its next chunk (bounded TTFT)
                # Cap at the largest remaining row budget (no trailing
                # iterations with every row frozen), rounded DOWN to a
                # power of two: the fused program recompiles per
                # distinct H, so adaptive serving touches at most
                # log2(horizon)+1 programs instead of one per budget
                # remainder.
                H = min(H, int(self.row_budget[decodable].max()))
                H = 1 << max(0, H.bit_length() - 1)
            self._dispatch_decode(H, decodable, chain=None)
        self._top_up_pipeline(decodable, horizon)
        self._drain_one(emitted)
        # End of stream: every request retired, but run-ahead blocks
        # may remain (all-masked overrun). Drain them now so pending()
        # reads true and the ring never outlives its requests.
        if self._ring and not any(r is not None for r in self.row_req):
            self._flush_pipeline(emitted)
        n_tokens = sum(len(t) for t in emitted.values())
        self.tokens_out += n_tokens
        self.metrics.on_step(
            sum(r is not None for r in self.row_req),
            len(self.scheduler), n_tokens)
        return emitted

    # -- async pipeline ----------------------------------------------------

    def _dispatch_decode(self, H: int, rows: List[int],
                         chain: Optional[tuple]) -> None:
        """Launch ONE fused decode step without waiting on anything:
        from replayed host state after a flush (`chain=None`), or
        chained off the previous in-flight dispatch's device-carried
        row state (run-ahead). The token block's `copy_to_host_async`
        is issued immediately, so the transfer overlaps the device
        computing the block — and any queued successors."""
        if chain is None:
            active = np.array([self.row_req[b] is not None
                               and b not in self._row_prefill
                               for b in range(self.B)])
            args = (jnp.asarray(self.row_len), jnp.asarray(active),
                    jnp.asarray(self.row_budget),
                    jnp.asarray(self._tok_idx))
        else:
            args = chain
        toks, self.cache, self._last_logits, rl, ac, bu, ti = \
            _decode_multi(
                self.params, self.cache, self._last_logits, *args,
                jnp.asarray(self._row_keys), self.temperature,
                self.cfg, H, self.greedy, self.top_k, self.top_p,
                self.eos_id, shardings=self._shardings)
        try:
            toks.copy_to_host_async()
        except AttributeError:
            pass                   # non-jax.Array backends (tests)
        self._ring.append(_InflightStep(toks, H, list(rows),
                                        run_ahead=chain is not None,
                                        chain=(rl, ac, bu, ti)))
        self.decode_dispatches += 1
        self.metrics.on_dispatch(H, host_syncs=0)

    def _top_up_pipeline(self, rows: List[int],
                         horizon: Optional[int]) -> None:
        """Run ahead: keep up to `pipeline_depth` fused steps in flight
        while the engine is in a pure-decode stretch (no admission
        could change the batch, no row mid-prefill). Each queued step
        chains the previous dispatch's device row state, so no host
        sync happens between dispatches. Horizons are chosen from host
        budgets minus everything already in flight — pessimistic, so a
        queued step is never provably all-frozen; rows that finish
        mid-flight still mask their tail iterations on device
        (`pipeline_overrun_tokens`)."""
        if (self.pipeline_depth < 2 or self._row_prefill
                or self.scheduler.admissions_pending()):
            return
        while len(self._ring) < self.pipeline_depth:
            inflight = sum(e.H for e in self._ring)
            rem = int(self.row_budget[rows].max()) - inflight
            if rem <= 0:
                break              # every further iteration would be
                #                    overrun — nothing left to compute
            if horizon is not None:
                Hn = horizon
            else:
                Hn = self.scheduler.horizon_hint(
                    free_slots=self.B - sum(r is not None
                                            for r in self.row_req),
                    max_horizon=self.decode_horizon)
                Hn = min(Hn, rem)
                Hn = 1 << max(0, Hn.bit_length() - 1)
            self._dispatch_decode(Hn, rows,
                                  chain=self._ring[-1].chain)

    def _drain_one(self, emitted: Dict[int, List[int]]) -> None:
        """Pull the OLDEST in-flight token block to the host (its async
        copy has been in progress since dispatch) and replay it. With
        the ring topped up first, the device is already computing the
        next step(s) while this replay runs — the overlap that hides
        the host bookkeeping."""
        entry = self._ring.popleft()
        depth = len(self._ring) + 1    # steps in flight at this drain
        self._pl_depth_sum += depth
        self._pl_depth_n += 1
        block = _device_get(entry.toks)
        self.host_syncs += 1
        nbytes = int(getattr(block, "nbytes", block.size * 4))
        self.host_transfer_bytes += nbytes
        self.metrics.on_host_sync(nbytes=nbytes)
        self._emit_block(block, entry, emitted)
        self.metrics.on_pipeline_drain(depth, len(self._ring))

    def _flush_pipeline(self, emitted: Dict[int, List[int]]) -> None:
        """Drain EVERY in-flight step. Called before any admission /
        prefill / prefix copy, and at end of stream — the points where
        host state must be fully caught up with the device."""
        if not self._ring:
            return
        self.pipeline_flushes += 1
        self.metrics.on_pipeline_flush()
        while self._ring:
            self._drain_one(emitted)

    def stats(self) -> Dict[str, float]:
        """Flat numeric telemetry snapshot (EngineMetrics.stats) plus
        the engine's instantaneous queue/slot state — safe to publish
        as gauges (serve.metrics.report_engine_stats)."""
        out = self.metrics.stats()
        out["queue_depth"] = float(len(self.scheduler))
        out["live_slots"] = float(
            sum(r is not None for r in self.row_req))
        out["slot_occupancy"] = out["live_slots"] / self.B
        # Fleet plane: the router scores replicas on these three plus
        # the TTFT/TPOT percentiles from EngineMetrics.stats().
        out["requests_shed"] = float(self.requests_shed)
        out["pending_prefill_tokens"] = float(
            self.pending_prefill_tokens())
        out["draining"] = 1.0 if self.draining else 0.0
        # Engine-level dispatch accounting (kept even when metrics are
        # disabled — benchmarks read these to report syncs per token).
        # Every derived ratio guards its denominator: a fresh engine
        # (no token emitted, no prefill run) reports 0.0, never NaN.
        def _ratio(num: float, den: float) -> float:
            return num / den if den else 0.0

        out["decode_dispatches"] = float(self.decode_dispatches)
        out["prefill_dispatches"] = float(self.prefill_dispatches)
        out["host_syncs"] = float(self.host_syncs)
        out["host_syncs_per_token"] = _ratio(self.host_syncs,
                                             self.tokens_out)
        # Tensor-parallel plane: tp_degree is 1 for an unsharded
        # engine; transfer bytes count the [H, B] token blocks pulled
        # at drain — the replicated choke point, so bytes/token must
        # NOT grow with tp degree (microbench gates this).
        out["tp_degree"] = float(self.tp_degree)
        out["host_transfer_bytes"] = float(self.host_transfer_bytes)
        out["host_transfer_bytes_per_token"] = _ratio(
            self.host_transfer_bytes, self.tokens_out)
        out["dispatches_per_token"] = _ratio(self.decode_dispatches,
                                             self.tokens_out)
        # Prefill efficiency: real suffix tokens vs bucket/pow2 filler.
        out["prefill_real_tokens"] = float(self.prefill_real_tokens)
        out["prefill_padded_tokens"] = float(self.prefill_padded_tokens)
        out["prefill_padding_waste_frac"] = _ratio(
            self.prefill_padded_tokens,
            self.prefill_real_tokens + self.prefill_padded_tokens)
        # Prefix-reuse plane: reused = prompt tokens COPIED from the
        # pool; recomputed (= prefill_real_tokens) = prompt tokens the
        # prefill actually ran.
        out["prefix_lookups"] = float(self.prefix_lookups)
        out["prefix_hits"] = float(self.prefix_hits)
        out["prefix_hit_rate"] = _ratio(self.prefix_hits,
                                        self.prefix_lookups)
        out["prefix_reused_tokens"] = float(self.prefix_reused_tokens)
        out["prefix_reused_frac"] = _ratio(
            self.prefix_reused_tokens,
            self.prefix_reused_tokens + self.prefill_real_tokens)
        out["prefix_evictions"] = float(self.prefix_evictions)
        out["prefix_copy_dispatches"] = float(self.prefix_copy_dispatches)
        out["chunked_prefill_stalls"] = float(self.chunked_prefill_stalls)
        # Async-pipeline plane. depth_effective is the mean number of
        # fused steps in flight at each drain (1.0 = synchronous; ->
        # pipeline_depth when run-ahead is sustained); host_lag_steps
        # is the instantaneous ring length (dispatched, not yet
        # replayed); overrun tokens are masked device iterations run
        # ahead for rows that had already finished. Fresh engine: all
        # 0.0 (the _ratio guard).
        out["pipeline_depth"] = float(self.pipeline_depth)
        out["pipeline_depth_effective"] = _ratio(self._pl_depth_sum,
                                                 self._pl_depth_n)
        out["pipeline_flushes"] = float(self.pipeline_flushes)
        out["pipeline_overrun_tokens"] = float(
            self.pipeline_overrun_tokens)
        out["host_lag_steps"] = float(len(self._ring))
        if self._prefix is not None:
            out["prefix_blocks_in_use"] = float(self._prefix.blocks_in_use)
            out["prefix_blocks_total"] = float(self._prefix.blocks_total)
        return out

    def run(self) -> Dict[int, List[int]]:
        """Drain queue + slots; returns {req_id: generated tokens} for
        every finished request and POPS them from the engine (a
        long-running server that never popped would leak one _Request
        per call served)."""
        while self.pending():
            self.step()
        return {rid: self.pop_result(rid) for rid in list(self.finished)}

    def pop_result(self, req_id: int) -> List[int]:
        """Remove a FINISHED request from the engine and return its
        generated tokens. Long-running callers driving step() directly
        must pop each request as it finishes (see `finished`). A shed
        request pops an empty list — check `shed_ids` BEFORE popping
        to distinguish a shed from a zero-token finish."""
        if req_id not in self.finished:
            raise KeyError(f"request {req_id} unknown or not finished")
        self.finished.discard(req_id)
        self.shed_ids.discard(req_id)
        return self.results.pop(req_id).tokens

    # -- fleet integration: drain hook + router load probes ----------------

    def begin_drain(self) -> None:
        """Stop accepting new requests; everything already submitted
        (queued or in-flight) still runs to completion. This is the
        flush-before-removal half of fleet scale-down: the fleet stops
        routing to a DRAINING replica, keeps stepping it until
        `pending()` reads False, then removes it — so an admitted
        token is never lost to a scale decision. Idempotent."""
        self.draining = True

    def drain(self) -> Dict[int, List[int]]:
        """`begin_drain()` + run to empty: flushes the async pipeline,
        finishes every queued/in-flight request, and returns
        {req_id: tokens} for all of them (popping, like `run()`)."""
        self.begin_drain()
        return self.run()

    def pending_prefill_tokens(self) -> int:
        """Prompt tokens this engine has accepted but not yet
        prefilled: every queued request's full prompt plus the
        uncovered suffix of every row mid-chunked-prefill. A pure host
        count (zero device syncs) — the fleet router's per-replica
        cost signal: a replica may show free slots yet owe seconds of
        prefill to requests ahead of the newcomer."""
        n = sum(len(st.req.prompt) - st.pos
                for st in self._row_prefill.values())
        queued = getattr(self.scheduler, "queued_requests", None)
        if queued is not None:
            try:
                for r in queued():
                    n += len(r.prompt)
            except NotImplementedError:
                pass     # custom policy without the probe: slots-only
        return n

    def prefix_match_tokens(self, prompt: List[int]) -> int:
        """Prompt tokens this engine could COPY from its prefix pool
        instead of prefilling, right now (0 without a prefix cache).
        A pure host trie walk with peek=True: probing every replica
        per routing decision must not perturb any replica's LRU
        recency — only the replica that WINS the request touches its
        trie (at admission)."""
        if self._prefix is None:
            return 0
        ids, _ = self._prefix.match(prompt, peek=True)
        return len(ids) * self.prefix_block

    # -- internals ---------------------------------------------------------

    def _bucket(self, n: int) -> int:
        if not self.bucket_lens:
            return n
        return min(1 << (n - 1).bit_length(), self.max_len)

    def _req_key(self, req: _Request) -> np.ndarray:
        """Per-request sampling stream: the submitted key verbatim, or
        a distinct stream mixed host-side from the engine key and the
        request id (no device dispatch per admission)."""
        if req.rng is not None:
            return req.rng
        mix0 = (req.req_id * 0x9E3779B9 + 1) & 0xFFFFFFFF
        mix1 = (req.req_id * 0x85EBCA6B + 1) & 0xFFFFFFFF
        return np.array([int(self._base_key[0]) ^ mix0,
                         int(self._base_key[1]) ^ mix1], np.uint32)

    def _shed(self, req: _Request) -> None:
        """Retire a past-deadline request WITHOUT admitting it: no
        slot, no prefill, no tokens. It lands in `finished` (and
        `shed_ids`) like a normal completion so callers polling
        finished/pop_result need no special path."""
        req.done = True
        req.shed = True
        self.finished.add(req.req_id)
        self.shed_ids.add(req.req_id)
        self.requests_shed += 1
        self.metrics.on_shed(req.req_id)

    def _on_prefix_evict(self, n: int) -> None:
        self.prefix_evictions += n
        self.metrics.on_prefix_evictions(n)

    def _prefix_probe(self, prompt) -> Tuple[int, Optional[tuple],
                                             bool]:
        """(matched_tokens, prefix_group_key, next_block_pending) for
        the prefix-affinity scheduler — a pure host trie walk, zero
        device dispatches. The group key (the prompt's first block) is
        None for prompts too short to ever share a block."""
        ids, pending = self._prefix.match(prompt)
        T = self.prefix_block
        key = tuple(prompt[:T]) if len(prompt) > T else None
        return len(ids) * T, key, pending

    def _admit_rows(self, admissions: List[Tuple[int, _Request]]) -> None:
        """Bind this step's admissions to their rows and start their
        prefills. With the prefix cache on, each admission first probes
        the trie: a warm prompt's matched blocks are COPIED from the
        device pool into the row (grouped so same-chain-length copies
        share ONE `_prefix_copy_in` program) and only the suffix is
        prefilled; novel full blocks are registered PENDING and copied
        out to the pool as the row's prefill covers them. The actual
        prefill work — whole suffix, or `prefill_chunk`-sized pieces
        across steps — runs in `_advance_prefills`. First tokens are
        NOT sampled here: each row's last-prompt logits stay on device
        in `_last_logits` and the fused decode samples them — admission
        costs zero host round-trips."""
        copy_groups: Dict[int, List[Tuple[int, List[int]]]] = {}
        for row, req in admissions:
            self.metrics.on_admit(req.req_id)   # queue wait ends here
            start = 0
            nodes: list = []
            if self._prefix is not None:
                ids, _ = self._prefix.match(req.prompt)
                self.prefix_lookups += 1
                T = self.prefix_block
                if ids:
                    self.prefix_hits += 1
                    start = len(ids) * T
                    self.prefix_reused_tokens += start
                    # Pad the chain to a power of two (repeat the last
                    # block: its rewrite is overwritten by the suffix
                    # prefill / never attended) so a handful of copy-in
                    # compiles cover every chain length.
                    nbp = _pow2(len(ids))
                    if nbp * T > self.max_len:
                        nbp = len(ids)
                    ids_p = list(ids) + [ids[-1]] * (nbp - len(ids))
                    copy_groups.setdefault(nbp, []).append((row, ids_p))
                nodes = self._prefix.extend(req.prompt)
                self.metrics.on_prefix(hit=bool(ids), reused_tokens=start)
            self.row_req[row] = req
            self.row_len[row] = start          # frontier: copied prefix
            self.row_budget[row] = req.max_new_tokens
            self._tok_idx[row] = 0
            self._row_keys[row] = self._req_key(req)
            self._row_prefill[row] = _PrefillState(req, start, nodes)
        for nbp in sorted(copy_groups):
            grp = copy_groups[nbp]
            n = len(grp)
            n_pad = _pow2(n)
            rows = np.zeros((n_pad,), np.int32)
            bids = np.zeros((n_pad, nbp), np.int32)
            for i, (row, ids_p) in enumerate(grp):
                rows[i] = row
                bids[i] = ids_p
            rows[n:] = rows[n - 1]     # duplicate scatters: identical
            bids[n:] = bids[n - 1]     # values, deterministic result
            self.cache = _prefix_copy_in(
                self.cache, self._pool_k, self._pool_v,
                jnp.asarray(bids), jnp.asarray(rows), nbp,
                self.prefix_block, shardings=self._shardings)
            self.prefix_copy_dispatches += 1

    def _advance_prefills(self) -> None:
        """Advance every mid-prefill row by one chunk (the whole
        remaining suffix when `prefill_chunk` is None), same-bucket
        chunks batched into ONE `_prefill_rows` program. A row whose
        frontier reaches its prompt length leaves `_row_prefill` and is
        decodable THIS step (its last chunk scattered the true
        last-prompt logits). Completed prefix blocks are flushed to the
        pool and committed as the frontier passes them."""
        if not self._row_prefill:
            return
        groups: Dict[int, List[Tuple[int, _PrefillState, int]]] = {}
        for row, st in self._row_prefill.items():
            C = len(st.req.prompt) - st.pos
            if self.prefill_chunk is not None:
                C = min(C, self.prefill_chunk)
            # Bucket the chunk, capped so the scatter never runs past
            # max_len (starts differ per row; the cap is per-row).
            Cb = min(self._bucket(C), self.max_len - st.pos)
            groups.setdefault(Cb, []).append((row, st, C))
        for Cb in sorted(groups):
            grp = groups[Cb]
            n = len(grp)
            n_pad = _pow2(n)
            prompts = np.zeros((n_pad, Cb), np.int32)
            rows = np.zeros((n_pad,), np.int32)
            starts = np.zeros((n_pad,), np.int32)
            last_idx = np.zeros((n_pad,), np.int32)
            real = 0
            for i, (row, st, C) in enumerate(grp):
                prompts[i, :C] = st.req.prompt[st.pos:st.pos + C]
                rows[i] = row
                starts[i] = st.pos
                last_idx[i] = C - 1
                real += C
            prompts[n:] = prompts[n - 1]    # filler: repeat last row —
            rows[n:] = rows[n - 1]          # duplicate scatters write
            starts[n:] = starts[n - 1]      # identical values
            last_idx[n:] = last_idx[n - 1]
            self.cache, self._last_logits = _prefill_rows(
                self.params, jnp.asarray(prompts), self.cache,
                self._last_logits, jnp.asarray(rows),
                jnp.asarray(starts), jnp.asarray(last_idx), self.cfg,
                shardings=self._shardings)
            self.prefill_dispatches += 1
            padded = n_pad * Cb - real
            self.prefill_real_tokens += real
            self.prefill_padded_tokens += padded
            self.metrics.on_prefill_batch(real, padded)
        done_rows = []
        for grp in groups.values():
            for row, st, C in grp:
                st.pos += C
                self.row_len[row] = st.pos
                if self._prefix is not None:
                    self._flush_copy_out(row, st)
                if st.pos >= len(st.req.prompt):
                    done_rows.append(row)
        for row in done_rows:
            del self._row_prefill[row]

    def _flush_copy_out(self, row: int, st: _PrefillState) -> None:
        """Copy every pending prefix block the row's frontier now
        covers out to the pool (one program per consecutive run,
        chain length padded to a power of two with the scratch block)
        and COMMIT it — from the next admission round on, `match` will
        hand the block to warm requests."""
        T = self.prefix_block
        while st.nodes and (st.nodes[0][0] + 1) * T <= st.pos:
            run = [st.nodes.pop(0)]
            while st.nodes and st.nodes[0][0] == run[-1][0] + 1 and \
                    (st.nodes[0][0] + 1) * T <= st.pos:
                run.append(st.nodes.pop(0))
            nbp = _pow2(len(run))
            bids = np.zeros((nbp,), np.int32)   # pad = scratch block 0
            for i, (_, node) in enumerate(run):
                bids[i] = node.block_id
            self._pool_k, self._pool_v = _prefix_copy_out(
                self.cache["k"], self.cache["v"], self._pool_k,
                self._pool_v, row,
                run[0][0] * T, jnp.asarray(bids), nbp, T,
                shardings=self._shardings)
            self.prefix_copy_dispatches += 1
            for _, node in run:
                self._prefix.commit(node)

    def _emit_block(self, block: np.ndarray, entry: _InflightStep,
                    emitted: Dict[int, List[int]]) -> None:
        """VECTORIZED host replay of one [H, B] token block: mirrors
        `_decode_multi`'s per-iteration transition without touching the
        device, but in one numpy slice + one arithmetic pass per ROW
        instead of a Python iteration per token.

        The device masks every emit after a row freezes to -1, and
        `active` only ever transitions True->False inside a block, so
        each column is a prefix of real tokens followed by -1s: the
        count of != -1 entries IS the number of emitted tokens, and
        replaying the transition once with that count is bit-identical
        to replaying it token by token —
            budget   -= count;  tok_idx += count
            done      = budget <= 0
                        | row_len + count >= max_len   (room check at
                          the LAST emitted token's pre-advance row_len)
                        | last_tok == eos
            row_len  += count if continuing (a finishing row's state is
                        reset on retirement, so its advance is moot)
        Emission order is unchanged from the scalar loop: every live
        row emits from iteration 0, so `emitted` insertion order — and
        therefore retire-on-eos ordering — is identical.

        Rows found already retired (`row_req is None`) only occur in
        run-ahead blocks dispatched before the host replayed the
        retiring block; their columns are all-masked on device and
        accounted as `pipeline_overrun_tokens`."""
        for b in entry.rows:
            req = self.row_req[b]
            if req is None:
                if entry.run_ahead:
                    self.pipeline_overrun_tokens += entry.H
                    self.metrics.on_pipeline_overrun(entry.H)
                continue
            col = block[:, b]
            count = int((col != -1).sum())
            if count == 0:
                continue
            toks = col[:count].tolist()
            req.tokens.extend(toks)
            emitted.setdefault(req.req_id, []).extend(toks)
            self.metrics.on_tokens(req.req_id, count)
            self.row_budget[b] -= count
            self._tok_idx[b] += count
            out_of_room = self.row_len[b] + count >= self.max_len
            if (self.row_budget[b] <= 0 or out_of_room
                    or (self.eos_id is not None
                        and toks[-1] == self.eos_id)):
                req.done = True
                self.finished.add(req.req_id)
                self.metrics.on_finish(req.req_id)
                self.row_req[b] = None
                self.row_len[b] = 0      # slot free for the next prefill
                self.row_budget[b] = 0
                self._tok_idx[b] = 0
            else:
                self.row_len[b] += count  # the fed tokens took their slots
