"""Continuous-batching decode engine, TPU-first.

The reference has no serving engine for LLMs (Serve hosts arbitrary
torch callables; continuous batching lives outside it in vLLM-class
engines). Serving an LM is this framework's flagship deployment, so
slot-based continuous batching is first-class here, built the XLA way:

- ONE decode program for the whole engine, compiled once: B fixed
  decode slots advance together each step, every row at its OWN cache
  offset (per-row scatter writes + per-row masks — no recompilation as
  requests come and go, no left-padding).
- Admission is a per-length-bucket prefill program that writes one
  request's prompt K/V into a freed slot's cache row while the other
  rows' state rides along untouched (donated buffers, in-place in HBM).
- A finished row's slot is reused immediately: its stale K/V need no
  clearing because every mask is `slot < row_len`, and the next
  occupant's prefill overwrites from slot 0.

Consistency contract (tested): greedy engine output for every request
is token-identical to that request's solo `generate` run, regardless of
admission order, slot reuse, or which other requests share the batch —
and regardless of the SCHEDULER POLICY: scheduling (models/scheduler.py
— FIFO, priority classes, bounded-queue backpressure, per-step prefill
budget) only reorders admissions, never what an admitted row computes.

Telemetry (models/engine_metrics.py) timestamps every request through
queued → admitted → decoding → finished and exports queue-wait / TTFT /
TPOT / occupancy through the util.metrics Prometheus plane; `stats()`
snapshots it for the Serve path (serve.metrics.report_engine_stats).

Cites: reference Serve's dynamic batching seam
(python/ray/serve/batching.py:1) coalesces CALLS; this engine coalesces
DECODE STEPS — requests join and leave a running batch mid-flight.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.engine_metrics import EngineMetrics, NullEngineMetrics
from ray_tpu.models.generate import (_check_sampling_knobs,
                                     _layer_body, _sample_token,
                                     forward_cached, init_cache)
from ray_tpu.models.llama import LlamaConfig, _rmsnorm
from ray_tpu.models.scheduler import (EngineOverloaded, SchedulerPolicy,
                                      make_policy)

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# Compiled programs
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache",))
def _prefill_row(params: Params, prompt: jax.Array, cache, row,
                 last_idx, cfg: LlamaConfig):
    """Write `prompt` [1, Pb] K/V into cache row `row` at slots
    [0, Pb) and return (last-real-token logits [vocab], cache).

    Pb may exceed the true prompt length (length-bucketed serving):
    trailing filler tokens' K/V land at slots >= the true length, which
    every later mask excludes (`slot < row_len`), and causality keeps
    real tokens from ever attending filler — only the logits at
    `last_idx` (true length - 1) are read out."""
    row_cache = {
        "k": jax.lax.dynamic_slice_in_dim(cache["k"], row, 1, axis=1),
        "v": jax.lax.dynamic_slice_in_dim(cache["v"], row, 1, axis=1),
    }
    logits, row_cache = forward_cached(params, prompt, row_cache, 0, cfg)
    cache = {
        "k": jax.lax.dynamic_update_slice_in_dim(
            cache["k"], row_cache["k"], row, axis=1),
        "v": jax.lax.dynamic_update_slice_in_dim(
            cache["v"], row_cache["v"], row, axis=1),
    }
    return logits[0, last_idx], cache


def _decode_layer_rows(h, layer, k_cache, v_cache, write_slots,
                       cfg: LlamaConfig):
    """One decoder layer, one new token per row, each row writing its
    K/V at its own slot (scatter) and attending its own prefix.

    h: [B, 1, d]; caches [B, max_len, KV, D]; write_slots: [B].

    All the per-layer math lives in generate.py's `_layer_body` (one
    source of truth for both decode paths); only the cache-write
    strategy differs — per-row scatter here vs the contiguous chunk
    slice in `_cached_layer`. The per-prefix causal mask falls out of
    `_cached_attention` with q_slots = each row's own write slot and
    kv_valid_len = max_len (dead slots beyond a row's frontier are
    already excluded by `slot <= write_slot`)."""
    B = h.shape[0]
    bidx = jnp.arange(B)

    def write_kv(k_cache, v_cache, k, v):
        k_cache = k_cache.at[bidx, write_slots].set(
            k[:, 0].astype(k_cache.dtype))
        v_cache = v_cache.at[bidx, write_slots].set(
            v[:, 0].astype(v_cache.dtype))
        return k_cache, v_cache

    return _layer_body(h, layer, k_cache, v_cache,
                       write_slots[:, None], write_kv,
                       write_slots[:, None], k_cache.shape[1], cfg)


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache",))
def _decode_rows(params: Params, toks: jax.Array, cache, row_len,
                 cfg: LlamaConfig):
    """One decode step for ALL slots: row b's token `toks[b]` is
    written at slot `row_len[b]` and attends slots [0, row_len[b]].
    Dead rows (row_len 0) compute discarded garbage at slot 0 — their
    slot is overwritten by the next admission's prefill. Returns
    (next-token logits [B, vocab] f32, cache)."""
    write_slots = row_len                                   # [B]
    h = params["tok_embed"].astype(cfg.dtype)[toks[:, None]]

    def body(carry, xs):
        h = carry
        layer, k_c, v_c = xs
        h, k_c, v_c = _decode_layer_rows(h, layer, k_c, v_c,
                                         write_slots, cfg)
        return h, (k_c, v_c)

    h, (k_new, v_new) = jax.lax.scan(
        body, h, (params["layers"], cache["k"], cache["v"]))
    h = _rmsnorm(h, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", h,
                        params["lm_head"].astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return logits[:, 0], {"k": k_new, "v": v_new}


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------

class _Request:
    __slots__ = ("req_id", "prompt", "max_new_tokens", "tokens", "done",
                 "priority", "seq")

    def __init__(self, req_id: int, prompt: List[int],
                 max_new_tokens: int, priority: int = 0, seq: int = 0):
        self.req_id = req_id
        self.prompt = list(prompt)
        self.max_new_tokens = max_new_tokens
        self.tokens: List[int] = []
        self.done = False
        self.priority = priority    # lower = admitted first (priority policy)
        self.seq = seq              # submission order (FIFO tie-break)


class DecodeEngine:
    """Slot-based continuous batching over a shared KV cache.

    `submit()` enqueues a request; `step()` advances the whole engine
    one token (admitting queued requests into free slots first) and
    returns the tokens emitted this step; `run()` drains everything.
    Greedy by default; sampling mode (greedy=False) applies the same
    temperature/top_k/top_p semantics as `generate` with an
    engine-owned key stream.

    bucket_lens=True rounds each admission's prefill to the next power
    of two, so a handful of XLA compiles (one per length bucket) cover
    all traffic; the decode program compiles exactly once.

    Scheduling / admission control (models/scheduler.py):
      scheduler="fifo"|"priority"|SchedulerPolicy — which queued
        request takes the next freed slot (`submit(..., priority=)`
        orders the priority policy; lower admits first);
      max_queue + on_full ("reject"|"block") — bounded queue
        backpressure: reject raises EngineOverloaded, block drives
        step() until a queue slot frees;
      max_prefills_per_step — per-step prefill admission budget so a
        burst of long prompts cannot starve in-flight decode rows.

    Telemetry: `self.metrics` (EngineMetrics) records queue-wait /
    TTFT / TPOT / occupancy through the util.metrics Prometheus plane;
    `stats()` returns the flat snapshot. enable_metrics=False swaps in
    a no-op recorder for benchmark inner loops.
    """

    def __init__(self, params: Params, cfg: LlamaConfig, *,
                 batch_slots: int = 8, max_len: Optional[int] = None,
                 greedy: bool = True, temperature: float = 1.0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 bucket_lens: bool = True,
                 rng: Optional[jax.Array] = None,
                 scheduler: Union[str, SchedulerPolicy] = "fifo",
                 max_queue: Optional[int] = None,
                 on_full: str = "reject",
                 max_prefills_per_step: Optional[int] = None,
                 engine_id: Optional[str] = None,
                 enable_metrics: bool = True):
        _check_sampling_knobs(greedy, top_k, top_p)
        if on_full not in ("reject", "block"):
            raise ValueError(f"on_full must be 'reject' or 'block', "
                             f"got {on_full!r}")
        if max_queue is not None and max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if max_prefills_per_step is not None and max_prefills_per_step < 1:
            raise ValueError("max_prefills_per_step must be >= 1")
        self.params = params
        self.cfg = cfg
        self.B = batch_slots
        self.max_len = max_len or cfg.max_seq_len
        if self.max_len > cfg.max_seq_len:
            raise ValueError(f"max_len {self.max_len} exceeds "
                             f"max_seq_len {cfg.max_seq_len}")
        self.greedy = greedy
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self.bucket_lens = bucket_lens
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)

        self.scheduler = make_policy(scheduler)
        self.max_queue = max_queue
        self.on_full = on_full
        self.max_prefills_per_step = max_prefills_per_step
        self.metrics = (EngineMetrics(engine_id=engine_id,
                                      batch_slots=self.B)
                        if enable_metrics else NullEngineMetrics())

        self.cache = init_cache(cfg, self.B, self.max_len)
        self.row_len = np.zeros((self.B,), np.int32)   # written slots
        self.row_req: List[Optional[_Request]] = [None] * self.B
        self.row_budget = np.zeros((self.B,), np.int32)
        self._next_tok = np.zeros((self.B,), np.int32)  # pending feed
        self._next_id = 0
        self.results: Dict[int, _Request] = {}
        self.finished: set = set()      # done but not yet popped

    # -- public API --------------------------------------------------------

    def submit(self, prompt: List[int], max_new_tokens: int = 32,
               priority: int = 0) -> int:
        """Enqueue a request; returns its id (see `results`).

        ``priority`` (lower = sooner) orders admission under the
        priority policy; the FIFO policy ignores it. With a bounded
        queue (max_queue), a full queue either raises EngineOverloaded
        (on_full="reject") or drives the engine until a queue slot
        frees (on_full="block")."""
        if not len(prompt):
            raise ValueError("empty prompt: need at least one token "
                             "(prepend a BOS token)")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds engine max_len "
                f"{self.max_len}")
        if self.max_queue is not None and \
                len(self.scheduler) >= self.max_queue:
            if self.on_full == "reject":
                self.metrics.on_reject()
                raise EngineOverloaded(
                    f"queue full ({self.max_queue} queued requests); "
                    f"shed load or use on_full='block'")
            while len(self.scheduler) >= self.max_queue:
                self.step()   # admissions + finishes drain the queue
        req = _Request(self._next_id, prompt, max_new_tokens,
                       priority=priority, seq=self._next_id)
        self._next_id += 1
        self.scheduler.push(req)
        self.results[req.req_id] = req
        self.metrics.on_submit(req.req_id)
        self.metrics.observe_queue_depth(len(self.scheduler))
        return req.req_id

    def pending(self) -> bool:
        return bool(len(self.scheduler)) or any(
            r is not None for r in self.row_req)

    def step(self) -> Dict[int, List[int]]:
        """Admit queued requests into free slots (at most
        max_prefills_per_step of them), then advance every live slot
        one token. Returns {req_id: [tokens]} emitted this step — a
        just-admitted request can emit TWO tokens in one step (its
        prefill's first token, then the decode's)."""
        emitted: Dict[int, List[int]] = {}
        budget = self.max_prefills_per_step or self.B
        for row in range(self.B):
            if budget <= 0:
                break
            if self.row_req[row] is None and len(self.scheduler):
                self._admit(row, self.scheduler.pop(), emitted)
                budget -= 1

        live = [b for b in range(self.B) if self.row_req[b] is not None]
        if not live:
            return emitted

        toks = jnp.asarray(self._next_tok)
        logits, self.cache = _decode_rows(
            self.params, toks, self.cache, jnp.asarray(self.row_len),
            self.cfg)
        self.row_len[live] += 1  # fed tokens now occupy their slots
        nxt = self._sample(logits)
        for b in live:
            self._emit(b, int(nxt[b]), emitted)
        self.metrics.on_step(
            sum(r is not None for r in self.row_req),
            len(self.scheduler),
            sum(len(t) for t in emitted.values()))
        return emitted

    def stats(self) -> Dict[str, float]:
        """Flat numeric telemetry snapshot (EngineMetrics.stats) plus
        the engine's instantaneous queue/slot state — safe to publish
        as gauges (serve.metrics.report_engine_stats)."""
        out = self.metrics.stats()
        out["queue_depth"] = float(len(self.scheduler))
        out["live_slots"] = float(
            sum(r is not None for r in self.row_req))
        out["slot_occupancy"] = out["live_slots"] / self.B
        return out

    def run(self) -> Dict[int, List[int]]:
        """Drain queue + slots; returns {req_id: generated tokens} for
        every finished request and POPS them from the engine (a
        long-running server that never popped would leak one _Request
        per call served)."""
        while self.pending():
            self.step()
        return {rid: self.pop_result(rid) for rid in list(self.finished)}

    def pop_result(self, req_id: int) -> List[int]:
        """Remove a FINISHED request from the engine and return its
        generated tokens. Long-running callers driving step() directly
        must pop each request as it finishes (see `finished`)."""
        if req_id not in self.finished:
            raise KeyError(f"request {req_id} unknown or not finished")
        self.finished.discard(req_id)
        return self.results.pop(req_id).tokens

    # -- internals ---------------------------------------------------------

    def _bucket(self, n: int) -> int:
        if not self.bucket_lens:
            return n
        return min(1 << (n - 1).bit_length(), self.max_len)

    def _admit(self, row: int, req: _Request,
               emitted: Dict[int, List[int]]) -> None:
        self.metrics.on_admit(req.req_id)   # queue wait ends here
        P = len(req.prompt)
        Pb = self._bucket(P)
        padded = np.zeros((1, Pb), np.int32)
        padded[0, :P] = req.prompt
        last_logits, self.cache = _prefill_row(
            self.params, jnp.asarray(padded), self.cache,
            jnp.int32(row), jnp.int32(P - 1), self.cfg)
        self.row_req[row] = req
        self.row_len[row] = P
        self.row_budget[row] = req.max_new_tokens
        tok = int(self._sample(last_logits[None, :])[0])
        self._emit(row, tok, emitted)

    def _sample(self, logits: jax.Array) -> np.ndarray:
        if self.greedy:
            return np.asarray(jnp.argmax(logits, axis=-1)).astype(
                np.int32)
        self._rng, key = jax.random.split(self._rng)
        return np.asarray(_sample_token(
            logits, key, self.temperature, self.top_k, self.top_p))

    def _emit(self, row: int, tok: int,
              emitted: Dict[int, List[int]]) -> None:
        req = self.row_req[row]
        req.tokens.append(tok)
        emitted.setdefault(req.req_id, []).append(tok)
        self.metrics.on_token(req.req_id)
        self.row_budget[row] -= 1
        out_of_room = self.row_len[row] + 1 >= self.max_len
        if (self.row_budget[row] <= 0 or out_of_room
                or (self.eos_id is not None and tok == self.eos_id)):
            req.done = True
            self.finished.add(req.req_id)
            self.metrics.on_finish(req.req_id)
            self.row_req[row] = None
            self.row_len[row] = 0        # slot free for the next prefill
            self._next_tok[row] = 0
        else:
            self._next_tok[row] = tok
