"""Host-side index for the DecodeEngine's shared-prefix KV cache.

Serving traffic is dominated by shared prompt prefixes (system prompts,
few-shot preambles, multi-turn history): vLLM's PagedAttention and
SGLang's RadixAttention showed that REUSING the K/V of an
already-computed prefix, instead of re-running prefill over it, is the
single largest remaining throughput lever once decode itself is fused.

This module is the pure-host half of that design: a radix/trie index at
BLOCK granularity (``block_tokens`` tokens per node — only full blocks
are shareable, the vLLM rule) mapping token-sequence prefixes to slots
in a device-resident pool of cached K/V blocks. The device half — the
pool arrays themselves and the one-program gather/scatter copies in and
out of engine slot rows — lives in ``models/engine.py``
(``_prefix_copy_in`` / ``_prefix_copy_out``); this index never touches
a device buffer, so matching and eviction cost zero dispatches.

Concurrency/ordering contract with the engine (single-threaded, but
dispatch-ordered): a node is created PENDING when the engine plans to
fill its block (the owning row's prefill must first produce the K/V)
and COMMITTED once the copy-out program has been dispatched. `match`
only walks committed nodes; eviction only takes committed leaves.
Because XLA executes same-device programs in dispatch order, a block
evicted and reassigned on the host is still read with its OLD content
by any copy-in dispatched before the new owner's copy-out.

Eviction is LRU over committed leaf nodes under a byte budget (the pool
is preallocated at ``n_blocks`` = budget // block_bytes): evicting a
leaf frees exactly one block; interior nodes become leaves as their
children go, so cold chains drain tail-first while hot shared prefixes
(recent ``last_use``) survive.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.models.block_pool import BlockPool


def block_bytes(n_layers: int, block_tokens: int, kv_heads: int,
                head_dim: int, dtype_bytes: int, *,
                per_layer: bool = False) -> int:
    """Device bytes one cached block occupies (K and V).

    Two axes of "whole vs slice" used to be conflated here (flagged in
    the PR-7 docs), so both are now explicit:

    - LAYERS: a block id indexes the pool's ``n_blocks`` axis of BOTH
      pool arrays ``[L, NB, T, KV, D]``, so one block holds T tokens'
      K/V for ALL ``n_layers`` decoder layers. The default (and the
      number every byte budget must divide by) is therefore the
      layer-SUMMED figure ``2 * L * T * KV * D * dtype``;
      ``per_layer=True`` returns the single-layer slice (what one
      layer's gather touches — the microbench unit).
    - MESH: the returned figure is GLOBAL across the serving mesh. On
      a tensor-parallel engine whose KV-head axis shards over tp, each
      chip holds block_bytes/tp of it; ``prefix_cache_bytes`` /
      ``kv_pool_bytes`` therefore size the pool in global bytes at
      every tp degree (same block count, smaller per-chip slice), so
      eviction/preemption behavior — and the emitted token stream — is
      identical sharded or not.

    Pool sizing from a byte budget is exact: a budget of
    ``k * block_bytes(...)`` buys exactly k shareable blocks (the
    reserved scratch block 0 rides on top — it is part of the pool
    allocation but never holds cached data)."""
    layers = 1 if per_layer else n_layers
    return 2 * layers * block_tokens * kv_heads * head_dim * dtype_bytes


class _Node:
    __slots__ = ("key", "block_id", "parent", "children", "committed",
                 "last_use")

    def __init__(self, key: Optional[Tuple[int, ...]], block_id: int,
                 parent: Optional["_Node"]):
        self.key = key
        self.block_id = block_id
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.committed = False
        self.last_use = 0


class PrefixCacheIndex:
    """Radix index over cached prompt prefixes at block granularity.

    ``match(prompt)`` returns the pool block ids of the longest
    COMMITTED chain of full blocks prefixing ``prompt`` — capped so the
    matched length never covers the whole prompt (the engine must
    always prefill at least the final token to have last-token logits
    to sample from, the same rule vLLM applies).

    ``extend(prompt)`` walks the chain for every full block of
    ``prompt`` and creates missing nodes as PENDING, allocating pool
    blocks from the free list (evicting LRU committed leaves when it
    runs dry). The caller fills each pending node's block from the
    owning row's prefilled K/V and then calls ``commit(node)``.

    Block id 0 is RESERVED as scratch: copy programs pad their block-id
    vectors to a power of two with it so a handful of XLA compiles
    cover every chain length; garbage scattered there is never indexed.

    PAGED MODE (``pool=`` a shared BlockPool): the index no longer
    owns a private free list — blocks belong to the engine-wide
    refcounted pool that also backs every live request's block table.
    The trie holds ONE pool reference per cached block (`register`
    increfs a row's freshly filled blocks instead of copying them out;
    warm admissions incref matched blocks instead of copying them in),
    and eviction is HARDENED: only blocks whose sole remaining holder
    is the trie itself (``pool.ref(bid) == 1``) are eviction
    candidates, so a block shared with any live (or swapped-out) row
    can never be recycled under its reader — the
    refcount-never-evicted property, tested in
    tests/test_engine_paged.py.
    """

    def __init__(self, *, block_tokens: int, n_blocks: int,
                 on_evict: Optional[Callable[[int], None]] = None,
                 pool: Optional[BlockPool] = None):
        if block_tokens < 1:
            raise ValueError("block_tokens must be >= 1")
        if n_blocks < 2:
            raise ValueError(
                "n_blocks must be >= 2 (block 0 is the scratch block); "
                "raise prefix_cache_bytes or shrink prefix_block")
        self.block_tokens = block_tokens
        self.n_blocks = n_blocks
        self.pool = pool
        self._free: List[int] = ([] if pool is not None
                                 else list(range(n_blocks - 1, 0, -1)))
        self._root = _Node(None, -1, None)
        self._nodes: List[_Node] = []
        self._clock = 0
        self.evictions = 0
        self._on_evict = on_evict

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        return len(self._nodes)

    @property
    def blocks_in_use(self) -> int:
        return len(self._nodes)

    @property
    def blocks_total(self) -> int:
        return self.n_blocks - 1          # scratch block excluded

    # -- core ops ----------------------------------------------------------

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _chunk(self, prompt, j: int) -> Tuple[int, ...]:
        T = self.block_tokens
        return tuple(prompt[j * T:(j + 1) * T])

    def match(self, prompt, *, peek: bool = False,
              allow_full: bool = False) -> Tuple[List[int], bool]:
        """Longest committed full-block chain prefixing ``prompt``.

        Returns (block_ids, next_is_pending): the matched chain walks at
        most ``(len(prompt) - 1) // block_tokens`` blocks (at least one
        suffix token is always left for the engine to prefill), and
        ``next_is_pending`` reports whether the walk stopped at a node
        another row is still filling — the prefix-affinity scheduler
        defers such requests one step so they admit warm.

        ``allow_full=True`` lifts the one-suffix-token cap to
        ``len(prompt) // block_tokens`` — the PAGED engine's entry: a
        block-aligned prompt matching its whole chain shares every
        block and COPY-ON-WRITES the last one (recomputing only the
        final token inside the private copy for its logits), instead
        of recomputing a full block of suffix. The copy-in engine must
        NOT use this: it has no CoW, so writing the recomputed final
        token would land in the shared pool block.

        ``peek=True`` leaves LRU recency untouched: a pure read for
        load probes (the fleet router scores EVERY replica's trie per
        request — touching last_use from probes that lose the routing
        decision would let routing traffic evict genuinely hot blocks)."""
        node = self._root
        ids: List[int] = []
        cap = len(prompt) if allow_full else len(prompt) - 1
        max_blocks = cap // self.block_tokens
        while len(ids) < max_blocks:
            child = node.children.get(self._chunk(prompt, len(ids)))
            if child is None:
                return ids, False
            if not child.committed:
                return ids, True
            if not peek:
                child.last_use = self._tick()
            ids.append(child.block_id)
            node = child
        return ids, False

    def extend(self, prompt) -> List[Tuple[int, "_Node"]]:
        """Ensure a (possibly pending) node chain exists for every full
        block of ``prompt``; returns ``[(block_index, node), ...]`` for
        the nodes CREATED by this call — always a consecutive tail of
        the chain — which the caller must fill and ``commit``. Stops
        early (shorter list) if the pool runs dry even after LRU
        eviction; the uncached tail simply isn't shared."""
        node = self._root
        created: List[Tuple[int, _Node]] = []
        protect = {id(self._root)}
        for j in range(len(prompt) // self.block_tokens):
            key = self._chunk(prompt, j)
            child = node.children.get(key)
            if child is None:
                bid = self._alloc(protect)
                if bid is None:
                    break
                child = _Node(key, bid, node)
                node.children[key] = child
                self._nodes.append(child)
                created.append((j, child))
            child.last_use = self._tick()
            protect.add(id(child))
            node = child
        return created

    def register(self, prompt, block_ids: List[int]
                 ) -> List[Tuple[int, "_Node"]]:
        """Paged-mode twin of `extend`: bind the chain for every full
        block of ``prompt`` to the caller's OWN pool blocks
        (``block_ids[j]`` backs chain position j) instead of
        allocating fresh ones — the row that is about to prefill those
        blocks donates a share, so publication is zero-copy: the trie
        increfs each newly registered block and there is nothing to
        copy out when the prefill lands. Positions already in the trie
        are left untouched (their existing block holds identical
        content; the caller keeps its own reference to its own block).
        Returns the nodes CREATED — pending until the caller's prefill
        frontier covers them and it calls ``commit``."""
        if self.pool is None:
            raise ValueError("register() requires a pool-backed index "
                             "(pass pool= at construction)")
        node = self._root
        created: List[Tuple[int, _Node]] = []
        for j in range(len(prompt) // self.block_tokens):
            if j >= len(block_ids):
                break
            key = self._chunk(prompt, j)
            child = node.children.get(key)
            if child is None:
                self.pool.incref([block_ids[j]])
                child = _Node(key, block_ids[j], node)
                node.children[key] = child
                self._nodes.append(child)
                created.append((j, child))
            child.last_use = self._tick()
            node = child
        return created

    def commit(self, node: "_Node") -> None:
        """Mark a pending node's block as filled (copy-out dispatched)."""
        node.committed = True
        node.last_use = self._tick()

    # -- allocation / eviction ---------------------------------------------

    def _evictable(self, n: "_Node", protect) -> bool:
        """Eviction candidacy, HARDENED for the refcounted pool: a
        victim must be a committed childless leaf outside the caller's
        protected chain AND — when pool-backed — a block whose only
        remaining holder is the trie itself. A refcount above 1 means
        a live row's block table (or a swapped-out request) still
        reads the block; recycling it would corrupt that reader, so
        such blocks are simply not candidates until their last sharer
        releases them."""
        if n.children or not n.committed or id(n) in protect:
            return False
        if self.pool is not None and self.pool.ref(n.block_id) != 1:
            return False
        return True

    def _evict_victim(self, protect) -> Optional[int]:
        """Evict the LRU evictable leaf; returns its block id (with
        the trie's reference DROPPED in pool mode — the block is free
        unless someone else still holds it) or None."""
        victim = None
        for n in self._nodes:
            if not self._evictable(n, protect):
                continue
            if victim is None or n.last_use < victim.last_use:
                victim = n
        if victim is None:
            return None
        victim.parent.children.pop(victim.key, None)
        self._nodes.remove(victim)
        self.evictions += 1
        if self._on_evict is not None:
            self._on_evict(1)
        if self.pool is not None:
            self.pool.decref([victim.block_id])
        return victim.block_id

    def evict_one(self) -> bool:
        """Release one cold cached block back to the shared pool
        (paged engines call this when `BlockPool.alloc` runs dry —
        cold cache always gives way before any live request is
        preempted). Returns False when nothing is evictable."""
        return self._evict_victim({id(self._root)}) is not None

    def evictable_blocks(self) -> int:
        """How many cached blocks COULD be released by repeated
        `evict_one` calls (the engine's admission gate counts these as
        available capacity; the fleet router scores replicas on free +
        evictable).

        This is the CASCADE fixpoint, not just the current leaves:
        evicting a childless leaf makes its parent childless, so a
        whole cold chain is reclaimable even though only its tail is
        evictable right now. Counting only the instantaneous leaves
        under-reports capacity and livelocks the paged engine's
        admission gate — `_fits_now` says a swapped-out request can
        never fit while `_pool_alloc`'s evict loop would in fact free
        the chain (regression-tested by the tight-pool churn in
        `_bench_paged`). A node is reclaimable iff it is committed,
        the trie holds its only reference, and EVERY descendant is
        reclaimable too (a shared or pending descendant pins the whole
        path to the root above it)."""
        def reclaimable(n) -> bool:
            if not n.committed:
                return False
            if self.pool is not None and self.pool.ref(n.block_id) != 1:
                return False
            return all(reclaimable(c) for c in n.children.values())

        return sum(sum(1 for _ in self._subtree_if(n, reclaimable))
                   for n in self._root.children.values())

    def _subtree_if(self, node, pred):
        """Yield `node`'s whole subtree when `pred(node)` holds (the
        cascade reclaims subtrees from the root down: an unreclaimable
        ancestor keeps its reclaimable descendants pinned only until
        the ancestor itself is evicted, which cannot happen while it
        has children — so reclaimability is decided at the subtree
        root)."""
        if not pred(node):
            for c in node.children.values():
                yield from self._subtree_if(c, pred)
            return
        stack = [node]
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children.values())

    def _alloc(self, protect) -> Optional[int]:
        if self.pool is not None:
            ids = self.pool.alloc(1)
            if ids is not None:
                return ids[0]
            return self._evict_victim_realloc(protect)
        if self._free:
            return self._free.pop()
        return self._evict_victim(protect)

    def _evict_victim_realloc(self, protect) -> Optional[int]:
        """Pool-mode retry: evict one cold block, then re-alloc from
        the pool (the evicted block is only actually free if the trie
        was its last holder — `_evictable` guarantees it was)."""
        if self._evict_victim(protect) is None:
            return None
        ids = self.pool.alloc(1)
        return None if ids is None else ids[0]
