"""Host-side index for the DecodeEngine's shared-prefix KV cache.

Serving traffic is dominated by shared prompt prefixes (system prompts,
few-shot preambles, multi-turn history): vLLM's PagedAttention and
SGLang's RadixAttention showed that REUSING the K/V of an
already-computed prefix, instead of re-running prefill over it, is the
single largest remaining throughput lever once decode itself is fused.

This module is the pure-host half of that design: a radix/trie index at
BLOCK granularity (``block_tokens`` tokens per node — only full blocks
are shareable, the vLLM rule) mapping token-sequence prefixes to slots
in a device-resident pool of cached K/V blocks. The device half — the
pool arrays themselves and the one-program gather/scatter copies in and
out of engine slot rows — lives in ``models/engine.py``
(``_prefix_copy_in`` / ``_prefix_copy_out``); this index never touches
a device buffer, so matching and eviction cost zero dispatches.

Concurrency/ordering contract with the engine (single-threaded, but
dispatch-ordered): a node is created PENDING when the engine plans to
fill its block (the owning row's prefill must first produce the K/V)
and COMMITTED once the copy-out program has been dispatched. `match`
only walks committed nodes; eviction only takes committed leaves.
Because XLA executes same-device programs in dispatch order, a block
evicted and reassigned on the host is still read with its OLD content
by any copy-in dispatched before the new owner's copy-out.

Eviction is LRU over committed leaf nodes under a byte budget (the pool
is preallocated at ``n_blocks`` = budget // block_bytes): evicting a
leaf frees exactly one block; interior nodes become leaves as their
children go, so cold chains drain tail-first while hot shared prefixes
(recent ``last_use``) survive.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple


def block_bytes(n_layers: int, block_tokens: int, kv_heads: int,
                head_dim: int, dtype_bytes: int) -> int:
    """Device bytes one cached block occupies (K and V) — the GLOBAL
    footprint across the serving mesh. On a tensor-parallel engine
    whose KV-head axis shards over tp, each chip holds block_bytes/tp
    of it; ``prefix_cache_bytes`` therefore sizes the pool in global
    bytes at every tp degree (same block count, smaller per-chip
    slice), so eviction behavior — and the emitted token stream — is
    identical sharded or not."""
    return 2 * n_layers * block_tokens * kv_heads * head_dim * dtype_bytes


class _Node:
    __slots__ = ("key", "block_id", "parent", "children", "committed",
                 "last_use")

    def __init__(self, key: Optional[Tuple[int, ...]], block_id: int,
                 parent: Optional["_Node"]):
        self.key = key
        self.block_id = block_id
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.committed = False
        self.last_use = 0


class PrefixCacheIndex:
    """Radix index over cached prompt prefixes at block granularity.

    ``match(prompt)`` returns the pool block ids of the longest
    COMMITTED chain of full blocks prefixing ``prompt`` — capped so the
    matched length never covers the whole prompt (the engine must
    always prefill at least the final token to have last-token logits
    to sample from, the same rule vLLM applies).

    ``extend(prompt)`` walks the chain for every full block of
    ``prompt`` and creates missing nodes as PENDING, allocating pool
    blocks from the free list (evicting LRU committed leaves when it
    runs dry). The caller fills each pending node's block from the
    owning row's prefilled K/V and then calls ``commit(node)``.

    Block id 0 is RESERVED as scratch: copy programs pad their block-id
    vectors to a power of two with it so a handful of XLA compiles
    cover every chain length; garbage scattered there is never indexed.
    """

    def __init__(self, *, block_tokens: int, n_blocks: int,
                 on_evict: Optional[Callable[[int], None]] = None):
        if block_tokens < 1:
            raise ValueError("block_tokens must be >= 1")
        if n_blocks < 2:
            raise ValueError(
                "n_blocks must be >= 2 (block 0 is the scratch block); "
                "raise prefix_cache_bytes or shrink prefix_block")
        self.block_tokens = block_tokens
        self.n_blocks = n_blocks
        self._free: List[int] = list(range(n_blocks - 1, 0, -1))
        self._root = _Node(None, -1, None)
        self._nodes: List[_Node] = []
        self._clock = 0
        self.evictions = 0
        self._on_evict = on_evict

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        return len(self._nodes)

    @property
    def blocks_in_use(self) -> int:
        return len(self._nodes)

    @property
    def blocks_total(self) -> int:
        return self.n_blocks - 1          # scratch block excluded

    # -- core ops ----------------------------------------------------------

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _chunk(self, prompt, j: int) -> Tuple[int, ...]:
        T = self.block_tokens
        return tuple(prompt[j * T:(j + 1) * T])

    def match(self, prompt, *, peek: bool = False) -> Tuple[List[int], bool]:
        """Longest committed full-block chain prefixing ``prompt``.

        Returns (block_ids, next_is_pending): the matched chain walks at
        most ``(len(prompt) - 1) // block_tokens`` blocks (at least one
        suffix token is always left for the engine to prefill), and
        ``next_is_pending`` reports whether the walk stopped at a node
        another row is still filling — the prefix-affinity scheduler
        defers such requests one step so they admit warm.

        ``peek=True`` leaves LRU recency untouched: a pure read for
        load probes (the fleet router scores EVERY replica's trie per
        request — touching last_use from probes that lose the routing
        decision would let routing traffic evict genuinely hot blocks)."""
        node = self._root
        ids: List[int] = []
        max_blocks = (len(prompt) - 1) // self.block_tokens
        while len(ids) < max_blocks:
            child = node.children.get(self._chunk(prompt, len(ids)))
            if child is None:
                return ids, False
            if not child.committed:
                return ids, True
            if not peek:
                child.last_use = self._tick()
            ids.append(child.block_id)
            node = child
        return ids, False

    def extend(self, prompt) -> List[Tuple[int, "_Node"]]:
        """Ensure a (possibly pending) node chain exists for every full
        block of ``prompt``; returns ``[(block_index, node), ...]`` for
        the nodes CREATED by this call — always a consecutive tail of
        the chain — which the caller must fill and ``commit``. Stops
        early (shorter list) if the pool runs dry even after LRU
        eviction; the uncached tail simply isn't shared."""
        node = self._root
        created: List[Tuple[int, _Node]] = []
        protect = {id(self._root)}
        for j in range(len(prompt) // self.block_tokens):
            key = self._chunk(prompt, j)
            child = node.children.get(key)
            if child is None:
                bid = self._alloc(protect)
                if bid is None:
                    break
                child = _Node(key, bid, node)
                node.children[key] = child
                self._nodes.append(child)
                created.append((j, child))
            child.last_use = self._tick()
            protect.add(id(child))
            node = child
        return created

    def commit(self, node: "_Node") -> None:
        """Mark a pending node's block as filled (copy-out dispatched)."""
        node.committed = True
        node.last_use = self._tick()

    # -- allocation / eviction ---------------------------------------------

    def _alloc(self, protect) -> Optional[int]:
        if self._free:
            return self._free.pop()
        victim = None
        for n in self._nodes:
            if n.children or not n.committed or id(n) in protect:
                continue
            if victim is None or n.last_use < victim.last_use:
                victim = n
        if victim is None:
            return None
        victim.parent.children.pop(victim.key, None)
        self._nodes.remove(victim)
        self.evictions += 1
        if self._on_evict is not None:
            self._on_evict(1)
        return victim.block_id
