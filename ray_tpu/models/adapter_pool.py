"""HBM adapter residency for multi-LoRA serving (S-LoRA / Punica style).

The fleet's economics problem: thousands of per-customer fine-tunes
over ONE base model, but a naive deployment needs one replica per
adapter because the engine can only run one weight set. The fix is to
keep the base matmul shared and apply per-row low-rank deltas — which
turns adapter weights into CACHE STATE: a small working set lives in
HBM, stacked along a slot axis so a single gather serves every row of
a heterogeneous batch, and everything else stays on the host until
traffic warms it.

This module owns that residency:

- Device stacks ``{name: {"a": [L, A, n_in, r], "b": [L, A, r,
  n_out]}}`` with A = max_live_adapters + 1. Slot 0 is the NULL
  adapter (all zeros — base-only rows gather an exactly-zero delta,
  so one fused program serves mixed adapter/base batches with no
  branching). The b-stacks are pre-scaled by ``alpha/rank`` at
  registration so the decode path pays no per-step multiply.
- Sharding: stacks go through `lora_stack_specs` under the SAME
  pruned rule table as the engine's base weights, so adapters degrade
  to replicated exactly when the base axis does.
- Residency: LRU over refcount-0 residents. A slot acquired by a live
  row (`alloc`/`incref`) is pinned — it can never be an eviction
  victim until every holder `decref`s. This is the paged-KV block
  discipline applied to adapter slots, and graftlint's kv-refcount
  ownership rule audits call sites the same way.
- Prefetch: cold adapters stage host→device with an ASYNC
  `jax.device_put` (the swap ledger's transfer idiom — enqueue, don't
  block) and commit into a slot on a later `drain_prefetches` call via
  one jitted donated scatter (`_adapter_commit`, slot index traced so
  every slot shares one compile). The scheduler defers the requester
  meanwhile instead of stalling the step.

Telemetry flows through the engine's metrics plane
(``llm_engine_adapter_*``, see engine_metrics.py) and the request
tracer ("adapter_prefetch" / "adapter_evict" instants).
"""

from __future__ import annotations

import collections
import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models.lora import (LoraConfig, _in_out_split,
                                 lora_stack_specs)
from ray_tpu.models.llama import LlamaConfig, _layer_shapes

Params = Dict[str, Any]


@functools.partial(jax.jit, static_argnames=("shardings",),
                   donate_argnames=("stacks",))
def _adapter_commit(stacks, staged, slot, shardings=None):
    """Scatter one staged adapter into stack slot ``slot`` (traced
    scalar — one XLA program covers every slot; a static slot would
    retrace per slot and trip the armed sanitizer on adapter churn).
    ``stacks`` is donated: the pool holds the only reference, and a
    copy of the full [L, A, ...] buffers per commit would dwarf the
    adapter itself."""
    sh = dict(shardings) if shardings is not None else {}
    out = {}
    for name in sorted(stacks):
        ab = stacks[name]
        a = ab["a"].at[:, slot].set(staged[name]["a"].astype(ab["a"].dtype))
        b = ab["b"].at[:, slot].set(staged[name]["b"].astype(ab["b"].dtype))
        if name in sh:
            a = jax.lax.with_sharding_constraint(a, sh[name][0])
            b = jax.lax.with_sharding_constraint(b, sh[name][1])
        out[name] = {"a": a, "b": b}
    return out


class AdapterPool:
    """LRU residency manager for stacked LoRA adapters in HBM.

    Ownership contract (mirrors block_pool.py's KV blocks): `alloc`
    returns a slot with one reference taken; the holder must `decref`
    exactly once (row retirement, preemption, halt) or hand the slot
    to another owner. `incref` adds holders. Slot 0 (null adapter) is
    refcount-exempt: it is never evicted and never freed.
    """

    def __init__(self, cfg: LlamaConfig, lora_cfg: LoraConfig, *,
                 max_live_adapters: int = 4,
                 mesh: Optional[Mesh] = None,
                 rules=None, metrics=None, trace=None):
        if max_live_adapters < 1:
            raise ValueError(
                f"max_live_adapters must be >= 1, got {max_live_adapters}")
        self.cfg = cfg
        self.lora_cfg = lora_cfg
        self.max_live_adapters = max_live_adapters
        self.n_slots = max_live_adapters + 1    # + slot 0 = null adapter
        self.mesh = mesh
        self.metrics = metrics
        self.trace = trace

        shapes = _layer_shapes(cfg)
        self._dims: Dict[str, Tuple[int, int]] = {}
        for name in lora_cfg.targets:
            shape, _logical, fan_in = shapes[name]
            self._dims[name] = _in_out_split(shape, fan_in)

        dt = cfg.param_dtype
        self._np_dtype = np.dtype(jnp.zeros((), dt).dtype)
        stacks: Params = {}
        for name, (n_in, n_out) in self._dims.items():
            stacks[name] = {
                "a": jnp.zeros((cfg.n_layers, self.n_slots, n_in,
                                lora_cfg.rank), dt),
                "b": jnp.zeros((cfg.n_layers, self.n_slots,
                                lora_cfg.rank, n_out), dt),
            }
        self._commit_shardings = None
        self._staged_sh: Optional[Dict[str, Tuple]] = None
        if mesh is not None:
            specs = lora_stack_specs(cfg, lora_cfg, rules)
            stacks = {
                name: {k: jax.device_put(
                    v, NamedSharding(mesh, specs[name][k]))
                    for k, v in ab.items()}
                for name, ab in stacks.items()}
            # Static tuple for the jitted commit's output constraint,
            # plus per-adapter staging shardings (stack spec minus the
            # slot axis) so the async device_put lands pre-sharded.
            self._commit_shardings = tuple(
                (name, (NamedSharding(mesh, specs[name]["a"]),
                        NamedSharding(mesh, specs[name]["b"])))
                for name in sorted(self._dims))
            self._staged_sh = {
                name: (NamedSharding(mesh, P(specs[name]["a"][0],
                                             specs[name]["a"][2],
                                             specs[name]["a"][3])),
                       NamedSharding(mesh, P(specs[name]["b"][0],
                                             specs[name]["b"][2],
                                             specs[name]["b"][3])))
                for name in self._dims}
        self.stacks = stacks

        # Host-side ledger. _registry holds pre-scaled host copies (the
        # "disk tier"); _slot_of/_slot_aid map residency; _refs pins;
        # _lru orders refcount-0 residents for eviction; _fetching holds
        # in-flight async host->device stages.
        self._registry: Dict[str, Params] = {}
        self._slot_of: Dict[str, int] = {}
        self._slot_aid: List[Optional[str]] = [None] * self.n_slots
        self._refs = [0] * self.n_slots
        self._free: List[int] = list(range(self.n_slots - 1, 0, -1))
        self._lru: "collections.OrderedDict[str, int]" = \
            collections.OrderedDict()
        self._fetching: Dict[str, Params] = {}
        self._doomed: set = set()

        self.lookups = 0
        self.hits = 0
        self.prefetches = 0
        self.evictions = 0

    # -- registration ------------------------------------------------------

    def register(self, adapter_id: str, lora: Params) -> None:
        """Admit an adapter's weights to the host tier. ``lora`` is a
        `lora_init`-shaped tree ({"layers": {name: {"a","b"}}}); the
        b factors are pre-scaled by alpha/rank here so decode gathers
        need no scale multiply. Host copies only — HBM is touched by
        `prefetch`, not registration."""
        if not adapter_id:
            raise ValueError("adapter_id must be a non-empty string")
        layers = lora.get("layers", lora)
        missing = set(self._dims) - set(layers)
        if missing:
            raise ValueError(
                f"adapter {adapter_id!r} missing targets {sorted(missing)} "
                f"(pool targets: {sorted(self._dims)})")
        host: Params = {}
        scale = self.lora_cfg.scale
        for name, (n_in, n_out) in self._dims.items():
            a = np.asarray(layers[name]["a"], np.float32)
            b = np.asarray(layers[name]["b"], np.float32)
            want_a = (self.cfg.n_layers, n_in, self.lora_cfg.rank)
            want_b = (self.cfg.n_layers, self.lora_cfg.rank, n_out)
            if a.shape != want_a or b.shape != want_b:
                raise ValueError(
                    f"adapter {adapter_id!r} {name}: shapes "
                    f"{a.shape}/{b.shape}, want {want_a}/{want_b}")
            host[name] = {"a": a.astype(self._np_dtype),
                          "b": (b * scale).astype(self._np_dtype)}
        self._registry[adapter_id] = host
        self._doomed.discard(adapter_id)

    def unregister(self, adapter_id: str) -> bool:
        """Drop an adapter. If it is pinned by live rows the removal is
        DEFERRED until the last decref (returns False); otherwise it
        leaves the registry — and its slot, if resident — immediately
        (returns True). Stale stack bytes in a freed slot are
        unreachable: no row holds its index and the next commit
        overwrites it."""
        if adapter_id not in self._registry:
            return True
        slot = self._slot_of.get(adapter_id)
        if slot is not None and self._refs[slot] > 0:
            self._doomed.add(adapter_id)
            return False
        self._fetching.pop(adapter_id, None)
        if slot is not None:
            self._release_slot(adapter_id, slot)
        del self._registry[adapter_id]
        self._doomed.discard(adapter_id)
        return True

    def registered(self, adapter_id: str) -> bool:
        return adapter_id in self._registry

    def adapter_ids(self) -> List[str]:
        return sorted(self._registry)

    # -- residency queries -------------------------------------------------

    def resident(self, adapter_id: Optional[str]) -> bool:
        return adapter_id is None or adapter_id in self._slot_of

    def fetching(self, adapter_id: str) -> bool:
        return adapter_id in self._fetching

    # -- slot ownership (kv-refcount discipline) ---------------------------

    def alloc(self, adapter_id: Optional[str]) -> Optional[int]:
        """Acquire a slot for one row. None adapter -> slot 0 (no
        reference taken; the null slot is permanent). A resident
        adapter returns its slot with one reference added (pinning it
        against eviction); a cold adapter returns None — call
        `prefetch` and retry after `drain_prefetches` commits."""
        if adapter_id is None:
            return 0
        if adapter_id not in self._registry:
            raise KeyError(f"unknown adapter_id {adapter_id!r}")
        self.lookups += 1
        slot = self._slot_of.get(adapter_id)
        hit = slot is not None
        if self.metrics is not None:
            self.metrics.on_adapter_lookup(hit)
        if not hit:
            return None
        self.hits += 1
        self._lru.pop(adapter_id, None)
        self._refs[slot] += 1
        return slot

    def incref(self, slot: int) -> None:
        if slot == 0:
            return
        aid = self._slot_aid[slot]
        if aid is None:
            raise ValueError(f"incref on unowned slot {slot}")
        self._lru.pop(aid, None)
        self._refs[slot] += 1

    def decref(self, slot: int) -> None:
        if slot == 0:
            return
        aid = self._slot_aid[slot]
        if aid is None or self._refs[slot] <= 0:
            raise ValueError(f"decref on unheld slot {slot}")
        self._refs[slot] -= 1
        if self._refs[slot] == 0:
            if aid in self._doomed:
                self._release_slot(aid, slot)
                self._registry.pop(aid, None)
                self._doomed.discard(aid)
            else:
                self._lru[aid] = slot       # newest eviction candidate

    def _release_slot(self, adapter_id: str, slot: int) -> None:
        self._slot_of.pop(adapter_id, None)
        self._lru.pop(adapter_id, None)
        self._slot_aid[slot] = None
        self._refs[slot] = 0
        self._free.append(slot)

    # -- prefetch / commit -------------------------------------------------

    def prefetch(self, adapter_id: str) -> bool:
        """Begin warming a cold adapter: enqueue its host tree on an
        async host->device transfer. Non-blocking — the commit into a
        stack slot happens at the next `drain_prefetches`. Returns
        True if the adapter is already resident (nothing to do)."""
        if adapter_id in self._slot_of:
            return True
        if adapter_id not in self._registry:
            raise KeyError(f"unknown adapter_id {adapter_id!r}")
        if adapter_id in self._fetching:
            return False
        host = self._registry[adapter_id]
        if self._staged_sh is not None:
            staged = {name: {
                "a": jax.device_put(ab["a"], self._staged_sh[name][0]),
                "b": jax.device_put(ab["b"], self._staged_sh[name][1])}
                for name, ab in host.items()}
        else:
            staged = {name: {"a": jax.device_put(ab["a"]),
                             "b": jax.device_put(ab["b"])}
                      for name, ab in host.items()}
        self._fetching[adapter_id] = staged
        self.prefetches += 1
        if self.metrics is not None:
            self.metrics.on_adapter_prefetch()
        if self.trace is not None and self.trace.enabled:
            self.trace.instant("adapter_prefetch", lane="events",
                               args={"adapter_id": adapter_id})
        return False

    def drain_prefetches(self) -> int:
        """Commit every staged adapter that can get a slot (free slot
        first, else the LRU refcount-0 resident is evicted). Staged
        adapters left slotless — every slot pinned — stay in flight
        and retry next drain. Returns the number committed."""
        if not self._fetching:
            return 0
        committed = 0
        for aid in list(self._fetching):
            slot = self._take_slot()
            if slot is None:
                break                       # every slot pinned
            staged = self._fetching.pop(aid)
            self.stacks = _adapter_commit(
                self.stacks, staged, jnp.int32(slot),
                shardings=self._commit_shardings)
            self._slot_of[aid] = slot
            self._slot_aid[slot] = aid
            self._refs[slot] = 0
            self._lru[aid] = slot
            committed += 1
        if committed and self.metrics is not None:
            self.metrics.on_adapter_slots(self.n_slots - 1,
                                          len(self._slot_of),
                                          self.pinned_slots())
        return committed

    def _take_slot(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        if not self._lru:
            return None
        victim_aid, slot = self._lru.popitem(last=False)   # coldest
        del self._slot_of[victim_aid]
        self._slot_aid[slot] = None
        self.evictions += 1
        if self.metrics is not None:
            self.metrics.on_adapter_evict()
        if self.trace is not None and self.trace.enabled:
            self.trace.instant("adapter_evict", lane="events",
                               args={"adapter_id": victim_aid})
        return slot

    # -- introspection -----------------------------------------------------

    def pinned_slots(self) -> int:
        return sum(1 for r in self._refs[1:] if r > 0)

    def stats(self) -> Dict[str, float]:
        return {
            "adapters_registered": float(len(self._registry)),
            "adapter_slots": float(self.n_slots - 1),
            "adapter_slots_resident": float(len(self._slot_of)),
            "adapter_slots_pinned": float(self.pinned_slots()),
            "adapter_lookups": float(self.lookups),
            "adapter_hits": float(self.hits),
            "adapter_hit_rate": (self.hits / self.lookups
                                 if self.lookups else 0.0),
            "adapter_prefetches": float(self.prefetches),
            "adapter_evictions": float(self.evictions),
        }
