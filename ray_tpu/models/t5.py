"""Encoder-decoder (T5-class) seq2seq family, TPU-first.

Same design language as the Llama flagship (models/llama.py): pure
functional params, scanned layer stacks (`lax.scan` — O(1) compile in
depth), pre-RMSNorm, gated MLP, logical-axis trees driving GSPMD
sharding over the dp/fsdp/tp mesh, bf16 activations / f32 master
params, per-layer remat. Architectural choices vs classic T5, made for
the MXU rather than copied: RoPE on the self-attention paths (no
learned relative-position bias tables — rotation fuses into the
attention matmuls), cross-attention position-free, weight-tied LM head.

Reference capability: the reference trains seq2seq models through Ray
Train as opaque torch modules (python/ray/train/torch/,
huggingface/transformers/); here the encoder-decoder family is a
first-class GSPMD citizen sharing `make_sharded_train_step` with the
other in-tree families.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import _rmsnorm, _rope
from ray_tpu.ops import attention
from ray_tpu.parallel.sharding import LogicalAxisRules, logical_to_mesh

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32000
    dim: int = 512
    n_layers: int = 6           # per stack (encoder AND decoder)
    n_heads: int = 8
    ffn_dim: int = 1024
    norm_eps: float = 1e-6
    rope_theta: float = 10_000.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    pad_id: int = 0

    def __post_init__(self):
        if self.dim % self.n_heads:
            raise ValueError("n_heads must divide dim")

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def nano(**kw) -> "T5Config":
        base = dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                    ffn_dim=128)
        base.update(kw)
        return T5Config(**base)

    def num_params(self) -> int:
        d, f, h, k = self.dim, self.ffn_dim, self.n_heads, self.head_dim
        attn = d * h * k * 4          # wq wk wv (d,h,k) + wo (h,k,d)
        mlp = 3 * d * f               # gate/up/down
        enc_layer = attn + mlp + 2 * d              # 2 norms
        dec_layer = 2 * attn + mlp + 3 * d          # self+cross, 3 norms
        return (self.vocab_size * d +               # tied embed/head
                self.n_layers * (enc_layer + dec_layer) + 2 * d)


def _attn_shapes(cfg: T5Config, prefix: str) -> Dict[str, Any]:
    d, h, k = cfg.dim, cfg.n_heads, cfg.head_dim
    return {
        f"{prefix}_norm": ((d,), ("embed",), None),
        f"{prefix}_wq": ((d, h, k), ("embed", "heads", "kv"), d),
        f"{prefix}_wk": ((d, h, k), ("embed", "heads", "kv"), d),
        f"{prefix}_wv": ((d, h, k), ("embed", "heads", "kv"), d),
        f"{prefix}_wo": ((h, k, d), ("heads", "kv", "embed"), h * k),
    }


def _mlp_shapes(cfg: T5Config) -> Dict[str, Any]:
    d, f = cfg.dim, cfg.ffn_dim
    return {
        "mlp_norm": ((d,), ("embed",), None),
        "w_gate": ((d, f), ("embed", "mlp"), d),
        "w_up": ((d, f), ("embed", "mlp"), d),
        "w_down": ((f, d), ("mlp", "embed"), f),
    }


def _enc_shapes(cfg: T5Config) -> Dict[str, Any]:
    return {**_attn_shapes(cfg, "attn"), **_mlp_shapes(cfg)}


def _dec_shapes(cfg: T5Config) -> Dict[str, Any]:
    return {**_attn_shapes(cfg, "self"), **_attn_shapes(cfg, "cross"),
            **_mlp_shapes(cfg)}


def _init_stack(rng: jax.Array, cfg: T5Config,
                shapes: Dict[str, Any]) -> Params:
    keys = jax.random.split(rng, len(shapes))
    out = {}
    for key, (name, (shape, _, fan_in)) in zip(keys, shapes.items()):
        full = (cfg.n_layers,) + shape
        if fan_in is None:
            out[name] = jnp.ones(full, cfg.param_dtype)
        else:
            out[name] = (jax.random.normal(key, full) *
                         fan_in ** -0.5).astype(cfg.param_dtype)
    return out


def t5_init(rng: jax.Array, cfg: T5Config) -> Params:
    k_embed, k_enc, k_dec = jax.random.split(rng, 3)
    return {
        "embed": (jax.random.normal(
            k_embed, (cfg.vocab_size, cfg.dim)) * cfg.dim ** -0.5
            ).astype(cfg.param_dtype),
        "encoder": _init_stack(k_enc, cfg, _enc_shapes(cfg)),
        "decoder": _init_stack(k_dec, cfg, _dec_shapes(cfg)),
        "enc_final_norm": jnp.ones((cfg.dim,), cfg.param_dtype),
        "dec_final_norm": jnp.ones((cfg.dim,), cfg.param_dtype),
    }


def t5_logical_specs(cfg: T5Config) -> Params:
    def stack(shapes):
        return {name: ("layers",) + logical
                for name, (_, logical, _f) in shapes.items()}

    return {
        "embed": ("vocab", "embed"),
        "encoder": stack(_enc_shapes(cfg)),
        "decoder": stack(_dec_shapes(cfg)),
        "enc_final_norm": ("embed",),
        "dec_final_norm": ("embed",),
    }


def t5_param_specs(cfg: T5Config,
                   rules: Optional[LogicalAxisRules] = None) -> Params:
    return jax.tree_util.tree_map(
        lambda logical: logical_to_mesh(logical, rules),
        t5_logical_specs(cfg),
        is_leaf=lambda x: isinstance(x, tuple) and all(
            isinstance(e, (str, type(None))) for e in x))


def _proj(x, w, dt):
    return jnp.einsum("bsd,dhk->bshk", x, w.astype(dt))


def _self_attention(x, layer, prefix, positions, cfg: T5Config,
                    causal: bool):
    dt = cfg.dtype
    q = _rope(_proj(x, layer[f"{prefix}_wq"], dt), positions,
              cfg.rope_theta)
    k = _rope(_proj(x, layer[f"{prefix}_wk"], dt), positions,
              cfg.rope_theta)
    v = _proj(x, layer[f"{prefix}_wv"], dt)
    o = attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                  v.transpose(0, 2, 1, 3), causal=causal)
    return jnp.einsum("bshk,hkd->bsd", o.transpose(0, 2, 1, 3),
                      layer[f"{prefix}_wo"].astype(dt))


def _cross_attention(x, memory, layer, cfg: T5Config):
    dt = cfg.dtype
    q = _proj(x, layer["cross_wq"], dt)
    k = _proj(memory, layer["cross_wk"], dt)
    v = _proj(memory, layer["cross_wv"], dt)
    o = attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                  v.transpose(0, 2, 1, 3), causal=False)
    return jnp.einsum("bshk,hkd->bsd", o.transpose(0, 2, 1, 3),
                      layer["cross_wo"].astype(dt))


def _mlp(x, layer, cfg: T5Config):
    dt = cfg.dtype
    gate = jnp.einsum("bsd,df->bsf", x, layer["w_gate"].astype(dt))
    up = jnp.einsum("bsd,df->bsf", x, layer["w_up"].astype(dt))
    return jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up,
                      layer["w_down"].astype(dt))


def _encoder_layer(h, layer, positions, cfg: T5Config):
    x = _rmsnorm(h, layer["attn_norm"], cfg.norm_eps)
    h = h + _self_attention(x, layer, "attn", positions, cfg,
                            causal=False)
    x = _rmsnorm(h, layer["mlp_norm"], cfg.norm_eps)
    return h + _mlp(x, layer, cfg)


def _decoder_layer(h, layer, memory, positions, cfg: T5Config):
    x = _rmsnorm(h, layer["self_norm"], cfg.norm_eps)
    h = h + _self_attention(x, layer, "self", positions, cfg,
                            causal=True)
    x = _rmsnorm(h, layer["cross_norm"], cfg.norm_eps)
    h = h + _cross_attention(x, memory, layer, cfg)
    x = _rmsnorm(h, layer["mlp_norm"], cfg.norm_eps)
    return h + _mlp(x, layer, cfg)


def t5_encode(params: Params, src_tokens: jax.Array,
              cfg: T5Config) -> jax.Array:
    """src_tokens [B, S] int32 -> memory [B, S, dim] (activations dtype)."""
    B, S = src_tokens.shape
    h = params["embed"].astype(cfg.dtype)[src_tokens]
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))

    def body(carry, layer):
        fn = _encoder_layer
        if cfg.remat:
            fn = jax.checkpoint(fn, static_argnums=(3,))
        return fn(carry, layer, positions, cfg), None

    h, _ = jax.lax.scan(body, h, params["encoder"])
    return _rmsnorm(h, params["enc_final_norm"], cfg.norm_eps)


def t5_decode(params: Params, memory: jax.Array, tgt_tokens: jax.Array,
              cfg: T5Config) -> jax.Array:
    """memory [B, S, d] + tgt_tokens [B, T] -> logits [B, T, vocab]."""
    B, T = tgt_tokens.shape
    h = params["embed"].astype(cfg.dtype)[tgt_tokens]
    positions = jnp.broadcast_to(jnp.arange(T), (B, T))

    def body(carry, layer):
        fn = _decoder_layer
        if cfg.remat:
            fn = jax.checkpoint(fn, static_argnums=(4,))
        return fn(carry, layer, memory, positions, cfg), None

    h, _ = jax.lax.scan(body, h, params["decoder"])
    h = _rmsnorm(h, params["dec_final_norm"], cfg.norm_eps)
    # Weight-tied head (embed^T), f32 logits like the LM flagship.
    return jnp.einsum("btd,vd->btv", h,
                      params["embed"].astype(h.dtype)
                      ).astype(jnp.float32)


def t5_forward(params: Params, src_tokens: jax.Array,
               tgt_tokens: jax.Array, cfg: T5Config) -> jax.Array:
    return t5_decode(params, t5_encode(params, src_tokens, cfg),
                     tgt_tokens, cfg)


# ------------------------------------------------------------- generation
def _cached_self_attention(q, k_cache, v_cache, slot, cfg: T5Config):
    """q [B, 1, H, D] over decoder cache slots <= slot."""
    B, S, H, D = q.shape
    max_len = k_cache.shape[1]
    logits = jnp.einsum("bshd,bthd->bhst", q, k_cache,
                        preferred_element_type=jnp.float32)
    logits = logits * (D ** -0.5)
    slots = jnp.arange(max_len)
    mask = slots[None, None, None, :] <= slot
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bhst,bthd->bshd", probs, v_cache,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _memory_attention(q, mem_k, mem_v, src_live, cfg: T5Config):
    """Cross-attention of q [B, 1, H, D] over precomputed memory K/V
    [B, S, H, D]; src_live [B, S] masks pad source positions."""
    D = q.shape[-1]
    logits = jnp.einsum("bshd,bthd->bhst", q, mem_k,
                        preferred_element_type=jnp.float32)
    logits = logits * (D ** -0.5)
    if src_live is not None:
        logits = jnp.where(src_live[:, None, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(mem_v.dtype)
    out = jnp.einsum("bhst,bthd->bshd", probs, mem_v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_tokens", "greedy"))
def t5_generate(params: Params, src_tokens: jax.Array, cfg: T5Config, *,
                bos_id: int = 1, max_new_tokens: int = 32,
                greedy: bool = True, temperature: float = 1.0,
                eos_id: Optional[int] = None,
                src_live: Optional[jax.Array] = None,
                rng: Optional[jax.Array] = None) -> jax.Array:
    """src_tokens [B, S] -> generated target tokens
    [B, max_new_tokens] (starting after bos, which is NOT returned).

    TPU-shaped like the LM decode loop (models/generate.py): the
    encoder runs once, every decoder layer's cross-attention K/V over
    the memory are precomputed ONCE, and the decode loop is one
    `lax.scan` with a static trip count over a preallocated
    self-attention cache."""
    B = src_tokens.shape[0]
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    memory = t5_encode(params, src_tokens, cfg)
    dt = cfg.dtype
    dec = params["decoder"]
    # Per-layer cross K/V of the (fixed) memory: [L, B, S, H, D].
    mem_k = jnp.einsum("bsd,ldhk->lbshk", memory,
                       dec["cross_wk"].astype(dt))
    mem_v = jnp.einsum("bsd,ldhk->lbshk", memory,
                       dec["cross_wv"].astype(dt))
    cache_shape = (cfg.n_layers, B, max_new_tokens, cfg.n_heads,
                   cfg.head_dim)
    self_k = jnp.zeros(cache_shape, dt)
    self_v = jnp.zeros(cache_shape, dt)

    def decode_step(tok, self_k, self_v, slot):
        h = params["embed"].astype(dt)[tok[:, None]]       # [B, 1, d]
        positions = jnp.full((B, 1), slot)

        def body(carry, xs):
            h = carry
            layer, k_c, v_c, m_k, m_v = xs
            x = _rmsnorm(h, layer["self_norm"], cfg.norm_eps)
            q = _rope(_proj(x, layer["self_wq"], dt), positions,
                      cfg.rope_theta)
            k = _rope(_proj(x, layer["self_wk"], dt), positions,
                      cfg.rope_theta)
            v = _proj(x, layer["self_wv"], dt)
            k_c = jax.lax.dynamic_update_slice(
                k_c, k.astype(k_c.dtype), (0, slot, 0, 0))
            v_c = jax.lax.dynamic_update_slice(
                v_c, v.astype(v_c.dtype), (0, slot, 0, 0))
            o = _cached_self_attention(q, k_c, v_c, slot, cfg)
            h = h + jnp.einsum("bshk,hkd->bsd", o,
                               layer["self_wo"].astype(dt))
            x = _rmsnorm(h, layer["cross_norm"], cfg.norm_eps)
            q = _proj(x, layer["cross_wq"], dt)
            o = _memory_attention(q, m_k, m_v, src_live, cfg)
            h = h + jnp.einsum("bshk,hkd->bsd", o,
                               layer["cross_wo"].astype(dt))
            x = _rmsnorm(h, layer["mlp_norm"], cfg.norm_eps)
            return h + _mlp(x, layer, cfg), (k_c, v_c)

        h, (self_k, self_v) = jax.lax.scan(
            body, h, (dec, self_k, self_v, mem_k, mem_v))
        h = _rmsnorm(h, params["dec_final_norm"], cfg.norm_eps)
        logits = jnp.einsum("btd,vd->btv", h,
                            params["embed"].astype(h.dtype)
                            ).astype(jnp.float32)
        return logits[:, 0], self_k, self_v

    def sample(logits_row, key):
        if greedy:
            return jnp.argmax(logits_row, axis=-1).astype(jnp.int32)
        scaled = logits_row / jnp.maximum(temperature, 1e-6)
        return jax.random.categorical(key, scaled).astype(jnp.int32)

    def step(carry, xs):
        tok, self_k, self_v, slot, done = carry
        key = xs
        logits, self_k, self_v = decode_step(tok, self_k, self_v, slot)
        nxt = sample(logits, key)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        return (nxt, self_k, self_v, slot + 1, done), nxt

    keys = jax.random.split(rng, max_new_tokens)
    bos = jnp.full((B,), bos_id, jnp.int32)
    done0 = jnp.zeros((B,), bool)
    (_, _, _, _, _), toks = jax.lax.scan(
        step, (bos, self_k, self_v, 0, done0), keys)
    return toks.T


def t5_loss(params: Params, batch: Dict[str, jax.Array],
            cfg: T5Config) -> jax.Array:
    """batch: {'src': [B,S], 'tgt': [B,T+1]} — teacher forcing: the
    decoder sees tgt[:, :-1] and predicts tgt[:, 1:]; pad positions
    (cfg.pad_id) in the LABELS are masked out of the mean."""
    src = batch["src"]
    tgt_in = batch["tgt"][:, :-1]
    labels = batch["tgt"][:, 1:]
    logits = t5_forward(params, src, tgt_in, cfg)
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(
        logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    live = (labels != cfg.pad_id).astype(jnp.float32)
    return (nll * live).sum() / jnp.maximum(live.sum(), 1.0)
