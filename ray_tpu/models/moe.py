"""Mixture-of-Experts decoder LM (Mixtral-style), TPU-first.

Expert parallelism is a capability the reference lacks entirely
(SURVEY.md §2.4: "Expert parallel (EP/MoE) — absent"); this module is the
new-framework original. Design:

- Top-k (default 2) token-choice routing with GShard/Switch-style static
  capacity: dispatch/combine are one-hot einsums so every shape is static
  and XLA tiles the expert matmuls onto the MXU — no ragged gather in the
  hot path. Overflow tokens are dropped (standard capacity semantics);
  the aux load-balancing loss keeps drop rates low.
- The expert dimension is a logical axis ("expert") mapped to the `ep`
  mesh axis: dispatch einsums become XLA all-to-alls over ICI, expert
  FFN weights shard E-way with zero code changes.
- Everything else (attention, RoPE, rmsnorm, scanned layers, remat)
  reuses the Llama building blocks.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import (LlamaConfig, _attention_call,
                                  _layer_shapes, _rmsnorm, _rope)
from ray_tpu.parallel.sharding import LogicalAxisRules, logical_to_mesh

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoeConfig(LlamaConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01

    def __post_init__(self):
        super().__post_init__()
        if self.remat_policy != "full":
            raise ValueError(
                "MoeConfig supports remat_policy='full' only: moe_forward "
                "ignores remat_policy and always applies plain per-layer "
                "jax.checkpoint (and _moe_decoder_layer carries no "
                "checkpoint_name tags for named policies either)")

    @staticmethod
    def mixtral_8x7b(**kw) -> "MoeConfig":
        return MoeConfig(vocab_size=32000, dim=4096, n_layers=32,
                         n_heads=32, n_kv_heads=8, ffn_dim=14336,
                         n_experts=8, top_k=2, **kw)

    @staticmethod
    def nano_moe(**kw) -> "MoeConfig":
        defaults = dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, ffn_dim=128, n_experts=4, top_k=2,
                        max_seq_len=128)
        defaults.update(kw)
        return MoeConfig(**defaults)

    def num_params(self) -> int:
        d, f, e = self.dim, self.ffn_dim, self.n_experts
        per_layer_attn = d * self.n_heads * self.head_dim * 2 + \
            d * self.n_kv_heads * self.head_dim * 2
        per_layer_moe = e * 3 * d * f + d * e  # experts + router
        return (self.vocab_size * d * 2 +
                self.n_layers * (per_layer_attn + per_layer_moe))

    def active_params(self) -> int:
        """Params touched per token (top-k experts only) — the MFU basis."""
        d, f = self.dim, self.ffn_dim
        per_layer_attn = d * self.n_heads * self.head_dim * 2 + \
            d * self.n_kv_heads * self.head_dim * 2
        per_layer_moe = self.top_k * 3 * d * f + d * self.n_experts
        return (self.vocab_size * d * 2 +
                self.n_layers * (per_layer_attn + per_layer_moe))


def _moe_layer_shapes(cfg: MoeConfig) -> Dict[str, Any]:
    """Llama attention shapes + expert-stacked FFN + router."""
    d, f, e = cfg.dim, cfg.ffn_dim, cfg.n_experts
    shapes = {k: v for k, v in _layer_shapes(cfg).items()
              if not k.startswith("w_")}  # drop dense FFN
    shapes.update({
        "w_router": ((d, e), ("embed", None), d),
        "we_gate": ((e, d, f), ("expert", "embed", "mlp"), d),
        "we_up": ((e, d, f), ("expert", "embed", "mlp"), d),
        "we_down": ((e, f, d), ("expert", "mlp", "embed"), f),
    })
    return shapes


def moe_init(rng: jax.Array, cfg: MoeConfig) -> Params:
    shapes = _moe_layer_shapes(cfg)
    keys = jax.random.split(rng, len(shapes) + 3)
    layers = {}
    for i, (name, (shape, _, fan_in)) in enumerate(shapes.items()):
        if fan_in is None:
            layers[name] = jnp.ones((cfg.n_layers,) + shape,
                                    cfg.param_dtype)
        else:
            layers[name] = (jax.random.normal(
                keys[i], (cfg.n_layers,) + shape) * fan_in ** -0.5
                ).astype(cfg.param_dtype)
    return {
        "tok_embed": (jax.random.normal(
            keys[-3], (cfg.vocab_size, cfg.dim)) * 0.02
            ).astype(cfg.param_dtype),
        "layers": layers,
        "final_norm": jnp.ones((cfg.dim,), cfg.param_dtype),
        "lm_head": (jax.random.normal(
            keys[-1], (cfg.dim, cfg.vocab_size)) * cfg.dim ** -0.5
            ).astype(cfg.param_dtype),
    }


def moe_logical_specs(cfg: MoeConfig) -> Params:
    layer_specs = {name: ("layers",) + logical
                   for name, (_, logical, _f) in
                   _moe_layer_shapes(cfg).items()}
    return {
        "tok_embed": ("vocab", "embed"),
        "layers": layer_specs,
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


def moe_param_specs(cfg: MoeConfig,
                    rules: Optional[LogicalAxisRules] = None) -> Params:
    return jax.tree_util.tree_map(
        lambda logical: logical_to_mesh(logical, rules),
        moe_logical_specs(cfg),
        is_leaf=lambda x: isinstance(x, tuple) and all(
            isinstance(e, (str, type(None))) for e in x))


def _route_topk(gates: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """gates [G,E] -> (weights [G,k], expert_idx [G,k]); weights
    renormalized over the chosen k."""
    weights, idx = jax.lax.top_k(gates, k)
    weights = weights / jnp.maximum(
        weights.sum(-1, keepdims=True), 1e-9)
    return weights, idx


def _moe_ffn(x: jax.Array, layer: Params,
             cfg: MoeConfig) -> Tuple[jax.Array, jax.Array]:
    """x [B,S,d] -> (out [B,S,d], aux_loss scalar). Static-capacity
    token-choice top-k dispatch."""
    dt = cfg.dtype
    b, s, d = x.shape
    g = b * s
    e, k = cfg.n_experts, cfg.top_k
    capacity = max(1, int(cfg.capacity_factor * g * k / e))

    xf = x.reshape(g, d)
    router_logits = jnp.einsum(
        "gd,de->ge", xf.astype(jnp.float32),
        layer["w_router"].astype(jnp.float32))
    gates = jax.nn.softmax(router_logits, axis=-1)          # [G,E]
    weights, expert_idx = _route_topk(gates, k)             # [G,k]

    # Position of each (token, choice) within its expert's capacity.
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)  # [G,k,E]
    flat = onehot.reshape(g * k, e)
    # Order: token-major, choice-minor — earlier tokens win capacity.
    position = jnp.cumsum(flat, axis=0) - 1                  # [G*k,E]
    position = (position * flat).sum(-1).reshape(g, k)       # [G,k]
    in_capacity = position < capacity

    # Combine weights [G,k] -> combine tensor [G,E,C] (one-hot einsum).
    keep = weights * in_capacity.astype(weights.dtype)
    pos_onehot = jax.nn.one_hot(position, capacity,
                                dtype=dt)                    # [G,k,C]
    exp_onehot = jax.nn.one_hot(expert_idx, e, dtype=dt)     # [G,k,E]
    combine = jnp.einsum("gk,gke,gkc->gec",
                         keep.astype(dt), exp_onehot, pos_onehot)
    dispatch = (combine > 0).astype(dt)                      # [G,E,C]

    # Expert compute: [E,C,d] batched matmuls (MXU-shaped, ep-sharded).
    expert_in = jnp.einsum("gec,gd->ecd", dispatch, xf.astype(dt))
    gate = jnp.einsum("ecd,edf->ecf", expert_in,
                      layer["we_gate"].astype(dt))
    up = jnp.einsum("ecd,edf->ecf", expert_in,
                    layer["we_up"].astype(dt))
    expert_out = jnp.einsum("ecf,efd->ecd", jax.nn.silu(gate) * up,
                            layer["we_down"].astype(dt))
    out = jnp.einsum("gec,ecd->gd", combine, expert_out)

    # Load-balancing aux loss (Switch/GShard): E * sum_e f_e * p_e.
    me = gates.mean(0)                                       # [E]
    ce = exp_onehot.sum(1).mean(0)                           # [E] frac routed
    aux = e * jnp.sum(me * ce) / k

    return out.reshape(b, s, d), aux.astype(jnp.float32)


def _moe_decoder_layer(carry, layer: Params, positions: jax.Array,
                       cfg: MoeConfig):
    h, aux_sum = carry
    dt = cfg.dtype
    x = _rmsnorm(h, layer["attn_norm"], cfg.norm_eps)
    q = jnp.einsum("bsd,dhk->bshk", x, layer["wq"].astype(dt))
    kk = jnp.einsum("bsd,dhk->bshk", x, layer["wk"].astype(dt))
    v = jnp.einsum("bsd,dhk->bshk", x, layer["wv"].astype(dt))
    q = _rope(q, positions, cfg.rope_theta)
    kk = _rope(kk, positions, cfg.rope_theta)
    o = _attention_call(q, kk, v, cfg)
    h = h + jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(dt))

    x = _rmsnorm(h, layer["mlp_norm"], cfg.norm_eps)
    moe_out, aux = _moe_ffn(x, layer, cfg)
    return (h + moe_out, aux_sum + aux)


def moe_forward(params: Params, tokens: jax.Array, cfg: MoeConfig,
                positions: Optional[jax.Array] = None
                ) -> Tuple[jax.Array, jax.Array]:
    """tokens [B,S] -> (logits [B,S,V] f32, mean aux loss)."""
    if positions is None:
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1]), tokens.shape)
    h = params["tok_embed"].astype(cfg.dtype)[tokens]

    layer_fn = functools.partial(_moe_decoder_layer, positions=positions,
                                 cfg=cfg)
    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn)

    def scan_body(carry, layer):
        return layer_fn(carry, layer), None

    (h, aux_sum), _ = jax.lax.scan(
        scan_body, (h, jnp.zeros((), jnp.float32)), params["layers"])
    h = _rmsnorm(h, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", h,
                        params["lm_head"].astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return logits, aux_sum / cfg.n_layers


def moe_loss(params: Params, batch: Dict[str, jax.Array],
             cfg: MoeConfig) -> jax.Array:
    """Next-token CE + router aux loss."""
    if "inputs" in batch:
        inputs, targets = batch["inputs"], batch["targets"]
    else:
        inputs, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    logits, aux = moe_forward(params, inputs, cfg)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll) + cfg.router_aux_coef * aux


def moe_flops_per_token(cfg: MoeConfig, seq_len: int) -> float:
    """Training FLOPs/token on ACTIVE params (top-k experts)."""
    attn = 12 * cfg.n_layers * cfg.dim * seq_len
    return 6.0 * cfg.active_params() + attn * 0.5
