"""Request-scheduler policies for the continuous-batching DecodeEngine.

The engine's admission loop used to be an implicit FIFO deque buried in
`DecodeEngine.submit()`/`step()`. Serving heavy traffic needs that seam
to be a first-class, pluggable policy — the analog of the reference
Serve router/scheduler plane (python/ray/serve/_private/router.py picks
replicas; this picks which QUEUED request gets the next freed decode
slot) — plus the two admission-control knobs every production LLM
server grows:

- a BOUNDED queue with backpressure (`max_queue` + `on_full`): reject
  (raise `EngineOverloaded`, the caller sheds load / retries elsewhere)
  or block (drive the engine until a queue slot frees — the
  single-threaded analog of awaiting queue room);
- a per-step PREFILL ADMISSION BUDGET (`max_prefills_per_step`): each
  admission runs a whole prompt-prefill program before the shared
  decode step, so a burst of long prompts admitted at once would stall
  every in-flight decode row for the full burst; capping admissions
  per step bounds the inter-token latency in-flight requests can lose
  to newcomers.

Scheduling only changes WHICH request is admitted when a slot frees —
and, via `horizon_hint`, how many decode iterations the engine fuses
into one program before it re-consults the queue (TTFT vs throughput)
— never what any admitted request computes: outputs stay
token-identical to solo `generate` under every policy and every
horizon (tested).
"""

from __future__ import annotations

import collections
import heapq
from typing import List, Optional


class EngineOverloaded(RuntimeError):
    """Raised by `DecodeEngine.submit()` when the bounded queue is full
    and the engine was configured with on_full="reject"."""


class SchedulerPolicy:
    """Ordering policy for queued (not-yet-admitted) requests.

    Implementations hold requests between `submit()` and admission and
    decide which one takes the next freed slot. They never see or
    touch in-flight rows."""

    name = "base"

    def push(self, req) -> None:
        raise NotImplementedError

    def pop(self):
        """Remove and return the next request to admit."""
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    def snapshot(self) -> List[int]:
        """Queued request ids, in no particular order (introspection)."""
        raise NotImplementedError

    def horizon_hint(self, *, free_slots: int,
                     max_horizon: int) -> int:
        """Suggested fused-decode horizon for the NEXT engine step
        (how many decode iterations to fuse into one program before
        the host looks at the queue again).

        Default policy, shared by every built-in: while a queued
        request could take a free slot next step (queue non-empty AND
        free_slots > 0 — admission was capped by the prefill budget
        this step), answer 1 so the newcomer's TTFT is not held behind
        a long horizon; otherwise (slots saturated, or nothing queued)
        answer `max_horizon` and amortize dispatch overhead. Policies
        may override — e.g. a deadline-aware policy shortening the
        horizon as the head-of-queue deadline approaches. The engine
        additionally caps the hint at the largest remaining row budget
        and rounds it down to a power of two (bounded compile count)."""
        if len(self) and free_slots > 0:
            return 1
        return max_horizon


class FIFOPolicy(SchedulerPolicy):
    """Admit in submission order (the engine's historical behavior)."""

    name = "fifo"

    def __init__(self):
        self._q: collections.deque = collections.deque()

    def push(self, req) -> None:
        self._q.append(req)

    def pop(self):
        return self._q.popleft()

    def __len__(self) -> int:
        return len(self._q)

    def snapshot(self) -> List[int]:
        return [r.req_id for r in self._q]


class PriorityPolicy(SchedulerPolicy):
    """Admit by priority class (LOWER number = admitted first), FIFO
    within a class — `submit(..., priority=0)` interactive traffic
    overtakes queued `priority=10` batch traffic at the next free slot.
    The submission sequence number breaks ties, so equal-priority
    requests never reorder (and the heap never compares request
    objects)."""

    name = "priority"

    def __init__(self):
        self._heap: list = []

    def push(self, req) -> None:
        heapq.heappush(self._heap, (req.priority, req.seq, req))

    def pop(self):
        return heapq.heappop(self._heap)[2]

    def __len__(self) -> int:
        return len(self._heap)

    def snapshot(self) -> List[int]:
        return [r.req_id for _, _, r in self._heap]


_POLICIES = {"fifo": FIFOPolicy, "priority": PriorityPolicy}


def make_policy(spec) -> SchedulerPolicy:
    """Resolve a policy spec: an instance passes through, a name
    ("fifo" | "priority") constructs the built-in."""
    if isinstance(spec, SchedulerPolicy):
        return spec
    try:
        return _POLICIES[spec]()
    except (KeyError, TypeError):
        raise ValueError(
            f"unknown scheduler policy {spec!r}: expected a "
            f"SchedulerPolicy instance or one of {sorted(_POLICIES)}")
