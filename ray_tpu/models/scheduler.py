"""Request-scheduler policies for the continuous-batching DecodeEngine.

The engine's admission loop used to be an implicit FIFO deque buried in
`DecodeEngine.submit()`/`step()`. Serving heavy traffic needs that seam
to be a first-class, pluggable policy — the analog of the reference
Serve router/scheduler plane (python/ray/serve/_private/router.py picks
replicas; this picks which QUEUED request gets the next freed decode
slot) — plus the two admission-control knobs every production LLM
server grows:

- a BOUNDED queue with backpressure (`max_queue` + `on_full`): reject
  (raise `EngineOverloaded`, the caller sheds load / retries elsewhere)
  or block (drive the engine until a queue slot frees — the
  single-threaded analog of awaiting queue room; `block_timeout_s`
  bounds the wait and raises `SubmitTimeout` when a wedged engine
  would otherwise block the caller forever);
- a per-step PREFILL ADMISSION BUDGET (`max_prefills_per_step`): each
  admission runs a whole prompt-prefill program before the shared
  decode step, so a burst of long prompts admitted at once would stall
  every in-flight decode row for the full burst; capping admissions
  per step bounds the inter-token latency in-flight requests can lose
  to newcomers.

Scheduling only changes WHICH request is admitted when a slot frees —
and, via `horizon_hint`, how many decode iterations the engine fuses
into one program before it re-consults the queue (TTFT vs throughput)
— never what any admitted request computes: outputs stay
token-identical to solo `generate` under every policy and every
horizon (tested).
"""

from __future__ import annotations

import collections
import heapq
from typing import List, Optional


class EngineOverloaded(RuntimeError):
    """Raised by `DecodeEngine.submit()` when the bounded queue is full
    and the engine was configured with on_full="reject"."""


class EngineDraining(RuntimeError):
    """Raised by `DecodeEngine.submit()` after `begin_drain()`: a
    draining engine finishes its in-flight and queued work but accepts
    no new requests (the fleet routes around it until removal)."""


class SubmitTimeout(EngineOverloaded):
    """Raised by `DecodeEngine.submit()` in on_full="block" mode when
    the queue stays full past ``block_timeout_s``: the engine was
    driven that long without freeing a queue slot, so it is wedged or
    hopelessly oversubscribed — surface a typed error instead of
    spinning forever. Subclasses EngineOverloaded so existing
    overload handlers keep catching it."""


class SchedulerPolicy:
    """Ordering policy for queued (not-yet-admitted) requests.

    Implementations hold requests between `submit()` and admission and
    decide which one takes the next freed slot. They never see or
    touch in-flight rows."""

    name = "base"

    def push(self, req) -> None:
        raise NotImplementedError

    def push_front(self, req) -> None:
        """Re-queue a request at the HEAD of the policy's order — used
        by the paged engine when an admission gate turns out stale
        (pool momentarily full) and, crucially, when a live row is
        PREEMPTED: the victim must be first in line to swap back in,
        not re-ranked behind the traffic that evicted it. Policies
        without a natural front (e.g. priority heaps, where `req.seq`
        already restores the original rank) may fall back to push."""
        self.push(req)

    def pop(self):
        """Remove and return the next request to admit."""
        raise NotImplementedError

    def choose_victim(self, rows: List[int], requests) -> int:
        """Pick which live row the paged engine preempts when the KV
        pool runs dry mid-decode. `rows` is ordered oldest-admitted
        first; `requests[row]` is the in-flight request. Default is
        LIFO — evict the newest admission (vLLM's discipline: the
        oldest request is closest to finishing and has absorbed the
        most compute, so it is the worst thing to throw away).
        Policies may override, e.g. priority-aware victim choice."""
        return rows[-1]

    def __len__(self) -> int:
        raise NotImplementedError

    def snapshot(self) -> List[int]:
        """Queued request ids, in no particular order (introspection)."""
        raise NotImplementedError

    def queued_requests(self) -> list:
        """The queued request OBJECTS, in no particular order — a
        read-only view for load probes (the fleet router sums queued
        prompt lengths into a replica's pending-prefill estimate).
        Callers must not mutate the returned requests or the list."""
        raise NotImplementedError

    def queued_state(self) -> List[dict]:
        """Plain-dict view of the queue for the state API
        (`ray_tpu.util.state.list_requests`): one entry per queued
        request with the fields an operator reads — and the request
        object itself under ``"request"`` so the caller can classify
        further (swap ledger, deadlines) without re-walking the queue.
        Falls back to id-only entries for a custom policy that
        implements `snapshot()` but not `queued_requests()`. Read-only:
        never mutates queue order or the requests."""
        try:
            reqs = self.queued_requests()
        except NotImplementedError:
            return [{"req_id": rid} for rid in self.snapshot()]
        return [{"req_id": r.req_id, "priority": r.priority,
                 "prompt_tokens": len(r.prompt),
                 "max_new_tokens": r.max_new_tokens,
                 "deadline": r.deadline, "resume": r.resume,
                 # Imported from a prefill-class replica, waiting for
                 # decode admission (disaggregated fleets; always
                 # False elsewhere). Surfaced flat so state-API
                 # callers need not reach into the request object.
                 "handoff": bool(getattr(r, "handoff", False)),
                 "request": r} for r in reqs]

    def horizon_hint(self, *, free_slots: int,
                     max_horizon: int) -> int:
        """Suggested fused-decode horizon for the NEXT engine step
        (how many decode iterations to fuse into one program before
        the host looks at the queue again).

        Default policy, shared by every built-in: while a queued
        request could take a free slot next step (queue non-empty AND
        free_slots > 0 — admission was capped by the prefill budget
        this step), answer 1 so the newcomer's TTFT is not held behind
        a long horizon; otherwise (slots saturated, or nothing queued)
        answer `max_horizon` and amortize dispatch overhead. Policies
        may override — e.g. a deadline-aware policy shortening the
        horizon as the head-of-queue deadline approaches. The engine
        additionally caps the hint at the largest remaining row budget
        and rounds it down to a power of two (bounded compile count)."""
        if len(self) and free_slots > 0:
            return 1
        return max_horizon

    def spec_window_hint(self, *, rates: List[Optional[float]],
                         spec_window: int) -> List[int]:
        """Per-row ADAPTIVE draft window for the next speculative
        dispatch — the speculation analog of `horizon_hint`. `rates`
        has one entry per candidate row: that row's recent acceptance
        rate (accepted / proposed over the engine's sliding window of
        rounds), or None for a row with no history yet (fresh
        admission). Returns one draft width per row, each in
        [1, spec_window].

        Default policy: trust a fresh row with the full window
        (optimistic — the first rounds measure it), then track the
        measured acceptance rate linearly: a row accepting everything
        keeps `spec_window`, a row rejecting everything shrinks to 1
        (one proposal still rides free on the verify pass), rows in
        between get `1 + rate * (spec_window - 1)` rounded. The engine
        takes the max over rows (rounded up to a power of two, capped
        at `spec_window`) as the dispatch width and applies each row's
        hint as its per-row acceptance cap, so one shrinking row never
        recompiles the program. Policies may override — e.g. a
        deadline-aware policy forcing 1 to minimize per-round latency
        variance."""
        out = []
        for r in rates:
            if r is None:
                out.append(spec_window)
            else:
                out.append(max(1, min(spec_window,
                                      1 + int(r * (spec_window - 1)
                                              + 0.5))))
        return out

    def admissions_pending(self) -> bool:
        """Could an admission decision change the batch soon? The
        engine's async decode pipeline consults this before running
        ahead: a pending admission means every freed slot must be
        re-examined with fully-replayed host state, so the engine
        FLUSHES its in-flight ring and steps synchronously instead of
        dispatching run-ahead decode blocks the newcomer could not
        join. Default: queue non-empty. Policies that defer requests
        (e.g. prefix affinity holding followers for a warm trie) must
        still answer True while anything is queued — a deferred
        request is admissible again next round."""
        return len(self) > 0


class FIFOPolicy(SchedulerPolicy):
    """Admit in submission order (the engine's historical behavior)."""

    name = "fifo"

    def __init__(self):
        self._q: collections.deque = collections.deque()

    def push(self, req) -> None:
        self._q.append(req)

    def push_front(self, req) -> None:
        self._q.appendleft(req)

    def pop(self):
        return self._q.popleft()

    def __len__(self) -> int:
        return len(self._q)

    def snapshot(self) -> List[int]:
        return [r.req_id for r in self._q]

    def queued_requests(self) -> list:
        return list(self._q)


class PriorityPolicy(SchedulerPolicy):
    """Admit by priority class (LOWER number = admitted first), FIFO
    within a class — `submit(..., priority=0)` interactive traffic
    overtakes queued `priority=10` batch traffic at the next free slot.
    The submission sequence number breaks ties, so equal-priority
    requests never reorder (and the heap never compares request
    objects)."""

    name = "priority"

    def __init__(self):
        self._heap: list = []

    def push(self, req) -> None:
        heapq.heappush(self._heap, (req.priority, req.seq, req))

    def pop(self):
        return heapq.heappop(self._heap)[2]

    def __len__(self) -> int:
        return len(self._heap)

    def snapshot(self) -> List[int]:
        return [r.req_id for _, _, r in self._heap]

    def queued_requests(self) -> list:
        return [r for _, _, r in self._heap]


class PrefixAffinityPolicy(FIFOPolicy):
    """FIFO order, made prefix-cache aware: maximize KV reuse by never
    admitting a request COLD when admitting it one step later would be
    WARM.

    The engine (when built with prefix_cache=True) attaches a probe via
    `attach_prefix_probe`: ``probe(prompt) -> (matched_tokens,
    prefix_group_key, next_block_pending)`` — a pure host walk of the
    prefix trie. `pop` scans the queue in FIFO order and SKIPS, for
    this admission round only, any request that is about to become
    warmer than it is now:

    - its next prefix block is PENDING — an in-flight row is already
      prefilling exactly the blocks this request would recompute; once
      that row's copy-out commits (at most a few steps), this request
      admits warm and prefills only its own suffix;
    - a same-prefix-group request (same first block) was already popped
      COLD this round — the classic burst of N requests sharing one
      system prompt: the first becomes the group's leader and computes
      the shared blocks once; the other N-1 wait for it rather than
      all recomputing the prefix in parallel rows.

    `pop` returns None when every queued request is deferred (the
    engine stops admitting for the step). Progress is guaranteed: the
    leader IS admitted and its prefill always advances, so the blocks
    followers wait on commit after finitely many steps — deferral
    trades one short admission delay for an order-of-magnitude prefill
    saving. Without a probe attached the policy degrades to plain
    FIFO. Like every policy, this reorders ADMISSION only: admitted
    requests compute exactly what they would under FIFO (token-identity
    is tested)."""

    name = "prefix"

    def __init__(self):
        super().__init__()
        self._probe = None
        self._round_cold: set = set()   # group keys popped cold this round
        self.deferrals = 0   # pops skipped to wait for a warmer admit
        #                      (observability: the tracer's
        #                      admission_defer events and this counter
        #                      say how often affinity held a request)

    def attach_prefix_probe(self, probe) -> None:
        self._probe = probe

    def begin_admission_round(self) -> None:
        self._round_cold = set()

    def pop(self):
        if self._probe is None:
            return super().pop()
        for i, req in enumerate(self._q):
            if getattr(req, "resume", False):
                # Preempted row swapping back in: its KV is in the host
                # swap buffer (or replayed from its own history), not
                # the trie — probing/deferring it can only delay the
                # restart it is owed.
                del self._q[i]
                return req
            matched, key, pending = self._probe(req.prompt)
            if pending or (key is not None and key in self._round_cold):
                self.deferrals += 1
                continue                 # warmer next round — defer
            if key is not None and matched == 0:
                self._round_cold.add(key)   # cold leader for its group
            del self._q[i]
            return req
        return None


class AdapterAffinityPolicy(FIFOPolicy):
    """FIFO order, made multi-LoRA aware: group admissions by adapter
    residency so cold-adapter requests wait on their PREFETCH instead
    of stalling the admission round.

    The engine (when built with `lora=`) attaches a probe via
    `attach_adapter_probe`: ``probe(adapter_id) -> (resident,
    fetching)`` — a pure host lookup against the AdapterPool's ledger.
    `pop` scans the queue in FIFO order and SKIPS, for this admission
    round only, any request whose adapter is not resident yet:

    - its adapter's prefetch is IN FLIGHT — the async host->device
      stage was already enqueued; once `drain_prefetches` commits it
      (at most a few steps), this request admits against a warm slot;
    - a same-adapter request was already popped cold this round — the
      first becomes the adapter's leader (the engine's admission gate
      starts the prefetch and requeues it); the rest wait for that one
      transfer rather than each re-triggering the gate.

    `pop` returns None when every queued request is deferred. Progress
    is guaranteed: base-model (adapter_id=None) and resident-adapter
    requests always admit, and a deferred adapter's prefetch commits
    after finitely many steps. Without a probe the policy degrades to
    plain FIFO. Like every policy, this reorders ADMISSION only —
    outputs stay token-identical to FIFO (tested)."""

    name = "adapter"

    def __init__(self):
        super().__init__()
        self._probe = None
        self._round_cold: set = set()   # adapter_ids popped cold this round
        self.deferrals = 0   # pops skipped to wait for a warm slot

    def attach_adapter_probe(self, probe) -> None:
        self._probe = probe

    def begin_admission_round(self) -> None:
        self._round_cold = set()

    def pop(self):
        if self._probe is None:
            return super().pop()
        for i, req in enumerate(self._q):
            aid = getattr(req, "adapter_id", None)
            if aid is None or getattr(req, "resume", False):
                # Base-model rows gather the null slot; a preempted
                # resume is owed its restart (its re-admission re-runs
                # the engine's adapter gate anyway).
                del self._q[i]
                return req
            resident, fetching = self._probe(aid)
            if resident:
                del self._q[i]
                return req
            if fetching or aid in self._round_cold:
                self.deferrals += 1
                continue                 # warmer next round — defer
            self._round_cold.add(aid)    # cold leader for its adapter
            del self._q[i]
            return req
        return None


_POLICIES = {"fifo": FIFOPolicy, "priority": PriorityPolicy,
             "prefix": PrefixAffinityPolicy,
             "adapter": AdapterAffinityPolicy}


def make_policy(spec) -> SchedulerPolicy:
    """Resolve a policy spec: an instance passes through, a name
    ("fifo" | "priority" | "prefix") constructs the built-in."""
    if isinstance(spec, SchedulerPolicy):
        return spec
    try:
        return _POLICIES[spec]()
    except (KeyError, TypeError):
        raise ValueError(
            f"unknown scheduler policy {spec!r}: expected a "
            f"SchedulerPolicy instance or one of {sorted(_POLICIES)}")
