"""LoRA (low-rank adaptation) fine-tuning for the stacked-layer models.

Reference counterpart: none — the reference (Deegue/ray) ships no
parameter-efficient fine-tuning; Train delegates model surgery to user
torch code (python/ray/train/torch/train_loop_utils.py:158). Here LoRA is
a first-class TPU-native capability over the same GSPMD train-step
machinery as full fine-tuning (models/training.py).

Design (TPU-first):
- Adapters are a SEPARATE tiny pytree ({layer_name: {a, b}} with leading
  [n_layers] like every stacked weight). The base tree is never mutated.
- The train step takes base params as a regular (non-donated) input under
  stop_gradient — not a closure, which would bake multi-GiB constants
  into the executable — and differentiates only the adapter tree.
- The merge (W + alpha/r * A@B) happens INSIDE the jitted step, so XLA
  fuses it with the forward's weight gathers; adapters are replicated
  across the mesh (they are ~0.1% of the model; their grad psum is
  negligible next to fsdp's all-gathers).
- `lora_merge` exports a plain param tree for serving/generation — the
  merged model runs through llama_forward / generate unchanged.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models.llama import LlamaConfig, _layer_shapes
from ray_tpu.parallel.sharding import LogicalAxisRules, logical_to_mesh

Params = Dict[str, Any]

# Layer weights eligible for adaptation (norm scales are excluded —
# rank-decomposing a vector is meaningless).
_ADAPTABLE = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: Tuple[str, ...] = ("wq", "wk", "wv", "wo")

    def __post_init__(self):
        if self.rank <= 0:
            raise ValueError(f"rank must be positive, got {self.rank}")
        bad = [t for t in self.targets if t not in _ADAPTABLE]
        if not self.targets or bad:
            raise ValueError(
                f"targets {self.targets!r}: "
                + (f"unknown {bad}" if bad else "empty")
                + f" (adaptable: {_ADAPTABLE})")

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def _in_out_split(shape: Tuple[int, ...], fan_in: int) -> Tuple[int, int]:
    """Split a weight shape into (fan_in, fan_out) sizes by locating the
    contraction prefix (wq: (d,h,hd) -> d | h*hd; wo: (h,hd,d) -> h*hd | d)."""
    acc = 1
    for i, s in enumerate(shape):
        acc *= s
        if acc == fan_in:
            return fan_in, math.prod(shape[i + 1:])
    raise ValueError(f"fan_in {fan_in} is not a prefix product of {shape}")


def lora_init(rng: jax.Array, cfg: LlamaConfig,
              lora_cfg: LoraConfig) -> Params:
    """Adapter tree {"layers": {name: {"a": [L, in, r], "b": [L, r, out]}}}.
    A ~ N(0, 1/in); B = 0, so the merged model equals the base exactly at
    init (standard LoRA initialization)."""
    shapes = _layer_shapes(cfg)
    keys = jax.random.split(rng, len(lora_cfg.targets))
    layers = {}
    for key, name in zip(keys, lora_cfg.targets):
        shape, _logical, fan_in = shapes[name]
        n_in, n_out = _in_out_split(shape, fan_in)
        layers[name] = {
            "a": (jax.random.normal(key, (cfg.n_layers, n_in, lora_cfg.rank))
                  * n_in ** -0.5).astype(cfg.param_dtype),
            "b": jnp.zeros((cfg.n_layers, lora_cfg.rank, n_out),
                           cfg.param_dtype),
        }
    return {"layers": layers}


def lora_num_params(cfg: LlamaConfig, lora_cfg: LoraConfig) -> int:
    shapes = _layer_shapes(cfg)
    total = 0
    for name in lora_cfg.targets:
        shape, _logical, fan_in = shapes[name]
        n_in, n_out = _in_out_split(shape, fan_in)
        total += cfg.n_layers * lora_cfg.rank * (n_in + n_out)
    return total


def lora_param_specs(lora_cfg: LoraConfig,
                     rules: Optional[LogicalAxisRules] = None) -> Params:
    """Adapters shard only their stacked layer axis mapping (same
    "layers" logical axis as the base weights); in/rank/out replicate —
    at ~0.1% of model size the replication is free and keeps the merge
    einsum local."""
    spec = logical_to_mesh(("layers", None, None), rules)
    return {"layers": {name: {"a": spec, "b": spec}
                       for name in lora_cfg.targets}}


def lora_stack_specs(cfg: LlamaConfig, lora_cfg: LoraConfig,
                     rules: Optional[LogicalAxisRules] = None) -> Params:
    """PartitionSpecs for the serving AdapterPool's device-resident
    stacks ``{name: {"a": [L, A, n_in, rank], "b": [L, A, rank,
    n_out]}}`` (A = adapter slots, slot 0 = null adapter).

    Unlike `lora_param_specs` (training adapters, replicated), serving
    stacks follow the BASE weight's per-axis rules: the a-stack's
    fan-in axis takes the base weight's leading input logical axis and
    the b-stack's fan-out axis takes the base weight's first output
    logical axis, both resolved through the SAME (pruned) rule table
    the engine built for its base params — so a rank-r adapter
    degrades to replicated exactly when the base axis does (e.g. kv
    heads not divisible by tp). The slot and rank axes always
    replicate. Flattened axes stay divisible whenever the base axis
    is: n_in/n_out are products whose leading factor is the base dim
    the rule was pruned against."""
    shapes = _layer_shapes(cfg)
    out = {}
    for name in lora_cfg.targets:
        shape, logical, fan_in = shapes[name]
        acc, split = 1, None
        for i, s in enumerate(shape):
            acc *= s
            if acc == fan_in:
                split = i
                break
        if split is None:
            raise ValueError(
                f"fan_in {fan_in} is not a prefix product of {shape}")
        out[name] = {
            "a": logical_to_mesh(("layers", None, logical[0], None),
                                 rules),
            "b": logical_to_mesh(("layers", None, None,
                                  logical[split + 1]), rules),
        }
    return out


def lora_merge(base: Params, lora: Params, cfg: LlamaConfig,
               lora_cfg: LoraConfig) -> Params:
    """base + scale * A@B, reshaped per weight. Returns a full param tree
    usable by llama_forward/generate; base is not mutated."""
    merged_layers = dict(base["layers"])
    for name, ab in lora["layers"].items():
        w = base["layers"][name]
        delta = jnp.einsum("lir,lro->lio", ab["a"].astype(jnp.float32),
                           ab["b"].astype(jnp.float32)) * lora_cfg.scale
        merged_layers[name] = (w.astype(jnp.float32)
                               + delta.reshape(w.shape)).astype(w.dtype)
    out = dict(base)
    out["layers"] = merged_layers
    return out


def make_lora_train_step(
    loss_fn,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    cfg: LlamaConfig,
    lora_cfg: LoraConfig,
    base_specs: Params,
    batch_logical: Tuple[Optional[str], ...] = ("batch", None),
    rules: Optional[LogicalAxisRules] = None,
):
    """Returns (init_fn, step_fn) for adapter-only training.

    loss_fn(merged_params, batch) -> scalar — the SAME loss used for full
    fine-tuning (e.g. llama_loss); merging happens inside the step.

    init_fn(base_params, lora_params) -> (base, lora, opt_state): shards
    base per base_specs and adapters per lora_param_specs; optimizer
    state covers only the adapters.

    step_fn(lora, opt_state, base, batch) -> (lora, opt_state, metrics).
    Only lora/opt_state are donated; base flows through stop_gradient so
    XLA prunes the base-weight gradient computation entirely.
    """
    from ray_tpu.models.training import batch_sharding_fn

    base_shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), base_specs,
        is_leaf=lambda x: isinstance(x, P))
    lora_shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), lora_param_specs(lora_cfg, rules),
        is_leaf=lambda x: isinstance(x, P))
    _batch_sharding_for = batch_sharding_fn(mesh, batch_logical, rules)

    def init_fn(base_params, lora_params):
        base_params = jax.tree_util.tree_map(
            jax.device_put, base_params, base_shardings)
        lora_params = jax.tree_util.tree_map(
            jax.device_put, lora_params, lora_shardings)
        opt_state = jax.jit(optimizer.init)(lora_params)
        return base_params, lora_params, opt_state

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step_fn(lora, opt_state, base, batch):
        from ray_tpu.ops.attention import spmd_mesh_scope

        with spmd_mesh_scope(mesh):
            batch = jax.tree_util.tree_map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, _batch_sharding_for(x)), batch)
            frozen = jax.lax.stop_gradient(base)

            def lora_loss(lora_):
                return loss_fn(lora_merge(frozen, lora_, cfg, lora_cfg),
                               batch)

            loss, grads = jax.value_and_grad(lora_loss)(lora)
            updates, opt_state_ = optimizer.update(grads, opt_state, lora)
            lora = optax.apply_updates(lora, updates)
            metrics = {"loss": loss,
                       "grad_norm": optax.global_norm(grads)}
            return lora, opt_state_, metrics

    return init_fn, step_fn
