"""Request-lifecycle tracing for the serving stack.

`EngineTracer` is the per-request observability twin of
`engine_metrics.EngineMetrics`: where metrics aggregate (counters,
window percentiles), the tracer keeps the individual spans — one
bounded ring buffer of (name, req_id, lane, t0, dur, args) records fed
by `DecodeEngine` at the exact seams where the metrics hooks already
fire, and stitched across replicas by `LLMFleet`. `dump_trace()` emits
chrome://tracing complete events through `util.timeline`'s shared
event shape, so an engine trace, a fleet trace and a `ray timeline`
task dump all concatenate into one loadable file.

Design rules (mirroring engine_metrics):

- Zero-cost-when-off. The default is `NULL_TRACER`, a no-op twin with
  ``enabled = False``; every engine hot-path call site guards with
  ``if tr.enabled:`` so the off path never builds an args dict, never
  reads a clock, never allocates. `tests/test_perf_gates.py` pins
  this with a tracemalloc gate.
- Bounded-memory-when-on. The ring overwrites its OLDEST record when
  full and counts the overwrite in ``events_dropped`` — a long churn
  run keeps the most recent window, never grows without bound.
- Injectable ``clock=`` (monotonic by default), same discipline as
  `EngineMetrics`: tests drive spans on a FakeClock.

Per-request spans are CONTIGUOUS by construction: each request carries
a frontier timestamp (`_req_mark`) advanced by every span emitted for
it, so queue_wait + prefill_chunk* + swap spans + decode_block* sums
exactly to submit->finish wall time — the property `tools/trace_report.py`
and the lifecycle tests lean on.

Env gate: ``RAY_TPU_TRACE=<prefix>`` (the `_private/profiling_hook.py`
pattern) turns tracing on for every engine constructed with
``trace=None`` and dumps ``<prefix>.<engine_id>.<pid>.trace.json`` at
process exit. ``RAY_TPU_PROFILE`` composes independently: it profiles
the host control plane with cProfile, this traces requests — setting
both gets both artifacts.

Span catalogue (name / tid lane / meaning):

- ``queue_wait`` (req): submit -> admission.
- ``prefill_chunk`` (req): one prompt-prefill program (chunked
  prefill emits one span per chunk).
- ``decode_block`` (req): the request's share of one fused decode
  dispatch+drain (args: tokens emitted).
- ``preempt_swap_out`` / ``swap_in`` (req): paged preemption round
  trip.
- ``finish`` / ``shed`` (req): instant markers closing the lifecycle.
- ``dispatch`` / ``host_drain`` (engine lane): one batched program
  launch / one blocking device->host token pull.
- ``spec_draft`` (engine ``dispatch`` lane): one speculative dispatch
  — draft proposals + target verify fused in one program (args:
  window, proposed, rows, run_ahead).
- ``spec_draft_prefill`` (engine ``dispatch`` lane): draft-plane
  prompt seeding at admission / swap-in (args: bucket, rows).
- ``spec_verify`` (engine ``drain`` lane): the host-side acceptance
  accounting for one drained speculative block (args: window, rounds,
  proposed, accepted).

Speculative spans ride the ENGINE lanes, not per-request tids — one
spec dispatch serves the whole batch, so attributing it to a request
would break the per-request contiguity sum that `tools/trace_report.py`
leans on; the report aggregates them in a separate engine-lane
speculation summary instead.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from ray_tpu.util.timeline import chrome_complete_event

ENV_TRACE = "RAY_TPU_TRACE"

# Default ring capacity: ~16k spans covers thousands of requests of
# recent history at a few spans per request, at < 2 MiB of host RAM.
DEFAULT_CAPACITY = 16384


class EngineTracer:
    """Bounded ring buffer of lifecycle spans.

    Records are tuples ``(name, req_id, lane, t0, dur, args)``;
    ``req_id=None`` marks an engine-level span (dispatch / host-drain
    lanes), ``dur=0.0`` an instant marker. `chrome_events()` maps them
    to the trace-viewer layout: pid = this tracer's id (the replica),
    tid = ``req-<id>`` per request or ``engine:<lane>`` for engine
    lanes."""

    enabled = True

    def __init__(self, *, capacity: int = DEFAULT_CAPACITY,
                 clock: Callable[[], float] = time.monotonic,
                 engine_id: Optional[str] = None,
                 dump_path: Optional[str] = None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self.clock = clock
        self.engine_id = engine_id or "engine"
        self.dump_path = dump_path
        self.events_dropped = 0
        self._buf: List[Optional[tuple]] = [None] * capacity
        self._n = 0          # records ever written
        # Open spans awaiting their close (queue_wait mostly) and the
        # per-request contiguity frontier. Both are pruned on
        # finish/shed, so they stay O(live + queued requests).
        self._open: Dict[Tuple[str, Any], float] = {}
        self._req_mark: Dict[Any, float] = {}

    # -- primitives --------------------------------------------------------

    def now(self) -> float:
        return self.clock()

    def add(self, name: str, t0: float, dur: float = 0.0,
            req_id: Any = None, lane: Optional[str] = None,
            args: Optional[dict] = None) -> None:
        """Append one record; overwrite the oldest (and count the
        drop) when the ring is full."""
        if self._n >= self.capacity:
            self.events_dropped += 1
        self._buf[self._n % self.capacity] = (
            name, req_id, lane, t0, dur, args)
        self._n += 1

    def instant(self, name: str, req_id: Any = None,
                args: Optional[dict] = None,
                lane: Optional[str] = None) -> None:
        self.add(name, self.clock(), 0.0, req_id, lane, args)

    def open(self, name: str, req_id: Any) -> None:
        """Mark the start of a span closed later by `close` (or
        synthesized as still-open at dump time, the `util/timeline.py`
        discipline for hung work)."""
        self._open[(name, req_id)] = self.clock()

    def close(self, name: str, req_id: Any,
              args: Optional[dict] = None) -> float:
        """Emit the span opened by `open`; returns its end time (which
        also becomes the request's contiguity frontier)."""
        t1 = self.clock()
        t0 = self._open.pop((name, req_id), None)
        if t0 is not None:
            self.add(name, t0, t1 - t0, req_id, None, args)
        self._req_mark[req_id] = t1
        return t1

    def mark(self, req_id: Any) -> None:
        """Reset a request's frontier to now (span-less advance)."""
        self._req_mark[req_id] = self.clock()

    def span_since_mark(self, name: str, req_id: Any,
                        args: Optional[dict] = None) -> None:
        """Emit a span from the request's frontier to now and advance
        the frontier — the primitive that keeps each request's spans
        contiguous (durations sum to end-to-end latency)."""
        t1 = self.clock()
        t0 = self._req_mark.get(req_id, t1)
        self.add(name, t0, t1 - t0, req_id, None, args)
        self._req_mark[req_id] = t1

    def finish(self, req_id: Any, args: Optional[dict] = None,
               name: str = "finish") -> None:
        """Instant `finish` (or `shed`) marker + drop the request's
        frontier/open state (bounded bookkeeping under endless
        churn)."""
        self.add(name, self.clock(), 0.0, req_id, None, args)
        self._req_mark.pop(req_id, None)
        for key in [k for k in self._open if k[1] == req_id]:
            del self._open[key]

    # -- introspection / export --------------------------------------------

    def __len__(self) -> int:
        return min(self._n, self.capacity)

    def events(self) -> List[tuple]:
        """Ring contents, oldest first."""
        if self._n <= self.capacity:
            return [e for e in self._buf[:self._n]]
        i = self._n % self.capacity
        return [e for e in self._buf[i:] + self._buf[:i]]

    def chrome_events(self, pid: Any = None) -> List[dict]:
        """Ring -> chrome://tracing complete events (plus synthesized
        still-open spans for anything `open`ed but never closed), in
        timestamp order."""
        pid = self.engine_id if pid is None else pid
        out = []
        for name, req_id, lane, t0, dur, args in self.events():
            tid = (f"req-{req_id}" if req_id is not None
                   else f"engine:{lane or 'events'}")
            out.append(chrome_complete_event(
                name, "request" if req_id is not None else "engine",
                t0, dur, pid, tid, args))
        now = self.clock()
        for (name, req_id), t0 in self._open.items():
            out.append(chrome_complete_event(
                name, "request", t0, now - t0, pid, f"req-{req_id}",
                {"open": True}))
        out.sort(key=lambda e: e["ts"])
        return out

    def dump(self, path: Optional[str] = None,
             pid: Any = None) -> List[dict]:
        """Write (and return) the chrome-trace JSON. ``path=None``
        falls back to the env-gate dump path; with neither, the events
        are just returned."""
        events = self.chrome_events(pid=pid)
        path = path or self.dump_path
        if path:
            with open(path, "w") as f:
                json.dump(events, f)
        return events


class NullEngineTracer:
    """No-op twin: every engine/fleet hot-path call site guards on
    ``enabled`` so the off path costs one attribute read; the methods
    exist so unguarded callers still work."""

    enabled = False
    engine_id = "disabled"
    events_dropped = 0
    dump_path = None

    def now(self) -> float:
        return 0.0

    def add(self, *a, **k) -> None:
        pass

    def instant(self, *a, **k) -> None:
        pass

    def open(self, *a, **k) -> None:
        pass

    def close(self, *a, **k) -> float:
        return 0.0

    def mark(self, *a, **k) -> None:
        pass

    def span_since_mark(self, *a, **k) -> None:
        pass

    def finish(self, *a, **k) -> None:
        pass

    def __len__(self) -> int:
        return 0

    def events(self) -> List[tuple]:
        return []

    def chrome_events(self, pid: Any = None) -> List[dict]:
        return []

    def dump(self, path: Optional[str] = None, pid: Any = None) -> List[dict]:
        return []


NULL_TRACER = NullEngineTracer()


def maybe_tracer_from_env(tag: str,
                          clock: Callable[[], float] = time.monotonic,
                          ) -> Optional[EngineTracer]:
    """`RAY_TPU_TRACE=<prefix>` -> an EngineTracer that dumps
    ``<prefix>.<tag>.<pid>.trace.json`` at process exit (the
    `profiling_hook.maybe_enable_profiler` pattern); None when the
    env gate is off."""
    prefix = os.environ.get(ENV_TRACE)
    if not prefix:
        return None
    import atexit

    tracer = EngineTracer(
        clock=clock, engine_id=tag,
        dump_path=f"{prefix}.{tag}.{os.getpid()}.trace.json")
    atexit.register(tracer.dump)
    return tracer


def resolve_tracer(spec: Union[None, bool, EngineTracer,
                               NullEngineTracer, "EngineTracer"],
                   *, engine_id: str,
                   clock: Callable[[], float] = time.monotonic):
    """The `trace=` knob: an EngineTracer instance is used as-is,
    ``True`` builds one, ``False`` forces off, and ``None`` (the
    default) defers to the RAY_TPU_TRACE env gate."""
    if spec is None:
        # Explicit None check: an EngineTracer defines __len__, so a
        # fresh (empty) one is FALSY — `env_tracer or NULL_TRACER`
        # would silently discard it.
        env_tracer = maybe_tracer_from_env(engine_id, clock)
        return NULL_TRACER if env_tracer is None else env_tracer
    if spec is False:
        return NULL_TRACER
    if spec is True:
        return EngineTracer(clock=clock, engine_id=engine_id)
    return spec
