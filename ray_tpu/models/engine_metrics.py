"""Request-lifecycle telemetry for the continuous-batching DecodeEngine.

Every request moves queued → admitted (prefill) → decoding → finished;
this module timestamps each transition and exports the serving numbers
a vLLM-class engine is judged by:

- queue wait      (submit → prefill admission)
- TTFT            (submit → first emitted token)
- TPOT            (gap between consecutive tokens of one request)
- tokens/steps    (throughput counters)
- slot occupancy / batch efficiency per step (how full the shared
  decode program actually runs)

Export goes through the ordinary `ray_tpu.util.metrics`
Counter/Gauge/Histogram plane, so inside a cluster the series flow to
the GCS metrics table and the dashboard /metrics Prometheus endpoint
exactly like every other runtime metric (reference analog: Serve's
replica request/latency series in python/ray/serve/_private/replica.py
feeding python/ray/_private/metrics_agent.py). Outside a cluster the
registry is still populated locally — tests and notebooks read
`stats()` or `ray_tpu._private.metrics.snapshots()` directly.

All instruments carry an ``engine`` tag (one DecodeEngine = one tag
value) so several engines in one process — or one per replica — stay
separable in the same Prometheus plane.
"""

from __future__ import annotations

import itertools
import time
from typing import Callable, Dict, List, Optional

from ray_tpu.util.metrics import Counter, Gauge, Histogram

# Token-scale latency buckets: default runtime boundaries top out at
# 1000 (s) for RPCs; decode cadences live in the 0.5 ms – 30 s range.
LATENCY_BOUNDARIES_S = [
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0]

# Fused-decode horizon buckets (tokens per dispatch): powers of two up
# to well past the default decode_horizon of 8.
HORIZON_BOUNDARIES = [1, 2, 4, 8, 16, 32, 64]

_engine_ids = itertools.count()


class _Agg:
    """Running aggregate (count/sum/max) plus a bounded ring of recent
    observations for tail-percentile snapshots. Mean/max alone hide the
    tail — the autoscaler scales on TTFT p95 and the SLO bench reports
    p95/p99, so `fields` additionally emits `_p50`/`_p95`/`_p99` over
    the last ``WINDOW`` observations (a sliding window, the serving
    convention: an SLO is judged on RECENT traffic, and the bound keeps
    a long-running engine's snapshot cost flat). The full unbounded
    distribution still lives in the Histogram instruments."""

    WINDOW = 2048

    __slots__ = ("count", "sum", "max", "_ring", "_ring_i")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.max = 0.0
        self._ring: List[float] = []
        self._ring_i = 0

    def add(self, v: float) -> None:
        self.count += 1
        self.sum += v
        if v > self.max:
            self.max = v
        if len(self._ring) < self.WINDOW:
            self._ring.append(v)
        else:                       # overwrite oldest: O(1), no shift
            self._ring[self._ring_i] = v
            self._ring_i = (self._ring_i + 1) % self.WINDOW

    def percentile(self, q: float) -> float:
        """q-th percentile (0..100) of the retained window — the
        nearest-rank method on a sorted copy; 0.0 when empty."""
        if not self._ring:
            return 0.0
        vals = sorted(self._ring)
        rank = max(0, min(len(vals) - 1,
                          int(round(q / 100.0 * (len(vals) - 1)))))
        return vals[rank]

    def fields(self, prefix: str, out: Dict[str, float]) -> None:
        out[f"{prefix}_count"] = self.count
        out[f"{prefix}_mean"] = self.sum / self.count if self.count else 0.0
        out[f"{prefix}_max"] = self.max
        out[f"{prefix}_p50"] = self.percentile(50.0)
        out[f"{prefix}_p95"] = self.percentile(95.0)
        out[f"{prefix}_p99"] = self.percentile(99.0)


class _ReqTimes:
    __slots__ = ("submit_t", "admit_t", "first_token_t", "last_token_t",
                 "n_tokens")

    def __init__(self, submit_t: float):
        self.submit_t = submit_t
        self.admit_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.last_token_t: Optional[float] = None
        self.n_tokens = 0


class EngineMetrics:
    """One instance per DecodeEngine. The engine calls the on_* hooks
    at each lifecycle transition; `stats()` returns a flat numeric
    snapshot (gauge-friendly — see serve.metrics.report_engine_stats).

    ``clock`` is injectable for deterministic tests."""

    def __init__(self, *, engine_id: Optional[str] = None,
                 batch_slots: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        self.engine_id = engine_id or f"engine-{next(_engine_ids)}"
        self.batch_slots = max(1, batch_slots)
        self._clock = clock
        self._req: Dict[int, _ReqTimes] = {}

        self.requests_submitted = 0
        self.requests_admitted = 0
        self.requests_finished = 0
        self.requests_rejected = 0
        self.requests_shed = 0
        self.tokens_generated = 0
        self.steps = 0
        self.queue_depth = 0
        self.live_slots = 0
        self.batch_efficiency = 0.0
        self.queue_wait_s = _Agg()
        self.ttft_s = _Agg()
        self.tpot_s = _Agg()
        self.decode_dispatches = 0
        self.host_syncs = 0
        self.decode_horizon = _Agg()

        tag = {"engine": self.engine_id}
        keys = ("engine",)

        def counter(name, desc):
            return Counter(name, desc, tag_keys=keys).set_default_tags(tag)

        def gauge(name, desc):
            return Gauge(name, desc, tag_keys=keys).set_default_tags(tag)

        def hist(name, desc):
            return Histogram(name, desc, boundaries=LATENCY_BOUNDARIES_S,
                             tag_keys=keys).set_default_tags(tag)

        self._m_submitted = counter(
            "llm_engine_requests_submitted_total",
            "Requests accepted into the engine queue")
        self._m_finished = counter(
            "llm_engine_requests_finished_total",
            "Requests that completed (budget, eos, or max_len)")
        self._m_rejected = counter(
            "llm_engine_requests_rejected_total",
            "Requests shed by bounded-queue backpressure")
        self._m_shed = counter(
            "llm_engine_requests_shed_total",
            "Requests shed past their deadline before burning prefill "
            "(at submit, or expired mid-queue at admission)")
        self._m_tokens = counter(
            "llm_engine_tokens_generated_total",
            "Tokens emitted across all requests")
        self._m_steps = counter(
            "llm_engine_steps_total",
            "Shared decode steps executed")
        self._m_queue_wait = hist(
            "llm_engine_queue_wait_s",
            "Seconds from submit to prefill admission")
        self._m_ttft = hist(
            "llm_engine_ttft_s",
            "Seconds from submit to first emitted token")
        self._m_tpot = hist(
            "llm_engine_tpot_s",
            "Seconds between consecutive tokens of one request")
        self._m_queue_depth = gauge(
            "llm_engine_queue_depth",
            "Requests queued awaiting a decode slot")
        self._m_occupancy = gauge(
            "llm_engine_slot_occupancy",
            "Live decode slots / total slots (0..1)")
        self._m_batch_eff = gauge(
            "llm_engine_batch_efficiency",
            "Tokens emitted this step / total slots (0..1; ~occupancy "
            "unless rows finished mid-step)")
        self._m_dispatches = counter(
            "llm_engine_decode_dispatches_total",
            "Fused decode program launches (one per step horizon)")
        self._m_host_syncs = counter(
            "llm_engine_host_syncs_total",
            "Blocking device->host transfers in the serving loop")
        self._m_horizon = Histogram(
            "llm_engine_decode_horizon",
            "Decode iterations fused per dispatch (adaptive horizon)",
            boundaries=HORIZON_BOUNDARIES,
            tag_keys=keys).set_default_tags(tag)
        # Prefix-reuse / prefill-efficiency plane (PR: shared-prefix KV
        # cache + chunked prefill):
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefix_reused_tokens = 0
        self.prefix_evictions = 0
        self.prefill_real_tokens = 0
        self.prefill_padded_tokens = 0
        self.prefill_stalls = 0
        self._m_prefix_lookups = counter(
            "llm_engine_prefix_lookups_total",
            "Admissions probed against the prefix-cache trie")
        self._m_prefix_hits = counter(
            "llm_engine_prefix_hits_total",
            "Admissions that matched >= 1 cached prefix block")
        self._m_prefix_reused = counter(
            "llm_engine_prefix_reused_tokens_total",
            "Prompt tokens copied from the prefix pool, not prefilled")
        self._m_prefix_evictions = counter(
            "llm_engine_prefix_evictions_total",
            "Cold prefix blocks recycled by LRU eviction")
        self._m_prefill_real = counter(
            "llm_engine_prefill_tokens_total",
            "True prompt/suffix tokens run through batched prefill")
        self._m_prefill_padded = counter(
            "llm_engine_prefill_padded_tokens_total",
            "Length-bucket + pow2-group filler tokens run through "
            "batched prefill (padding waste)")
        self._m_prefill_stalls = counter(
            "llm_engine_chunked_prefill_stalls_total",
            "Engine steps with >= 1 row frozen mid-chunked-prefill")
        # Async-pipeline plane (PR: double-buffered decode):
        self.pipeline_flushes = 0
        self.pipeline_overrun_tokens = 0
        self.host_lag_steps = 0
        self.pipeline_depth = _Agg()
        self._m_pipe_flushes = counter(
            "llm_engine_pipeline_flushes_total",
            "Forced full drains of the in-flight decode ring "
            "(pending admission, mid-prefill row, or end of stream)")
        self._m_pipe_overrun = counter(
            "llm_engine_pipeline_overrun_tokens_total",
            "Masked run-ahead decode iterations dispatched for rows "
            "that had already finished")
        self._m_host_lag = gauge(
            "llm_engine_host_lag_steps",
            "Fused decode steps dispatched but not yet replayed on "
            "the host (ring length after the last drain)")
        # Tensor-parallel plane (PR: sharded engine over an ICI mesh):
        self.tp_degree = 1
        self.host_transfer_bytes = 0
        self._m_tp_degree = gauge(
            "llm_engine_tp_degree",
            "Tensor-parallel degree of the serving mesh (1 = "
            "unsharded single-chip engine)")
        self._m_transfer_bytes = counter(
            "llm_engine_host_transfer_bytes_total",
            "Bytes moved device->host by the serving loop (drained "
            "[H, B] token blocks — replicated, so per-token bytes do "
            "not grow with tp degree)")
        # Paged-KV plane (PR: one refcounted block pool, zero-copy
        # prefix shares, preempt-and-swap):
        self.kv_blocks_shared = 0
        self.kv_block_cows = 0
        self.preemptions = 0
        self.swap_in_bytes = 0
        self.swap_out_bytes = 0
        self.kv_pool_blocks_total = 0
        self.kv_pool_blocks_in_use = 0
        self.kv_pool_blocks_free = 0
        self.kv_bytes_per_token = 0.0
        self._m_kv_shared = counter(
            "llm_engine_kv_blocks_shared_total",
            "Prefix-cache blocks SHARED into warm admissions by "
            "refcount (zero bytes copied — the paged twin of "
            "prefix_reused_tokens)")
        self._m_kv_cow = counter(
            "llm_engine_kv_block_cow_total",
            "Shared blocks duplicated copy-on-write (a full-prompt "
            "hit whose tail block the new row must extend)")
        self._m_preemptions = counter(
            "llm_engine_preemptions_total",
            "Live decode rows evicted to free KV pool blocks "
            "(preempt-and-swap or preempt-and-recompute)")
        self._m_swap_out = counter(
            "llm_engine_swap_out_bytes_total",
            "Bytes spilled device->host by preemption swap-outs")
        self._m_swap_in = counter(
            "llm_engine_swap_in_bytes_total",
            "Bytes restored host->device by preemption swap-ins")
        # Disaggregated prefill/decode handoff plane:
        self.handoffs_out = 0
        self.handoffs_in = 0
        self.handoff_out_bytes = 0
        self.handoff_in_bytes = 0
        self._m_handoffs_out = counter(
            "llm_engine_handoffs_out_total",
            "Requests exported post-prefill to a decode-class "
            "replica (disaggregated fleet handoff)")
        self._m_handoffs_in = counter(
            "llm_engine_handoffs_in_total",
            "Requests imported from a prefill-class replica for "
            "decode (disaggregated fleet handoff)")
        self._m_handoff_out = counter(
            "llm_engine_handoff_out_bytes_total",
            "KV + logits bytes staged device->host by handoff "
            "exports")
        self._m_handoff_in = counter(
            "llm_engine_handoff_in_bytes_total",
            "KV + logits bytes accepted by handoff imports (swap "
            "pre-seed; 0 for a recompute-fallback import)")
        self._m_kv_pool_total = gauge(
            "llm_engine_kv_pool_blocks",
            "KV pool size in blocks (scratch block excluded)")
        self._m_kv_pool_in_use = gauge(
            "llm_engine_kv_pool_blocks_in_use",
            "KV pool blocks currently referenced by rows or the "
            "prefix trie")
        self._m_kv_pool_free = gauge(
            "llm_engine_kv_pool_blocks_free",
            "KV pool blocks on the free list")
        self._m_kv_bytes_per_token = gauge(
            "llm_engine_kv_bytes_per_token",
            "HBM bytes one cached token costs (quant dtype + its "
            "share of the per-block scale slab; the admission-"
            "capacity lever — see docs/serving.md)")
        # Speculative plane (PR: engine-integrated draft/verify). The
        # per-spec-plane llm_spec_* series live in SpecMetrics, tagged
        # with the SAME engine id; these engine-tagged aggregates let
        # dashboards join acceptance onto the other engine series.
        self.spec_rounds = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self._m_spec_rounds = counter(
            "llm_engine_spec_rounds_total",
            "Draft-propose / target-verify rounds replayed at drain")
        self._m_spec_proposed = counter(
            "llm_engine_spec_proposed_total",
            "Draft tokens proposed inside fused spec dispatches")
        self._m_spec_accepted = counter(
            "llm_engine_spec_accepted_total",
            "Proposed draft tokens the target accepted")
        self._m_spec_rate = gauge(
            "llm_engine_spec_acceptance_rate",
            "Cumulative accepted / proposed (0..1; 0 with spec off)")
        # Multi-LoRA plane (PR: batched heterogeneous-adapter decode
        # with HBM adapter residency). Counters track the AdapterPool's
        # LRU: a lookup is one admission-gate slot acquisition attempt,
        # a hit means the adapter was already resident.
        self.adapter_lookups = 0
        self.adapter_hits = 0
        self.adapter_prefetches = 0
        self.adapter_evictions = 0
        self.adapter_deferrals = 0
        self.adapter_slots = 0
        self.adapter_slots_resident = 0
        self.adapter_slots_pinned = 0
        self._m_adapter_lookups = counter(
            "llm_engine_adapter_lookups_total",
            "Adapter-slot acquisition attempts at the admission gate")
        self._m_adapter_hits = counter(
            "llm_engine_adapter_hits_total",
            "Slot acquisitions that found the adapter already "
            "resident in HBM")
        self._m_adapter_prefetches = counter(
            "llm_engine_adapter_prefetches_total",
            "Async host->device adapter weight transfers started for "
            "cold adapters")
        self._m_adapter_evictions = counter(
            "llm_engine_adapter_evictions_total",
            "Refcount-0 resident adapters evicted LRU-first to free "
            "a slot for a committing prefetch")
        self._m_adapter_deferrals = counter(
            "llm_engine_adapter_prefetch_deferrals_total",
            "Admissions requeued because their adapter was cold and "
            "its prefetch had not committed yet")
        self._m_adapter_slots = gauge(
            "llm_engine_adapter_slots",
            "Adapter slots in the device-resident stacks (null slot "
            "0 excluded)")
        self._m_adapter_resident = gauge(
            "llm_engine_adapter_slots_resident",
            "Slots currently holding a committed adapter")
        self._m_adapter_pinned = gauge(
            "llm_engine_adapter_slots_pinned",
            "Resident slots pinned by >= 1 live row (ineligible for "
            "eviction)")

    # -- lifecycle hooks (called by DecodeEngine) --------------------------

    def on_submit(self, req_id: int) -> None:
        self._req[req_id] = _ReqTimes(self._clock())
        self.requests_submitted += 1
        self._m_submitted.inc()

    def on_reject(self) -> None:
        self.requests_rejected += 1
        self._m_rejected.inc()

    def on_shed(self, req_id: int) -> None:
        """A queued request crossed its deadline and was retired
        WITHOUT prefilling (the overload plane's reject-before-prefill
        path). Distinct from on_reject: rejection is queue-full
        backpressure at submit; shedding is deadline expiry of an
        accepted request."""
        self.requests_shed += 1
        self._m_shed.inc()
        self._req.pop(req_id, None)

    def on_admit(self, req_id: int) -> None:
        rt = self._req.get(req_id)
        if rt is None or rt.admit_t is not None:
            return
        rt.admit_t = self._clock()
        wait = rt.admit_t - rt.submit_t
        self.requests_admitted += 1
        self.queue_wait_s.add(wait)
        self._m_queue_wait.observe(wait)

    def on_token(self, req_id: int, n: int = 1) -> None:
        rt = self._req.get(req_id)
        now = self._clock()
        self.tokens_generated += n
        self._m_tokens.inc(n)
        if rt is None:
            return
        if rt.first_token_t is None:
            rt.first_token_t = now
            ttft = now - rt.submit_t
            self.ttft_s.add(ttft)
            self._m_ttft.observe(ttft)
        else:
            tpot = now - rt.last_token_t
            self.tpot_s.add(tpot)
            self._m_tpot.observe(tpot)
        rt.last_token_t = now
        rt.n_tokens += n

    def on_tokens(self, req_id: int, n: int) -> None:
        """`n` tokens of one request landing TOGETHER (one drained
        [H, B] block) — the vectorized twin of per-token `on_token`
        calls, preserving its observation arithmetic: TTFT once at the
        request's first token, then one TPOT observation per further
        token (total = tokens - 1 per request). The first gap of a
        block is the real inter-block wall gap; the rest are 0.0 —
        honest for a fused block, whose tokens genuinely arrive at the
        same instant."""
        if n <= 0:
            return
        rt = self._req.get(req_id)
        now = self._clock()
        self.tokens_generated += n
        self._m_tokens.inc(n)
        if rt is None:
            return
        if rt.first_token_t is None:
            rt.first_token_t = now
            ttft = now - rt.submit_t
            self.ttft_s.add(ttft)
            self._m_ttft.observe(ttft)
        else:
            tpot = now - rt.last_token_t
            self.tpot_s.add(tpot)
            self._m_tpot.observe(tpot)
        for _ in range(n - 1):
            self.tpot_s.add(0.0)
            self._m_tpot.observe(0.0)
        rt.last_token_t = now
        rt.n_tokens += n

    def on_finish(self, req_id: int) -> None:
        self.requests_finished += 1
        self._m_finished.inc()
        self._req.pop(req_id, None)

    def on_step(self, live_slots: int, queue_depth: int,
                tokens_emitted: int) -> None:
        self.steps += 1
        self.live_slots = live_slots
        self.queue_depth = queue_depth
        self.batch_efficiency = tokens_emitted / self.batch_slots
        self._m_steps.inc()
        self._m_queue_depth.set(queue_depth)
        self._m_occupancy.set(live_slots / self.batch_slots)
        self._m_batch_eff.set(self.batch_efficiency)

    def on_dispatch(self, horizon: int, host_syncs: int = 1) -> None:
        """One fused decode dispatch of `horizon` iterations, costing
        `host_syncs` blocking device->host transfers (1 on the fused
        path: the [H, B] token block)."""
        self.decode_dispatches += 1
        self.host_syncs += host_syncs
        self.decode_horizon.add(horizon)
        self._m_dispatches.inc()
        if host_syncs > 0:
            self._m_host_syncs.inc(host_syncs)
        self._m_horizon.observe(horizon)

    def on_host_sync(self, n: int = 1, nbytes: int = 0) -> None:
        """A blocking device->host pull completed (a drained token
        block of `nbytes` bytes). Decoupled from `on_dispatch` by the
        async pipeline — dispatch happens up to `pipeline_depth` steps
        before its block's sync; totals converge once the ring
        drains."""
        self.host_syncs += n
        self._m_host_syncs.inc(n)
        if nbytes > 0:
            self.host_transfer_bytes += nbytes
            self._m_transfer_bytes.inc(nbytes)

    def on_tp_degree(self, tp: int) -> None:
        """Record the engine's tensor-parallel degree (once, at
        construction)."""
        self.tp_degree = int(tp)
        self._m_tp_degree.set(float(tp))

    def on_pipeline_drain(self, depth: int, lag: int) -> None:
        """One in-flight block replayed: `depth` fused steps were in
        flight when the drain started (1 = synchronous), `lag` remain
        after it (the host_lag_steps gauge)."""
        self.pipeline_depth.add(depth)
        self.host_lag_steps = lag
        self._m_host_lag.set(lag)

    def on_pipeline_flush(self, n: int = 1) -> None:
        self.pipeline_flushes += n
        self._m_pipe_flushes.inc(n)

    def on_pipeline_overrun(self, n: int) -> None:
        if n > 0:
            self.pipeline_overrun_tokens += n
            self._m_pipe_overrun.inc(n)

    def on_prefix(self, *, hit: bool, reused_tokens: int = 0) -> None:
        """One admission probed the prefix-cache trie; on a hit,
        `reused_tokens` prompt tokens were copied instead of run."""
        self.prefix_lookups += 1
        self._m_prefix_lookups.inc()
        if hit:
            self.prefix_hits += 1
            self._m_prefix_hits.inc()
        if reused_tokens > 0:
            self.prefix_reused_tokens += reused_tokens
            self._m_prefix_reused.inc(reused_tokens)

    def on_prefix_evictions(self, n: int = 1) -> None:
        if n > 0:
            self.prefix_evictions += n
            self._m_prefix_evictions.inc(n)

    def on_kv_shared(self, n: int) -> None:
        """`n` pool blocks handed to an admission by incref — the warm
        part of the prompt cost zero copy bytes."""
        if n > 0:
            self.kv_blocks_shared += n
            self._m_kv_shared.inc(n)

    def on_kv_cow(self, n: int = 1) -> None:
        if n > 0:
            self.kv_block_cows += n
            self._m_kv_cow.inc(n)

    def on_preempt(self, n: int = 1) -> None:
        if n > 0:
            self.preemptions += n
            self._m_preemptions.inc(n)

    def on_swap_out(self, nbytes: int) -> None:
        if nbytes > 0:
            self.swap_out_bytes += nbytes
            self._m_swap_out.inc(nbytes)

    def on_swap_in(self, nbytes: int) -> None:
        if nbytes > 0:
            self.swap_in_bytes += nbytes
            self._m_swap_in.inc(nbytes)

    def on_handoff_out(self, req_id: int, nbytes: int) -> None:
        """A request left this engine mid-flight (prefill→decode
        handoff): its per-request timing record goes with it — the
        importing engine owns TTFT/TPOT from here (the fleet stitches
        end-to-end TTFT itself)."""
        self.handoffs_out += 1
        self._m_handoffs_out.inc()
        if nbytes > 0:
            self.handoff_out_bytes += nbytes
            self._m_handoff_out.inc(nbytes)
        self._req.pop(req_id, None)

    def on_handoff_in(self, nbytes: int) -> None:
        self.handoffs_in += 1
        self._m_handoffs_in.inc()
        if nbytes > 0:
            self.handoff_in_bytes += nbytes
            self._m_handoff_in.inc(nbytes)

    def on_kv_pool(self, total: int, in_use: int, free: int,
                   bytes_per_token: float = 0.0) -> None:
        """Gauge update at step end: pool occupancy in blocks, plus
        the engine's per-token KV cost (constant per engine — quant
        dtype + scale-slab share — but exported per step so the fleet
        plane can weight occupancy into bytes)."""
        self.kv_pool_blocks_total = total
        self.kv_pool_blocks_in_use = in_use
        self.kv_pool_blocks_free = free
        self._m_kv_pool_total.set(total)
        self._m_kv_pool_in_use.set(in_use)
        self._m_kv_pool_free.set(free)
        if bytes_per_token > 0:
            self.kv_bytes_per_token = bytes_per_token
            self._m_kv_bytes_per_token.set(bytes_per_token)

    def on_prefill_batch(self, real_tokens: int,
                         padded_tokens: int) -> None:
        """One batched prefill program: `real_tokens` true chunk tokens
        plus `padded_tokens` bucket/pow2 filler riding along."""
        self.prefill_real_tokens += real_tokens
        self.prefill_padded_tokens += padded_tokens
        if real_tokens > 0:
            self._m_prefill_real.inc(real_tokens)
        if padded_tokens > 0:
            self._m_prefill_padded.inc(padded_tokens)

    def on_prefill_stall(self, n: int = 1) -> None:
        """One engine step ran with >= 1 row frozen mid-chunked-prefill
        (decode advanced without it, or was skipped entirely)."""
        if n > 0:
            self.prefill_stalls += n
            self._m_prefill_stalls.inc(n)

    def on_spec_round(self, rounds: int, proposed: int,
                      accepted: int) -> None:
        """One drained speculative block's acceptance accounting:
        `rounds` live greedy rows each verified their proposals —
        `proposed` draft tokens total, of which `accepted` matched the
        target's argmax chain (and were emitted for free)."""
        self.spec_rounds += rounds
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        if rounds > 0:
            self._m_spec_rounds.inc(rounds)
        if proposed > 0:
            self._m_spec_proposed.inc(proposed)
        if accepted > 0:
            self._m_spec_accepted.inc(accepted)
        if self.spec_proposed:
            self._m_spec_rate.set(self.spec_accepted
                                  / self.spec_proposed)

    def on_adapter_lookup(self, hit: bool) -> None:
        """One adapter-slot acquisition attempt at the admission gate
        (AdapterPool.alloc for a non-None adapter_id)."""
        self.adapter_lookups += 1
        self._m_adapter_lookups.inc()
        if hit:
            self.adapter_hits += 1
            self._m_adapter_hits.inc()

    def on_adapter_prefetch(self, n: int = 1) -> None:
        if n > 0:
            self.adapter_prefetches += n
            self._m_adapter_prefetches.inc(n)

    def on_adapter_evict(self, n: int = 1) -> None:
        if n > 0:
            self.adapter_evictions += n
            self._m_adapter_evictions.inc(n)

    def on_adapter_defer(self, n: int = 1) -> None:
        """An admission was requeued waiting on its adapter's
        prefetch instead of stalling the step."""
        if n > 0:
            self.adapter_deferrals += n
            self._m_adapter_deferrals.inc(n)

    def on_adapter_slots(self, total: int, resident: int,
                         pinned: int) -> None:
        """Gauge update after a pool state change (commit/evict)."""
        self.adapter_slots = total
        self.adapter_slots_resident = resident
        self.adapter_slots_pinned = pinned
        self._m_adapter_slots.set(total)
        self._m_adapter_resident.set(resident)
        self._m_adapter_pinned.set(pinned)

    def observe_queue_depth(self, depth: int) -> None:
        """Gauge update outside a step (e.g. right after submit)."""
        self.queue_depth = depth
        self._m_queue_depth.set(depth)

    # -- snapshot ----------------------------------------------------------

    def stats(self) -> Dict[str, float]:
        """Flat numeric snapshot of everything above — each field can
        be re-published as a gauge (serve.metrics.report_engine_stats)
        or asserted on directly in tests."""
        out: Dict[str, float] = {
            "requests_submitted": self.requests_submitted,
            "requests_admitted": self.requests_admitted,
            "requests_finished": self.requests_finished,
            "requests_rejected": self.requests_rejected,
            "requests_shed": self.requests_shed,
            "tokens_generated": self.tokens_generated,
            "steps": self.steps,
            "queue_depth": self.queue_depth,
            "live_slots": self.live_slots,
            "slot_occupancy": self.live_slots / self.batch_slots,
            "batch_efficiency": self.batch_efficiency,
        }
        out["decode_dispatches"] = self.decode_dispatches
        out["host_syncs"] = self.host_syncs
        out["host_syncs_per_token"] = (
            self.host_syncs / self.tokens_generated
            if self.tokens_generated else 0.0)
        out["tp_degree"] = self.tp_degree
        out["host_transfer_bytes"] = self.host_transfer_bytes
        out["host_transfer_bytes_per_token"] = (
            self.host_transfer_bytes / self.tokens_generated
            if self.tokens_generated else 0.0)
        out["dispatches_per_token"] = (
            self.decode_dispatches / self.tokens_generated
            if self.tokens_generated else 0.0)
        out["prefix_lookups"] = self.prefix_lookups
        out["prefix_hits"] = self.prefix_hits
        out["prefix_hit_rate"] = (
            self.prefix_hits / self.prefix_lookups
            if self.prefix_lookups else 0.0)
        out["prefix_reused_tokens"] = self.prefix_reused_tokens
        out["prefix_evictions"] = self.prefix_evictions
        out["prefill_real_tokens"] = self.prefill_real_tokens
        out["prefill_padded_tokens"] = self.prefill_padded_tokens
        prefill_total = self.prefill_real_tokens + self.prefill_padded_tokens
        out["prefill_padding_waste_frac"] = (
            self.prefill_padded_tokens / prefill_total
            if prefill_total else 0.0)
        out["chunked_prefill_stalls"] = self.prefill_stalls
        out["pipeline_flushes"] = self.pipeline_flushes
        out["pipeline_overrun_tokens"] = self.pipeline_overrun_tokens
        out["kv_blocks_shared"] = self.kv_blocks_shared
        out["kv_block_cows"] = self.kv_block_cows
        out["preemptions"] = self.preemptions
        out["swap_in_bytes"] = self.swap_in_bytes
        out["swap_out_bytes"] = self.swap_out_bytes
        out["handoffs_out"] = self.handoffs_out
        out["handoffs_in"] = self.handoffs_in
        out["handoff_out_bytes"] = self.handoff_out_bytes
        out["handoff_in_bytes"] = self.handoff_in_bytes
        out["kv_pool_blocks_total"] = self.kv_pool_blocks_total
        out["kv_pool_blocks_in_use"] = self.kv_pool_blocks_in_use
        out["kv_pool_blocks_free"] = self.kv_pool_blocks_free
        out["kv_bytes_per_token"] = self.kv_bytes_per_token
        out["kv_pool_occupancy"] = (
            self.kv_pool_blocks_in_use / self.kv_pool_blocks_total
            if self.kv_pool_blocks_total else 0.0)
        out["host_lag_steps"] = self.host_lag_steps
        out["pipeline_depth_effective"] = (
            self.pipeline_depth.sum / self.pipeline_depth.count
            if self.pipeline_depth.count else 0.0)
        out["spec_rounds"] = self.spec_rounds
        out["spec_proposed"] = self.spec_proposed
        out["spec_accepted"] = self.spec_accepted
        out["spec_acceptance_rate"] = (
            self.spec_accepted / self.spec_proposed
            if self.spec_proposed else 0.0)
        out["adapter_lookups"] = self.adapter_lookups
        out["adapter_hits"] = self.adapter_hits
        out["adapter_hit_rate"] = (
            self.adapter_hits / self.adapter_lookups
            if self.adapter_lookups else 0.0)
        out["adapter_prefetches"] = self.adapter_prefetches
        out["adapter_evictions"] = self.adapter_evictions
        out["adapter_prefetch_deferrals"] = self.adapter_deferrals
        out["adapter_slots"] = self.adapter_slots
        out["adapter_slots_resident"] = self.adapter_slots_resident
        out["adapter_slots_pinned"] = self.adapter_slots_pinned
        self.queue_wait_s.fields("queue_wait_s", out)
        self.ttft_s.fields("ttft_s", out)
        self.tpot_s.fields("tpot_s", out)
        self.decode_horizon.fields("decode_horizon", out)
        return out


class NullEngineMetrics:
    """No-op twin for benchmark loops that must not pay even the
    timestamping cost (DecodeEngine(..., enable_metrics=False))."""

    engine_id = "disabled"

    def on_submit(self, req_id): pass

    def on_reject(self): pass

    def on_shed(self, req_id): pass

    def on_admit(self, req_id): pass

    def on_token(self, req_id, n=1): pass

    def on_tokens(self, req_id, n): pass

    def on_finish(self, req_id): pass

    def on_step(self, live_slots, queue_depth, tokens_emitted): pass

    def on_dispatch(self, horizon, host_syncs=1): pass

    def on_host_sync(self, n=1, nbytes=0): pass

    def on_tp_degree(self, tp): pass

    def on_pipeline_drain(self, depth, lag): pass

    def on_pipeline_flush(self, n=1): pass

    def on_pipeline_overrun(self, n): pass

    def on_prefix(self, *, hit, reused_tokens=0): pass

    def on_prefix_evictions(self, n=1): pass

    def on_kv_shared(self, n): pass

    def on_kv_cow(self, n=1): pass

    def on_preempt(self, n=1): pass

    def on_swap_out(self, nbytes): pass

    def on_swap_in(self, nbytes): pass

    def on_handoff_out(self, req_id, nbytes): pass

    def on_handoff_in(self, nbytes): pass

    def on_kv_pool(self, total, in_use, free, bytes_per_token=0.0): pass

    def on_prefill_batch(self, real_tokens, padded_tokens): pass

    def on_prefill_stall(self, n=1): pass

    def on_spec_round(self, rounds, proposed, accepted): pass

    def on_adapter_lookup(self, hit): pass

    def on_adapter_prefetch(self, n=1): pass

    def on_adapter_evict(self, n=1): pass

    def on_adapter_defer(self, n=1): pass

    def on_adapter_slots(self, total, resident, pinned): pass

    def observe_queue_depth(self, depth): pass

    def stats(self):
        return {}
