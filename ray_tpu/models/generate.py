"""Autoregressive generation with a KV cache, TPU-first.

The reference ships no generation loop (models are torch user code);
serving an LM is the flagship deployment though, so the decode path is
first-class here. XLA-friendly by construction: ONE jitted program for
prefill and one for the whole decode loop (`lax.scan` over steps), all
shapes static (cache is preallocated at `max_len`, live length carried
as a traced scalar), GQA K/V heads repeated at attention time only.

Consistency contract (tested): prefill+cached-decode logits equal the
full uncached `llama_forward` on the concatenated sequence.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import LlamaConfig, _rmsnorm, _rope

Params = Dict[str, Any]
Cache = Dict[str, jax.Array]  # {"k","v": [L, B, max_len, kv_heads, hd]}


def init_cache(cfg: LlamaConfig, batch_size: int,
               max_len: Optional[int] = None,
               sharding=None) -> Cache:
    """Zero KV cache ``[L, B, max_len, KV, D]``. ``sharding`` (an
    optional `jax.sharding.Sharding`) commits both arrays to a device
    mesh — the tensor-parallel engine shards the KV-head axis so each
    chip holds only its heads' cache."""
    max_len = max_len or cfg.max_seq_len
    shape = (cfg.n_layers, batch_size, max_len, cfg.n_kv_heads,
             cfg.head_dim)
    cache = {"k": jnp.zeros(shape, cfg.dtype),
             "v": jnp.zeros(shape, cfg.dtype)}
    if sharding is not None:
        cache = {k: jax.device_put(v, sharding) for k, v in cache.items()}
    return cache


def _cached_attention(q, k_cache, v_cache, q_slots, kv_valid_len,
                      cfg: LlamaConfig, slot_live=None):
    """q: [B, S, H, D]; caches [B, max_len, KV, D]. Attends q (written
    at cache slots q_slots [B, S]) over cache slots < kv_valid_len,
    causally (slot index <= query slot). ``slot_live`` [B, max_len]
    (optional) additionally masks dead slots — left-pad positions in a
    ragged batch."""
    B, S, H, D = q.shape
    max_len = k_cache.shape[1]
    rep = H // k_cache.shape[2]
    k = jnp.repeat(k_cache, rep, axis=2)  # [B, max_len, H, D]
    v = jnp.repeat(v_cache, rep, axis=2)
    logits = jnp.einsum("bshd,bthd->bhst", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits * (D ** -0.5)
    slots = jnp.arange(max_len)
    mask = (slots[None, None, None, :] <= q_slots[:, None, :, None]) \
        & (slots[None, None, None, :] < kv_valid_len)
    if slot_live is not None:
        mask = mask & slot_live[:, None, None, :]
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhst,bthd->bshd", probs, v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _lora_delta(x, ab, slots, dt):
    """Per-row gathered low-rank delta (S-LoRA): x [B, S, n_in] through
    row-selected adapter factors a [A, n_in, r] / b [A, r, n_out]
    (b pre-scaled by alpha/rank at pool registration) -> [B, S, n_out].
    Slot 0 holds the all-zero null adapter, so base-only rows compute
    an exactly-zero delta inside the same fused program."""
    a = ab["a"][slots].astype(dt)                 # [B, n_in, r]
    b = ab["b"][slots].astype(dt)                 # [B, r, n_out]
    return jnp.einsum("bsr,bro->bso",
                      jnp.einsum("bsi,bir->bsr", x, a), b)


def _layer_body(h, layer, k_cache, v_cache, positions, write_kv,
                q_slots, kv_valid_len, cfg: LlamaConfig,
                slot_live=None, attend=None, lora=None,
                lora_slots=None):
    """The decoder-layer math shared by ALL cached decode paths —
    generate.py's contiguous-chunk writes, engine.py's per-row
    scatter writes, and the paged engine's block-pool writes: rmsnorm
    → q/k/v projections → RoPE → cache write → causal cached attention
    → attn residual → gated MLP residual.

    The ONLY things that differ between the paths are how this chunk's
    K/V land in storage and how attention reads them back, so exactly
    those are injected: ``write_kv(k_cache, v_cache, k, v) ->
    (k_cache, v_cache)`` always, and optionally ``attend(q, k_cache,
    v_cache) -> o`` when the storage is not a dense [B, max_len] cache
    row (the paged engine passes `ops.attention.paged_attention` over
    its block pool — which stays op-for-op lockstep with
    `_cached_attention`, so token identity across paths holds). Every
    other op is shared by construction (a norm tweak or attention
    change here reaches every engine automatically).

    Multi-LoRA: ``lora`` (optional) is ONE layer's slice of the
    adapter-pool stacks ({name: {"a": [A, n_in, r], "b": [A, r,
    n_out]}}) and ``lora_slots`` [B] maps each row to its adapter
    slot; every projection named in the stacks gains a per-row
    `_lora_delta` on top of the shared base matmul. Both are pytree
    leaves of the enclosing jit — lora=None paths trace a program
    byte-identical to before this feature existed."""
    dt = cfg.dtype
    x = _rmsnorm(h, layer["attn_norm"], cfg.norm_eps)
    q = jnp.einsum("bsd,dhk->bshk", x, layer["wq"].astype(dt))
    k = jnp.einsum("bsd,dhk->bshk", x, layer["wk"].astype(dt))
    v = jnp.einsum("bsd,dhk->bshk", x, layer["wv"].astype(dt))
    if lora is not None:
        if "wq" in lora:
            q = q + _lora_delta(x, lora["wq"], lora_slots,
                                dt).reshape(q.shape)
        if "wk" in lora:
            k = k + _lora_delta(x, lora["wk"], lora_slots,
                                dt).reshape(k.shape)
        if "wv" in lora:
            v = v + _lora_delta(x, lora["wv"], lora_slots,
                                dt).reshape(v.shape)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    k_cache, v_cache = write_kv(k_cache, v_cache, k, v)
    if attend is not None:
        o = attend(q, k_cache, v_cache)
    else:
        o = _cached_attention(q, k_cache, v_cache, q_slots,
                              kv_valid_len, cfg, slot_live=slot_live)
    attn_out = jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(dt))
    if lora is not None and "wo" in lora:
        o_flat = o.reshape(o.shape[0], o.shape[1], -1)
        attn_out = attn_out + _lora_delta(o_flat, lora["wo"],
                                          lora_slots, dt)
    h = h + attn_out
    x = _rmsnorm(h, layer["mlp_norm"], cfg.norm_eps)
    gate = jnp.einsum("bsd,df->bsf", x, layer["w_gate"].astype(dt))
    up = jnp.einsum("bsd,df->bsf", x, layer["w_up"].astype(dt))
    if lora is not None:
        if "w_gate" in lora:
            gate = gate + _lora_delta(x, lora["w_gate"], lora_slots, dt)
        if "w_up" in lora:
            up = up + _lora_delta(x, lora["w_up"], lora_slots, dt)
    act = jax.nn.silu(gate) * up
    mlp_out = jnp.einsum("bsf,fd->bsd", act, layer["w_down"].astype(dt))
    if lora is not None and "w_down" in lora:
        mlp_out = mlp_out + _lora_delta(act, lora["w_down"],
                                        lora_slots, dt)
    h = h + mlp_out
    return h, k_cache, v_cache


def _cached_layer(h, layer, k_cache, v_cache, positions, slot_ids,
                  start, kv_valid_len, cfg: LlamaConfig,
                  slot_live=None):
    """One decoder layer over a chunk [B, S, d] whose K/V are WRITTEN
    into the cache at slots [start, start+S); ``positions`` are the
    ROPE position ids (per-row, pad-adjusted in ragged batches) while
    ``slot_ids`` are the cache slot indices the chunk occupies.
    Returns (h, k_cache, v_cache)."""

    def write_kv(k_cache, v_cache, k, v):
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, start, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, start, 0, 0))
        return k_cache, v_cache

    return _layer_body(h, layer, k_cache, v_cache, positions, write_kv,
                       slot_ids, kv_valid_len, cfg, slot_live=slot_live)


def forward_cached(params: Params, tokens: jax.Array, cache: Cache,
                   start, cfg: LlamaConfig, *,
                   positions: Optional[jax.Array] = None,
                   slot_live: Optional[jax.Array] = None
                   ) -> Tuple[jax.Array, Cache]:
    """Run a token chunk [B, S] at cache offset `start` (traced scalar
    ok), writing its K/V into the cache. Returns
    (logits [B, S, vocab] f32, updated cache). Prefill is one call with
    the whole prompt; decode is S=1 calls. ``positions`` overrides the
    RoPE position ids (ragged batches: left-pad rows start their real
    tokens at position 0); ``slot_live`` [B, max_len] masks dead (pad)
    cache slots out of every attention."""
    B, S = tokens.shape
    h = params["tok_embed"].astype(cfg.dtype)[tokens]
    slot_ids = start + jnp.broadcast_to(jnp.arange(S), (B, S))
    if positions is None:
        positions = slot_ids
    kv_valid_len = start + S

    def body(carry, xs):
        h = carry
        layer, k_c, v_c = xs
        h, k_c, v_c = _cached_layer(h, layer, k_c, v_c, positions,
                                    slot_ids, start, kv_valid_len, cfg,
                                    slot_live=slot_live)
        return h, (k_c, v_c)

    h, (k_new, v_new) = jax.lax.scan(
        body, h, (params["layers"], cache["k"], cache["v"]))
    h = _rmsnorm(h, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", h,
                        params["lm_head"].astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return logits, {"k": k_new, "v": v_new}


def forward_cached_rows(params: Params, tokens: jax.Array, cache: Cache,
                        starts: jax.Array, cfg: LlamaConfig, *,
                        adapters: Optional[Params] = None,
                        row_slot: Optional[jax.Array] = None
                        ) -> Tuple[jax.Array, Cache]:
    """Run a token chunk [B, S] with a PER-ROW cache offset: row b's
    tokens land at cache slots ``starts[b] + i`` (scatter writes) and
    attend that row's whole prefix ``[0, starts[b] + i]``. Returns
    (logits [B, S, vocab] f32, updated cache).

    This is the suffix-offset prefill entry for prefix-reuse serving:
    `forward_cached` prefills a chunk at ONE shared offset (solo
    generate, where every row starts at 0), while the engine admits
    rows whose cached-prefix lengths differ — each suffix must continue
    from its own row's frontier in the same batched program. Rows'
    slots below ``starts[b]`` must already hold valid K/V (a copied
    prefix and/or earlier chunks); slots at or beyond the chunk are
    excluded by the causal ``slot <= q_slot`` mask, so stale K/V from a
    slot's previous occupant is never attended. RoPE positions equal
    cache slots (no left-padding in slot-based serving).

    Write-before-attend: the whole chunk's K/V is scattered into the
    cache BEFORE the chunk attends, so re-running a chunk over slots
    whose previous contents are stale simply overwrites them. The
    engine's speculative path leans on this as its no-rollback cache
    discipline — a rejected draft window's K/V is left in place and the
    next round's verify chunk lands exactly on top of it, the causal
    mask hiding whatever lies beyond the chunk.

    Multi-LoRA: ``adapters`` is the full adapter-pool stack tree
    ({name: {"a": [L, A, n_in, r], "b": [L, A, r, n_out]}}, leading
    layer axis unstacked by the scan) and ``row_slot`` [B] int32 maps
    each row to its adapter slot (0 = base-only). Both absent -> the
    scan carries its original 3-tuple and the traced program is
    byte-identical to the pre-LoRA path."""
    B, S = tokens.shape
    h = params["tok_embed"].astype(cfg.dtype)[tokens]
    slot_ids = starts[:, None] + jnp.arange(S)[None, :]      # [B, S]
    bidx = jnp.arange(B)

    def body(carry, xs):
        h = carry
        if adapters is None:
            layer, k_c, v_c = xs
            lora = None
        else:
            layer, k_c, v_c, lora = xs

        def write_kv(k_cache, v_cache, k, v):
            k_cache = k_cache.at[bidx[:, None], slot_ids].set(
                k.astype(k_cache.dtype))
            v_cache = v_cache.at[bidx[:, None], slot_ids].set(
                v.astype(v_cache.dtype))
            return k_cache, v_cache

        h, k_c, v_c = _layer_body(h, layer, k_c, v_c, slot_ids,
                                  write_kv, slot_ids, k_c.shape[1], cfg,
                                  lora=lora, lora_slots=row_slot)
        return h, (k_c, v_c)

    xs = (params["layers"], cache["k"], cache["v"])
    if adapters is not None:
        xs = xs + (adapters,)
    h, (k_new, v_new) = jax.lax.scan(body, h, xs)
    h = _rmsnorm(h, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", h,
                        params["lm_head"].astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return logits, {"k": k_new, "v": v_new}


def filter_logits(logits: jax.Array, top_k: Optional[int] = None,
                  top_p: Optional[float] = None) -> jax.Array:
    """Mask logits outside the top-k / nucleus (top-p) candidate set to
    the dtype's min (so `jax.random.categorical` never samples them).

    [..., vocab] -> same shape. Both knobs are STATIC (one XLA program
    per (k, p) pair — serving reuses a handful of compiles); when both
    are given, top-k applies first, then top-p over the survivors (the
    usual composition). top_p=1.0 / top_k>=vocab are no-ops."""
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, logits.dtype)
    if top_k is not None:
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        if top_k < logits.shape[-1]:
            kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
            logits = jnp.where(logits < kth, neg, logits)
    if top_p is not None:
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_p < 1.0:
            idx = jnp.argsort(logits, axis=-1)[..., ::-1]
            sort = jnp.take_along_axis(logits, idx, axis=-1)
            probs = jax.nn.softmax(sort.astype(jnp.float32), axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            # keep tokens whose PRECEDING cumulative mass is still below
            # top_p; the argmax always survives (its preceding mass is 0)
            keep = (cum - probs) < top_p
            # scatter the keep-mask back through the argsort rather than
            # thresholding on the logit VALUE: a token tying the smallest
            # kept logit must not ride into the nucleus and inflate it
            inv = jnp.argsort(idx, axis=-1)
            keep = jnp.take_along_axis(keep, inv, axis=-1)
            logits = jnp.where(keep, logits, neg)
    return logits


@functools.partial(jax.jit, static_argnames=("top_k", "top_p"))
def _sample_token(logits: jax.Array, key: jax.Array, temperature,
                  top_k: Optional[int], top_p: Optional[float]) -> jax.Array:
    """[B, vocab] logits -> [B] sampled int32 (temperature + filters).
    Jitted (static knobs) so the streaming path's per-token sampling is
    one fused program, not op-by-op dispatches of sort/softmax/cumsum;
    inside `generate`'s already-jitted scan it simply inlines."""
    scaled = logits / jnp.maximum(temperature, 1e-6)
    scaled = filter_logits(scaled, top_k, top_p)
    return jax.random.categorical(key, scaled).astype(jnp.int32)


def step_rng_key(rng: jax.Array, step) -> jax.Array:
    """The ONE per-step sampling-key schedule: ``fold_in(rng, step)``.

    Deliberately independent of max_new_tokens, of the batch size, and
    of how many steps are fused into one program — the key for a row's
    i-th sampled token depends only on (rng, i). That invariance is
    what lets the continuous-batching engine fuse H decode iterations
    into one program (engine.py `_decode_multi`) and still reproduce a
    request's solo `generate` samples token-for-token: each request
    carries its own rng stream, folded with its own token index, no
    matter which batch companions or horizon boundaries it crosses."""
    return jax.random.fold_in(rng, step)


def sample_rows(logits: jax.Array, row_keys: jax.Array,
                tok_idx: jax.Array, *, greedy: bool, temperature,
                top_k: Optional[int], top_p: Optional[float]) -> jax.Array:
    """Per-ROW sampling inside an already-jitted decode program.

    logits [B, vocab] f32; row_keys [B, 2] uint32 (one rng stream per
    row); tok_idx [B] int32 (tokens that row has sampled so far). Row b
    draws with ``step_rng_key(row_keys[b], tok_idx[b])`` and its own
    categorical — bit-identical to a solo B=1 `generate` seeded with
    that row's rng (counter-mode bits make the [1, vocab] and [vocab]
    draws equal), so batched engine sampling can honor the per-request
    token-identity contract. Greedy ignores keys (argmax)."""
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    keys = jax.vmap(step_rng_key)(row_keys, tok_idx)
    scaled = logits / jnp.maximum(temperature, 1e-6)
    scaled = filter_logits(scaled, top_k, top_p)
    return jax.vmap(jax.random.categorical)(keys, scaled).astype(jnp.int32)


def _check_sampling_knobs(greedy: bool, top_k, top_p) -> None:
    """greedy=True (the default) argmaxes — refuse to silently drop
    explicitly-requested sampling filters."""
    if greedy and (top_k is not None or top_p is not None):
        raise ValueError(
            "top_k/top_p require greedy=False (greedy decoding ignores "
            "sampling filters)")


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_tokens", "greedy",
                                    "top_k", "top_p"))
def generate(params: Params, prompt: jax.Array, cfg: LlamaConfig, *,
             max_new_tokens: int = 32, temperature: float = 1.0,
             greedy: bool = True, eos_id: Optional[int] = None,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             prompt_live: Optional[jax.Array] = None,
             rng: Optional[jax.Array] = None) -> jax.Array:
    """prompt [B, P] int32 -> [B, P + max_new_tokens] int32.

    One compiled program: prefill writes the prompt's K/V, then a
    `lax.scan` emits max_new_tokens steps (static trip count — XLA
    unrolls nothing, reuses one step computation). With eos_id set,
    finished rows keep emitting eos (scan trip count stays static; the
    caller trims). Sampling (greedy=False) draws from the
    temperature-scaled distribution restricted by `filter_logits`'s
    static top_k / top_p knobs; token i's key is
    ``step_rng_key(rng, i)`` (see its docstring — the schedule is the
    cross-path sampling contract shared with the serving engine).

    Ragged batches: LEFT-pad prompts to a common length and pass
    ``prompt_live`` [B, P] (True = real token). Pad slots are masked
    out of every attention, RoPE positions start at 0 on each row's
    first real token, and every row's last real token lands on slot
    P-1 — so the uniform decode loop serves rows of different prompt
    lengths in one program (see ``pad_prompts``)."""
    B, P = prompt.shape
    max_len = P + max_new_tokens
    if max_len > cfg.max_seq_len:
        raise ValueError(f"{max_len} exceeds max_seq_len "
                         f"{cfg.max_seq_len}")
    _check_sampling_knobs(greedy, top_k, top_p)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    cache = init_cache(cfg, B, max_len)

    if prompt_live is not None:
        live = prompt_live.astype(bool)
        positions = jnp.maximum(
            jnp.cumsum(live.astype(jnp.int32), axis=1) - 1, 0)
        slot_live = jnp.concatenate(
            [live, jnp.ones((B, max_new_tokens), bool)], axis=1)
        n_real = live.sum(axis=1).astype(jnp.int32)          # [B]
    else:
        positions = None
        slot_live = None
        n_real = jnp.full((B,), P, jnp.int32)

    logits, cache = forward_cached(params, prompt, cache, 0, cfg,
                                   positions=positions,
                                   slot_live=slot_live)
    last = logits[:, -1]

    def sample(logits_row, i):
        if greedy:
            return jnp.argmax(logits_row, axis=-1).astype(jnp.int32)
        return _sample_token(logits_row, step_rng_key(rng, i),
                             temperature, top_k, top_p)

    def step(carry, i):
        cache, last_logits, slot, pos_ids, done = carry
        tok = sample(last_logits, i)
        if eos_id is not None:
            tok = jnp.where(done, eos_id, tok)
            done = done | (tok == eos_id)
        logits, cache = forward_cached(
            params, tok[:, None], cache, slot, cfg,
            positions=pos_ids[:, None], slot_live=slot_live)
        return (cache, logits[:, 0], slot + 1, pos_ids + 1, done), tok

    done0 = jnp.zeros((B,), bool)
    (_, _, _, _, _), toks = jax.lax.scan(
        step, (cache, last, P, n_real, done0),
        jnp.arange(max_new_tokens))
    return jnp.concatenate([prompt, toks.T], axis=1)


# Donated cache: each step consumes the previous cache exactly once —
# without donation every step would COPY the whole [L,B,max_len,KV,D]
# cache across the jit boundary (multi-GB per token at real configs).
@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache",))
def _prefill_jit(params, prompt, cache, cfg, positions=None,
                 slot_live=None):
    return forward_cached(params, prompt, cache, 0, cfg,
                          positions=positions, slot_live=slot_live)


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache",))
def _decode_step_jit(params, tok, cache, slot, pos_ids, cfg,
                     slot_live=None):
    return forward_cached(params, tok[:, None], cache, slot, cfg,
                          positions=pos_ids[:, None],
                          slot_live=slot_live)


def generate_stream(params, prompt, cfg: LlamaConfig, *,
                    max_new_tokens: int = 32,
                    eos_id: Optional[int] = None,
                    temperature: float = 1.0, greedy: bool = True,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    prompt_live: Optional[jax.Array] = None,
                    rng: Optional[jax.Array] = None):
    """Decode as a PYTHON GENERATOR yielding one [B] token
    array per step — the token-streaming serving path (each step is
    one cached jitted program with a donated KV cache; `generate`'s
    scanned loop is the lower-latency batch path when streaming isn't
    needed). Stops early when every row has emitted eos. Ragged
    batches: LEFT-pad and pass ``prompt_live`` exactly as with
    `generate`. Sampling (greedy=False, temperature/top_k/top_p) uses
    `generate`'s exact per-step key schedule, so a streamed run with
    the same rng yields token-identical output to the batch path.

    Validation runs EAGERLY (this is a plain function returning the
    generator): bad knobs fail at the call site, not mid-stream at the
    first next()."""
    B, P = prompt.shape
    max_len = P + max_new_tokens
    if max_len > cfg.max_seq_len:
        raise ValueError(f"{max_len} exceeds max_seq_len "
                         f"{cfg.max_seq_len}")
    _check_sampling_knobs(greedy, top_k, top_p)
    return _stream_inner(params, prompt, cfg, max_new_tokens, eos_id,
                         temperature, greedy, top_k, top_p,
                         prompt_live, rng)


def _stream_inner(params, prompt, cfg, max_new_tokens, eos_id,
                  temperature, greedy, top_k, top_p, prompt_live, rng):
    import numpy as np

    B, P = prompt.shape
    max_len = P + max_new_tokens
    cache = init_cache(cfg, B, max_len)
    if prompt_live is not None:
        live = prompt_live.astype(bool)
        positions = jnp.maximum(
            jnp.cumsum(live.astype(jnp.int32), axis=1) - 1, 0)
        slot_live = jnp.concatenate(
            [live, jnp.ones((B, max_new_tokens), bool)], axis=1)
        pos = live.sum(axis=1).astype(jnp.int32)
    else:
        positions = None
        slot_live = None
        pos = jnp.full((B,), P, jnp.int32)
    logits, cache = _prefill_jit(params, prompt, cache, cfg,
                                 positions=positions,
                                 slot_live=slot_live)
    last = logits[:, -1]
    done = np.zeros((B,), bool)
    if not greedy:
        rng = rng if rng is not None else jax.random.PRNGKey(0)
    for step in range(max_new_tokens):
        if greedy:
            tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
        else:
            tok = _sample_token(last, step_rng_key(rng, step),
                                temperature, top_k, top_p)
        if eos_id is not None:
            tok = jnp.where(jnp.asarray(done), eos_id, tok)
        tok_np = np.asarray(tok)  # graftlint: disable=host-sync -- solo streaming yields one host token per step by contract; the engine path amortises via _device_get
        yield tok_np
        if eos_id is not None:
            done = done | (tok_np == eos_id)
            if done.all():
                return
        if step + 1 < max_new_tokens:
            logits, cache = _decode_step_jit(
                params, tok, cache, P + step, pos + step, cfg,
                slot_live=slot_live)
            last = logits[:, 0]


def pad_prompts(prompts, pad_id: int = 0, *, bucket_len: bool = False,
                pad_batch_to: Optional[int] = None):
    """Left-pad a ragged list of token lists to a dense [B, P] array +
    the matching ``prompt_live`` mask for `generate`.

    Empty prompts are rejected: a fully-dead row has no last real
    token to sample from (its attention would be all-masked garbage) —
    prepend a BOS token instead.

    Serving knobs (jit-cache hygiene — every distinct (B, P) pair is a
    separate XLA compile): ``bucket_len=True`` rounds P up to the next
    power of two, and ``pad_batch_to=N`` appends single-token filler
    rows up to batch N (the CALLER slices its outputs back to the real
    row count) — together a handful of compiles cover all traffic."""
    import numpy as np

    if not prompts:
        raise ValueError("pad_prompts needs at least one prompt")
    if any(len(p) == 0 for p in prompts):
        raise ValueError(
            "empty prompt: generation needs at least one real token "
            "per row (prepend a BOS token)")
    n_rows = len(prompts)
    rows = list(prompts)
    if pad_batch_to is not None and n_rows < pad_batch_to:
        rows += [[pad_id]] * (pad_batch_to - n_rows)
    P = max(len(p) for p in rows)
    if bucket_len:
        P = 1 << (P - 1).bit_length()
    out = np.full((len(rows), P), pad_id, np.int32)
    live = np.zeros((len(rows), P), bool)
    for i, p in enumerate(rows):
        out[i, P - len(p):] = np.asarray(p, np.int32)
        live[i, P - len(p):] = True
    return out, live
