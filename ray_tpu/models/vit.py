"""Vision Transformer (ViT) classifier, TPU-first.

Same design language as the Llama family (models/llama.py): pure
functional params, scanned encoder layers (`lax.scan` — O(1) compile in
depth), logical-axis trees driving GSPMD sharding over the dp/fsdp/tp
mesh, bf16 activations / f32 master params, per-layer remat. Patchify
is a reshape (no conv): [B,H,W,C] → [B, N, p*p*C] → linear embed, so
the whole forward is MXU matmuls.

Reference capability: the reference trains vision models through Ray
Train as opaque torch modules (python/ray/train/torch/); here the
vision family is a first-class GSPMD citizen sharing
`make_sharded_train_step` with the LM flagship.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops import attention
from ray_tpu.parallel.sharding import LogicalAxisRules, logical_to_mesh

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 32
    patch_size: int = 4
    channels: int = 3
    dim: int = 192
    n_layers: int = 6
    n_heads: int = 6
    ffn_dim: int = 768
    num_classes: int = 10
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    def __post_init__(self):
        if self.image_size % self.patch_size:
            raise ValueError("patch_size must divide image_size")
        if self.dim % self.n_heads:
            raise ValueError("n_heads must divide dim")

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.channels

    def num_params(self) -> int:
        # Mirrors vit_init exactly: per layer 4 LN vectors (4d), qkv
        # (3d^2) + out (d^2) projections, MLP w_in/b_in/w_out/b_out
        # (2df + f + d); top level patch_embed (no bias), cls, pos,
        # final LN pair, bias-free head.
        d, f = self.dim, self.ffn_dim
        per_layer = 4 * d * d + 2 * d * f + 5 * d + f
        return (self.patch_dim * d + d + (self.n_patches + 1) * d +
                self.n_layers * per_layer + 2 * d +
                d * self.num_classes)


def _layer_shapes(cfg: ViTConfig) -> Dict[str, tuple]:
    d, f = cfg.dim, cfg.ffn_dim
    return {
        # name: (shape, logical axes, fan_in or None-for-scale/bias)
        "ln1_scale": ((d,), ("embed",), None),
        "ln1_bias": ((d,), ("embed",), 0),
        "wqkv": ((d, 3 * d), ("embed", "qkv"), d),
        "wo": ((d, d), ("heads", "embed"), d),
        "ln2_scale": ((d,), ("embed",), None),
        "ln2_bias": ((d,), ("embed",), 0),
        "w_in": ((d, f), ("embed", "mlp"), d),
        "b_in": ((f,), ("mlp",), 0),
        "w_out": ((f, d), ("mlp", "embed"), f),
        "b_out": ((d,), ("embed",), 0),
    }


def vit_init(rng: jax.Array, cfg: ViTConfig) -> Params:
    shapes = _layer_shapes(cfg)
    keys = jax.random.split(rng, len(shapes) + 4)
    layers = {}
    for i, (name, (shape, _, fan_in)) in enumerate(shapes.items()):
        full = (cfg.n_layers,) + shape
        if fan_in is None:
            layers[name] = jnp.ones(full, cfg.param_dtype)
        elif fan_in == 0:
            layers[name] = jnp.zeros(full, cfg.param_dtype)
        else:
            layers[name] = (jax.random.normal(keys[i], full) *
                            fan_in ** -0.5).astype(cfg.param_dtype)
    return {
        "patch_embed": (jax.random.normal(
            keys[-4], (cfg.patch_dim, cfg.dim)) *
            cfg.patch_dim ** -0.5).astype(cfg.param_dtype),
        "cls_token": jnp.zeros((cfg.dim,), cfg.param_dtype),
        "pos_embed": (jax.random.normal(
            keys[-3], (cfg.n_patches + 1, cfg.dim)) * 0.02
            ).astype(cfg.param_dtype),
        "layers": layers,
        "final_ln_scale": jnp.ones((cfg.dim,), cfg.param_dtype),
        "final_ln_bias": jnp.zeros((cfg.dim,), cfg.param_dtype),
        "head": (jax.random.normal(
            keys[-1], (cfg.dim, cfg.num_classes)) * cfg.dim ** -0.5
            ).astype(cfg.param_dtype),
    }


def vit_logical_specs(cfg: ViTConfig) -> Params:
    layer_specs = {name: ("layers",) + logical
                   for name, (_, logical, _f) in _layer_shapes(cfg).items()}
    return {
        "patch_embed": (None, "embed"),
        "cls_token": ("embed",),
        "pos_embed": (None, "embed"),
        "layers": layer_specs,
        "final_ln_scale": ("embed",),
        "final_ln_bias": ("embed",),
        "head": ("embed", "vocab"),   # classes shard like the LM head
    }


def vit_param_specs(cfg: ViTConfig,
                    rules: Optional[LogicalAxisRules] = None) -> Params:
    return jax.tree_util.tree_map(
        lambda logical: logical_to_mesh(logical, rules),
        vit_logical_specs(cfg),
        is_leaf=lambda x: isinstance(x, tuple) and all(
            isinstance(e, (str, type(None))) for e in x))


def _layernorm(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mean = x32.mean(-1, keepdims=True)
    var = ((x32 - mean) ** 2).mean(-1, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return out.astype(x.dtype) * scale.astype(x.dtype) + \
        bias.astype(x.dtype)


def _encoder_layer(cfg: ViTConfig, x: jax.Array,
                   layer: Dict[str, jax.Array]) -> jax.Array:
    B, N, d = x.shape
    h, hd = cfg.n_heads, cfg.dim // cfg.n_heads
    y = _layernorm(x, layer["ln1_scale"], layer["ln1_bias"], cfg.norm_eps)
    qkv = (y @ layer["wqkv"].astype(y.dtype)).reshape(B, N, 3, h, hd)
    q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
    att = attention(q, k, v, causal=False)          # [B, h, N, hd]
    att = att.transpose(0, 2, 1, 3).reshape(B, N, d)
    x = x + att @ layer["wo"].astype(att.dtype)
    y = _layernorm(x, layer["ln2_scale"], layer["ln2_bias"], cfg.norm_eps)
    y = jax.nn.gelu(y @ layer["w_in"].astype(y.dtype) +
                    layer["b_in"].astype(y.dtype))
    return x + (y @ layer["w_out"].astype(y.dtype) +
                layer["b_out"].astype(y.dtype))


def vit_forward(params: Params, images: jax.Array,
                cfg: ViTConfig) -> jax.Array:
    """images [B, H, W, C] → class logits [B, num_classes] (f32)."""
    B = images.shape[0]
    p = cfg.patch_size
    g = cfg.image_size // p
    x = images.astype(cfg.dtype).reshape(B, g, p, g, p, cfg.channels)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(B, g * g, cfg.patch_dim)
    x = x @ params["patch_embed"].astype(cfg.dtype)
    cls = jnp.broadcast_to(params["cls_token"].astype(cfg.dtype),
                           (B, 1, cfg.dim))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(cfg.dtype)

    def body(carry, layer):
        fn = _encoder_layer
        if cfg.remat:
            fn = jax.checkpoint(fn, static_argnums=(0,))
        return fn(cfg, carry, layer), None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = _layernorm(x[:, 0], params["final_ln_scale"],
                   params["final_ln_bias"], cfg.norm_eps)
    return (x @ params["head"].astype(x.dtype)).astype(jnp.float32)


def vit_loss(params: Params, batch: Dict[str, jax.Array],
             cfg: ViTConfig) -> jax.Array:
    """Softmax cross-entropy on {'images': [B,H,W,C], 'labels': [B]}."""
    logits = vit_forward(params, batch["images"], cfg)
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(
        logp, batch["labels"][:, None].astype(jnp.int32), axis=-1)[:, 0]
    return nll.mean()
