"""Llama-family decoder LM, TPU-first.

Design choices (vs a torch translation):
- Pure functional: params are a pytree dict; a parallel tree of logical
  axis names drives GSPMD sharding (ray_tpu.parallel.sharding rules map
  them onto the dp/fsdp/tp/sp mesh).
- All layers are stacked and iterated with `lax.scan` ("scanned layers"),
  so compile time is O(1) in depth and XLA pipelines the weight
  all-gathers of layer i+1 under the compute of layer i.
- bf16 activations / f32 master params by default; matmuls hit the MXU.
- Attention via ray_tpu.ops (Pallas flash attention on TPU; ring
  attention over the `sp` axis for long context).
- `jax.checkpoint` (remat) per layer to trade FLOPs for HBM.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.ad_checkpoint
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.ops import attention
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.parallel.sharding import logical_to_mesh, LogicalAxisRules

Params = Dict[str, Any]

# checkpoint_name tags available to remat_policy="save:...". Each marks
# one dot output in _decoder_layer; saving it exempts that matmul (and
# everything downstream of it that is also saved) from the backward-pass
# recompute. ffn_gate+ffn_up are the FLOPs-heaviest (2/3 of the MLP);
# qkv covers the three attention input projections.
REMAT_SAVE_NAMES = frozenset(
    {"qkv", "attn_out", "wo_out", "ffn_gate", "ffn_up", "ffn_down"})


def _parse_save_names(policy: str) -> list:
    """'save:a+b' -> ['a', 'b']; raises on empty or unknown names."""
    names = [n for n in policy[len("save:"):].split("+") if n]
    bad = [n for n in names if n not in REMAT_SAVE_NAMES]
    if not names or bad:
        raise ValueError(
            f"remat_policy {policy!r}: "
            + (f"unknown names {bad}" if bad else "no names given")
            + f" (valid: {sorted(REMAT_SAVE_NAMES)})")
    return names


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    ffn_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16          # activation dtype
    param_dtype: Any = jnp.float32     # master weights
    remat: bool = True
    # Per-layer checkpoint policy: "full" recomputes everything (min
    # HBM), "save_dots" keeps matmul outputs (recompute only cheap
    # elementwise — more HBM, fewer recomputed FLOPs), or
    # "save:<name>+<name>+..." keeps only the NAMED dot outputs
    # (checkpoint_name tags in _decoder_layer) — the HBM/recompute
    # frontier in between. Valid names: REMAT_SAVE_NAMES.
    remat_policy: str = "full"
    attn_impl: str = "auto"            # auto|flash|reference|ring
    ring_axis: str = "sp"
    # Flash-kernel tile sizes (None = kernel default). Chip-dependent:
    # larger tiles amortize the per-block softmax rescale; sweep with
    # tools/remat_sweep.py-style timing before changing.
    flash_block_q: Optional[int] = None
    flash_block_k: Optional[int] = None
    # Cross-entropy sequence chunking: compute the vocab projection +
    # softmax loss loss_chunk tokens at a time (lax.map + remat) instead
    # of materializing the full [B, S, vocab] f32 logits. At S=2048 this
    # is MFU-neutral (measured; XLA handles the 2 GiB fine) — its purpose
    # is long-context training, where S=32k logits (e.g. B4xS32k x 32k
    # vocab = 16 GiB f32) cannot exist. None = unchunked. Ignored when
    # S % loss_chunk != 0.
    loss_chunk: Optional[int] = None

    def __post_init__(self):
        # validated here, not in dispatch: every attention path (flash,
        # ring, ulysses) receives these
        for nm in ("flash_block_q", "flash_block_k"):
            b = getattr(self, nm)
            if b is not None and b <= 0:
                raise ValueError(f"{nm} must be positive, got {b}")
        if self.remat_policy in ("full", "save_dots"):
            return
        if self.remat_policy.startswith("save:"):
            _parse_save_names(self.remat_policy)
            return
        raise ValueError(
            f"unknown remat_policy {self.remat_policy!r} "
            "(expected 'full', 'save_dots', or 'save:<names>')")

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    # ---- presets ----
    @staticmethod
    def llama2_7b(**kw) -> "LlamaConfig":
        return LlamaConfig(**kw)

    @staticmethod
    def llama2_13b(**kw) -> "LlamaConfig":
        return LlamaConfig(dim=5120, n_layers=40, n_heads=40, n_kv_heads=40,
                           ffn_dim=13824, **kw)

    @staticmethod
    def llama3_8b(**kw) -> "LlamaConfig":
        return LlamaConfig(vocab_size=128256, dim=4096, n_layers=32,
                           n_heads=32, n_kv_heads=8, ffn_dim=14336,
                           rope_theta=500000.0, max_seq_len=8192, **kw)

    @staticmethod
    def nano(**kw) -> "LlamaConfig":
        """Tiny config for tests / dryruns (runs on the CPU mesh)."""
        defaults = dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, ffn_dim=128, max_seq_len=128,
                        dtype=jnp.float32, remat=False)
        defaults.update(kw)
        return LlamaConfig(**defaults)

    def num_params(self) -> int:
        d, v, f, L = self.dim, self.vocab_size, self.ffn_dim, self.n_layers
        hd = self.head_dim
        attn = d * self.n_heads * hd + 2 * d * self.n_kv_heads * hd \
            + self.n_heads * hd * d
        mlp = 3 * d * f
        return v * d + L * (attn + mlp + 2 * d) + d + d * v


# ---------------------------------------------------------------------------
# Parameter init + sharding specs
# ---------------------------------------------------------------------------

def _layer_shapes(cfg: LlamaConfig) -> Dict[str, Any]:
    """name -> (shape, logical axes, fan_in of the contraction)."""
    d, hd = cfg.dim, cfg.head_dim
    return {
        "wq": ((d, cfg.n_heads, hd), ("embed", "heads", "head_dim"), d),
        "wk": ((d, cfg.n_kv_heads, hd), ("embed", "kv", "head_dim"), d),
        "wv": ((d, cfg.n_kv_heads, hd), ("embed", "kv", "head_dim"), d),
        "wo": ((cfg.n_heads, hd, d), ("heads", "head_dim", "embed"),
               cfg.n_heads * hd),
        "w_gate": ((d, cfg.ffn_dim), ("embed", "mlp"), d),
        "w_up": ((d, cfg.ffn_dim), ("embed", "mlp"), d),
        "w_down": ((cfg.ffn_dim, d), ("mlp", "embed"), cfg.ffn_dim),
        "attn_norm": ((d,), ("embed",), None),
        "mlp_norm": ((d,), ("embed",), None),
    }


def llama_init(rng: jax.Array, cfg: LlamaConfig) -> Params:
    """Stacked-layer param tree: every per-layer leaf has leading [n_layers]."""
    shapes = _layer_shapes(cfg)
    keys = jax.random.split(rng, len(shapes) + 3)

    layers = {}
    for i, (name, (shape, _, fan_in)) in enumerate(shapes.items()):
        if fan_in is None:  # norm scales
            layers[name] = jnp.ones((cfg.n_layers,) + shape, cfg.param_dtype)
        else:
            layers[name] = (jax.random.normal(
                keys[i], (cfg.n_layers,) + shape) * fan_in ** -0.5
                ).astype(cfg.param_dtype)
    return {
        "tok_embed": (jax.random.normal(
            keys[-3], (cfg.vocab_size, cfg.dim)) * 0.02
            ).astype(cfg.param_dtype),
        "layers": layers,
        "final_norm": jnp.ones((cfg.dim,), cfg.param_dtype),
        "lm_head": (jax.random.normal(
            keys[-1], (cfg.dim, cfg.vocab_size)) * cfg.dim ** -0.5
            ).astype(cfg.param_dtype),
    }


def llama_logical_specs(cfg: LlamaConfig) -> Params:
    """Tree of logical-axis tuples matching llama_init's tree."""
    layer_specs = {name: ("layers",) + logical
                   for name, (_, logical, _f) in _layer_shapes(cfg).items()}
    return {
        "tok_embed": ("vocab", "embed"),
        "layers": layer_specs,
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


def llama_param_specs(cfg: LlamaConfig,
                      rules: Optional[LogicalAxisRules] = None) -> Params:
    """Tree of PartitionSpecs for the param tree under the given rules."""
    return jax.tree_util.tree_map(
        lambda logical: logical_to_mesh(logical, rules),
        llama_logical_specs(cfg),
        is_leaf=lambda x: isinstance(x, tuple) and all(
            isinstance(e, (str, type(None))) for e in x))


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _rmsnorm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * rms).astype(x.dtype) * scale.astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """x: [B, S, H, D]; rotate pairs (d, d + D/2)."""
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(x.dtype)


def _attention_call(q, k, v, cfg: LlamaConfig):
    """q,k,v: [B, S, H, D] -> [B, S, H, D]."""
    qT = q.transpose(0, 2, 1, 3)
    kT = k.transpose(0, 2, 1, 3)
    vT = v.transpose(0, 2, 1, 3)
    blocks = {k_: v_ for k_, v_ in (("block_q", cfg.flash_block_q),
                                    ("block_k", cfg.flash_block_k))
              if v_ is not None}
    if cfg.attn_impl == "ring":
        out = ring_attention(qT, kT, vT, axis_name=cfg.ring_axis,
                             causal=True, **blocks)
    elif cfg.attn_impl == "ulysses":
        from ray_tpu.ops.ulysses import ulysses_attention

        out = ulysses_attention(qT, kT, vT, axis_name=cfg.ring_axis,
                                causal=True, **blocks)
    else:
        out = attention(qT, kT, vT, causal=True, impl=cfg.attn_impl,
                        **blocks)
    return out.transpose(0, 2, 1, 3)


def _decoder_layer(h: jax.Array, layer: Params, positions: jax.Array,
                   cfg: LlamaConfig) -> jax.Array:
    dt = cfg.dtype
    name = jax.ad_checkpoint.checkpoint_name
    x = _rmsnorm(h, layer["attn_norm"], cfg.norm_eps)
    q = name(jnp.einsum("bsd,dhk->bshk", x, layer["wq"].astype(dt)), "qkv")
    k = name(jnp.einsum("bsd,dhk->bshk", x, layer["wk"].astype(dt)), "qkv")
    v = name(jnp.einsum("bsd,dhk->bshk", x, layer["wv"].astype(dt)), "qkv")
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    o = name(_attention_call(q, k, v, cfg), "attn_out")
    h = h + name(jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(dt)),
                 "wo_out")

    x = _rmsnorm(h, layer["mlp_norm"], cfg.norm_eps)
    gate = name(jnp.einsum("bsd,df->bsf", x, layer["w_gate"].astype(dt)),
                "ffn_gate")
    up = name(jnp.einsum("bsd,df->bsf", x, layer["w_up"].astype(dt)),
              "ffn_up")
    h = h + name(jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up,
                            layer["w_down"].astype(dt)), "ffn_down")
    return h


def llama_hidden(params: Params, tokens: jax.Array, cfg: LlamaConfig,
                 positions: Optional[jax.Array] = None) -> jax.Array:
    """tokens [B, S] int32 -> final-norm hidden states [B, S, dim]
    (activation dtype) — the backbone without the vocab projection."""
    if positions is None:
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1]), tokens.shape)
    h = params["tok_embed"].astype(cfg.dtype)[tokens]

    layer_fn = functools.partial(_decoder_layer, positions=positions, cfg=cfg)
    if cfg.remat:
        if cfg.remat_policy == "save_dots":
            layer_fn = jax.checkpoint(
                layer_fn,
                policy=jax.checkpoint_policies
                .dots_with_no_batch_dims_saveable)
        elif cfg.remat_policy.startswith("save:"):
            layer_fn = jax.checkpoint(
                layer_fn,
                policy=jax.checkpoint_policies.save_only_these_names(
                    *_parse_save_names(cfg.remat_policy)))
        else:  # "full" — validated in LlamaConfig.__post_init__
            layer_fn = jax.checkpoint(layer_fn)

    def scan_body(h, layer):
        return layer_fn(h, layer), None

    h, _ = jax.lax.scan(scan_body, h, params["layers"])
    return _rmsnorm(h, params["final_norm"], cfg.norm_eps)


def llama_forward(params: Params, tokens: jax.Array, cfg: LlamaConfig,
                  positions: Optional[jax.Array] = None) -> jax.Array:
    """tokens [B, S] int32 -> logits [B, S, vocab] (float32)."""
    h = llama_hidden(params, tokens, cfg, positions)
    logits = jnp.einsum("bsd,dv->bsv", h, params["lm_head"].astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return logits


def _nll(h: jax.Array, targets: jax.Array, lm_head: jax.Array,
         cfg: LlamaConfig) -> jax.Array:
    """[.., S, d] hidden + [.., S] targets -> [.., S] token nll (f32)."""
    logits = jnp.einsum("...sd,dv->...sv", h, lm_head.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]


def llama_loss(params: Params, batch: Dict[str, jax.Array],
               cfg: LlamaConfig) -> jax.Array:
    """Next-token cross-entropy. batch: {'tokens': [B,S]} or
    {'inputs': [B,S], 'targets': [B,S]} (optional 'mask').

    With cfg.loss_chunk set (and dividing S), the vocab projection +
    softmax run loss_chunk tokens at a time under lax.map + remat: the
    [B, S, vocab] f32 logits are never materialized and the backward
    recomputes one chunk's projection instead of saving softmax
    residuals for the whole sequence — identical loss/grads (tested),
    lower HBM traffic."""
    if "inputs" in batch:
        inputs, targets = batch["inputs"], batch["targets"]
        mask = batch.get("mask")
    else:
        inputs, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
        mask = None
    h = llama_hidden(params, inputs, cfg)
    B, S = targets.shape
    chunk = cfg.loss_chunk
    if chunk and S % chunk == 0 and S > chunk:
        n = S // chunk
        h_c = h.reshape(B, n, chunk, cfg.dim).transpose(1, 0, 2, 3)
        t_c = targets.reshape(B, n, chunk).transpose(1, 0, 2)
        nll = jax.lax.map(
            jax.checkpoint(lambda ht: _nll(ht[0], ht[1],
                                           params["lm_head"], cfg)),
            (h_c, t_c))                      # [n, B, chunk]
        nll = nll.transpose(1, 0, 2).reshape(B, S)
    else:
        nll = _nll(h, targets, params["lm_head"], cfg)
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)
    return jnp.mean(nll)


def llama_flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    """Approximate training FLOPs/token (fwd+bwd): 6*N + attention term."""
    n = cfg.num_params()
    attn = 12 * cfg.n_layers * cfg.dim * seq_len  # causal: *0.5 of full
    return 6.0 * n + attn * 0.5
