"""Speculative decoding: a small draft model proposes, the target model
verifies — exact greedy equivalence at a fraction of the target's
sequential steps.

Reference counterpart: none (the reference ships no generation loop at
all); this is a TPU-native serving-latency capability on top of the
models/generate.py cache machinery.

Why it fits TPU: the target model stops being a chain of S sequential
single-token programs and becomes S/(c+1) chunk-verify programs of
width k+1 — wide enough to feed the MXU — while the cheap draft model
eats the sequential latency. Greedy acceptance (token match against the
target's argmax) makes the output provably identical to target-only
greedy decode (tested).

Cache discipline (no explicit rollback): `forward_cached` masks
attention to slots < kv_valid_len = start + S. Rejected candidates'
K/V entries live at slots >= the accepted position, which is exactly
where the next round's chunk starts writing — so stale entries are
never attended before they are overwritten. The draft consumes a CHUNK
of not-yet-written tokens each round (1 normally; 2 after a fully
accepted window, whose last draft token never became a draft input) so
neither cache ever has a hole behind its valid frontier.

Scope: batch size 1 (speculation is an interactive-latency
optimization; batched throughput serving uses `generate`'s scanned
batch decode, where the MXU is already fed by the batch dimension).
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.generate import (_prefill_jit, forward_cached,
                                     init_cache)
from ray_tpu.models.llama import LlamaConfig
from ray_tpu.util.metrics import Counter, Gauge

Params = Dict[str, Any]


@dataclasses.dataclass
class SpecStats:
    """Per-call acceptance telemetry (drives draft-model/window tuning)."""
    rounds: int = 0
    proposed: int = 0
    accepted: int = 0

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0


_spec_ids = itertools.count()


class SpecMetrics:
    """Publish SpecStats through the util.metrics Prometheus plane, the
    way EngineMetrics publishes DecodeEngine telemetry: pass one
    instance to `speculative_generate(..., metrics=...)` and every
    call's rounds/proposed/accepted land as tagged counters (plus an
    acceptance-rate gauge) next to the llm_engine_* series — so
    draft-model tuning reads off the same dashboard as serving. All
    instruments carry a ``spec`` tag (one draft/target pairing = one
    tag value); `stats()` returns the flat numeric snapshot."""

    def __init__(self, *, spec_id: Optional[str] = None):
        self.spec_id = spec_id or f"spec-{next(_spec_ids)}"
        self.calls = 0
        self.rounds = 0
        self.proposed = 0
        self.accepted = 0

        tag = {"spec": self.spec_id}
        keys = ("spec",)

        def counter(name, desc):
            return Counter(name, desc, tag_keys=keys).set_default_tags(tag)

        self._m_calls = counter(
            "llm_spec_calls_total",
            "speculative_generate invocations")
        self._m_rounds = counter(
            "llm_spec_rounds_total",
            "Draft-propose / target-verify rounds")
        self._m_proposed = counter(
            "llm_spec_proposed_total",
            "Draft tokens proposed for verification")
        self._m_accepted = counter(
            "llm_spec_accepted_total",
            "Draft tokens accepted by the target")
        self._m_rate = Gauge(
            "llm_spec_acceptance_rate",
            "Cumulative accepted / proposed (0..1)",
            tag_keys=keys).set_default_tags(tag)

    def observe(self, stats: SpecStats) -> None:
        """Fold one call's SpecStats into the cumulative series."""
        self.calls += 1
        self.rounds += stats.rounds
        self.proposed += stats.proposed
        self.accepted += stats.accepted
        self._m_calls.inc()
        if stats.rounds > 0:
            self._m_rounds.inc(stats.rounds)
        if stats.proposed > 0:
            self._m_proposed.inc(stats.proposed)
        if stats.accepted > 0:
            self._m_accepted.inc(stats.accepted)
        self._m_rate.set(self.acceptance_rate)

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0

    def stats(self) -> Dict[str, float]:
        """Flat numeric snapshot, gauge-friendly like
        EngineMetrics.stats() (all ratios 0.0 before any call)."""
        return {
            "calls": float(self.calls),
            "rounds": float(self.rounds),
            "proposed": float(self.proposed),
            "accepted": float(self.accepted),
            "acceptance_rate": self.acceptance_rate,
            "rounds_per_call": (self.rounds / self.calls
                                if self.calls else 0.0),
        }


@functools.partial(jax.jit, static_argnames=("cfg", "width"),
                   donate_argnames=("cache",))
def _draft_propose(params, chunk, cache, start, cfg, width):
    """Consume `chunk` [B, m] at cache slot `start` (appending its K/V),
    then greedily roll `width` proposals. Returns
    (proposals [B, width], cache); the cache gains K/V for the chunk and
    the first width-1 proposals (the last proposal is never an input)."""
    logits, cache = forward_cached(params, chunk, cache, start, cfg)
    first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    m = chunk.shape[1]

    def step(carry, _):
        tok, cache, slot = carry
        logits, cache = forward_cached(params, tok[:, None], cache, slot,
                                       cfg)
        nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
        return (nxt, cache, slot + 1), tok

    (last, cache, _), toks = jax.lax.scan(
        step, (first, cache, start + m), None, length=width - 1)
    proposals = jnp.concatenate([toks.T, last[:, None]], axis=1) \
        if width > 1 else last[:, None]
    return proposals, cache


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache",))
def _verify_chunk(params, chunk, cache, start, cfg):
    """Target forward over [last_emitted, d_1..d_w] at slot `start`;
    returns (argmax tokens [B, w+1], cache) — entry i is the target's
    greedy continuation of chunk[:, :i+1]."""
    logits, cache = forward_cached(params, chunk, cache, start, cfg)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache


def speculative_generate(
    target_params: Params, target_cfg: LlamaConfig,
    draft_params: Params, draft_cfg: LlamaConfig,
    prompt, *, max_new_tokens: int = 32, window: int = 4,
    eos_id: Optional[int] = None,
    metrics: Optional[SpecMetrics] = None,
) -> Tuple[jax.Array, SpecStats]:
    """prompt [1, P] int32 -> ([1, P + n] int32, stats), n <=
    max_new_tokens (early eos stops short, like `generate_stream`).

    Greedy only: emitted tokens are IDENTICAL to
    ``generate(target_params, prompt, target_cfg, greedy=True)`` up to
    eos/max_new_tokens truncation (tested). Draft and target must share
    the vocabulary. Pass a `SpecMetrics` to publish this call's
    acceptance telemetry to the util.metrics Prometheus plane."""
    if target_cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError(
            f"draft vocab {draft_cfg.vocab_size} != target vocab "
            f"{target_cfg.vocab_size}")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    prompt = jnp.asarray(prompt, jnp.int32)
    B, P = prompt.shape
    if B != 1:
        raise ValueError(
            "speculative_generate is the B=1 interactive-latency path; "
            "use generate() for batched decode")
    # +window+1 margin: the last round may overshoot before trimming
    max_len = P + max_new_tokens + window + 1
    for name, c in (("target", target_cfg), ("draft", draft_cfg)):
        if max_len > c.max_seq_len:
            raise ValueError(f"{name} max_seq_len {c.max_seq_len} < "
                             f"required {max_len}")

    t_cache = init_cache(target_cfg, 1, max_len)
    d_cache = init_cache(draft_cfg, 1, max_len)
    t_logits, t_cache = _prefill_jit(target_params, prompt, t_cache,
                                     target_cfg)
    _, d_cache = _prefill_jit(draft_params, prompt, d_cache, draft_cfg)

    stats = SpecStats()
    emitted: List[int] = [int(jnp.argmax(t_logits[0, -1]))]
    # seq = prompt tokens + emitted. Invariants before each round:
    #   target cache holds K/V for seq[:-1] (slots [0, n));
    #   draft cache holds K/V for seq[:d_valid], d_valid in {n-1, n}.
    n = P  # == len(seq) - 1
    d_valid = P

    while len(emitted) < max_new_tokens and \
            (eos_id is None or emitted[-1] != eos_id):
        seq_tail = emitted[-(n + 1 - d_valid):]  # seq[d_valid:]
        d_chunk = jnp.asarray([seq_tail], jnp.int32)
        proposals, d_cache = _draft_propose(
            draft_params, d_chunk, d_cache, d_valid, draft_cfg, window)
        last = jnp.asarray([emitted[-1]], jnp.int32)
        chunk = jnp.concatenate([last[:, None], proposals], axis=1)
        verdict, t_cache = _verify_chunk(
            target_params, chunk, t_cache, n, target_cfg)
        prop = np.asarray(proposals[0])
        ver = np.asarray(verdict[0])          # ver[i] follows chunk[:, i]
        accept = 0
        while accept < window and prop[accept] == ver[accept]:
            accept += 1
        stats.rounds += 1
        stats.proposed += window
        stats.accepted += accept
        # accepted drafts, then the target's correction (or bonus) token
        emitted.extend(int(t) for t in prop[:accept])
        emitted.append(int(ver[accept]))
        n += accept + 1
        # draft cache frontier: chunk + first window-1 proposals were
        # written; of those, [.. d_accept] are now part of seq. A fully
        # accepted window leaves d_window unwritten (never an input).
        d_valid = n - 1 if accept == window else n
        if eos_id is not None and eos_id in emitted:
            del emitted[emitted.index(eos_id) + 1:]
            break

    del emitted[max_new_tokens:]
    out = jnp.concatenate(
        [prompt, jnp.asarray(emitted, jnp.int32)[None, :]], axis=1)
    if metrics is not None:
        metrics.observe(stats)
    return out, stats
