"""Speculative decoding: a small draft model proposes, the target model
verifies — exact greedy equivalence at a fraction of the target's
sequential steps.

Reference counterpart: none (the reference ships no generation loop at
all); this is a TPU-native serving-latency capability on top of the
models/generate.py cache machinery.

Why it fits TPU: the target model stops being a chain of S sequential
single-token programs and becomes S/(c+1) chunk-verify programs of
width k+1 — wide enough to feed the MXU — while the cheap draft model
eats the sequential latency. Greedy acceptance (token match against the
target's argmax) makes the output provably identical to target-only
greedy decode (tested).

Cache discipline (no explicit rollback): attention is masked to
slots <= the query's own slot. Rejected candidates' K/V entries live
at slots >= the accepted position, which is exactly where the next
round's chunk starts writing — so stale entries are never attended
before they are overwritten. The draft consumes a fixed-width CHUNK
of 2 tokens each round via a per-row LAG lane (lag=1 after a fully
accepted window: the last draft token never became a draft input and
is still pending; lag=0 otherwise, where the second chunk token is a
junk duplicate whose K/V is overwritten by the first proposal's write
before anything attends it) so neither cache ever has a hole behind
its valid frontier — and the chunk shape stays static across rows
with different lags.

Scope: any batch size. Rows advance independently — the host accept
loop is vectorized with numpy over the batch (the same replay
discipline as the engine's `_emit_block`), and every jitted program
takes per-row `starts`, so rows with divergent acceptance histories
share one program. `DecodeEngine(draft_params=...)` integrates the
same round structure into continuous batching (see models/engine.py);
this standalone entry point remains the no-engine path.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.generate import (_prefill_jit, forward_cached_rows,
                                     init_cache)
from ray_tpu.models.llama import LlamaConfig
from ray_tpu.util.metrics import Counter, Gauge

Params = Dict[str, Any]


@dataclasses.dataclass
class SpecStats:
    """Per-call acceptance telemetry (drives draft-model/window tuning)."""
    rounds: int = 0
    proposed: int = 0
    accepted: int = 0

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0


_spec_ids = itertools.count()


class SpecMetrics:
    """Publish SpecStats through the util.metrics Prometheus plane, the
    way EngineMetrics publishes DecodeEngine telemetry: pass one
    instance to `speculative_generate(..., metrics=...)` and every
    call's rounds/proposed/accepted land as tagged counters (plus an
    acceptance-rate gauge) next to the llm_engine_* series — so
    draft-model tuning reads off the same dashboard as serving. All
    instruments carry a ``spec`` tag (one draft/target pairing = one
    tag value); `stats()` returns the flat numeric snapshot."""

    def __init__(self, *, spec_id: Optional[str] = None):
        self.spec_id = spec_id or f"spec-{next(_spec_ids)}"
        self.calls = 0
        self.rounds = 0
        self.proposed = 0
        self.accepted = 0

        tag = {"spec": self.spec_id}
        keys = ("spec",)

        def counter(name, desc):
            return Counter(name, desc, tag_keys=keys).set_default_tags(tag)

        self._m_calls = counter(
            "llm_spec_calls_total",
            "speculative_generate invocations")
        self._m_rounds = counter(
            "llm_spec_rounds_total",
            "Draft-propose / target-verify rounds")
        self._m_proposed = counter(
            "llm_spec_proposed_total",
            "Draft tokens proposed for verification")
        self._m_accepted = counter(
            "llm_spec_accepted_total",
            "Draft tokens accepted by the target")
        self._m_rate = Gauge(
            "llm_spec_acceptance_rate",
            "Cumulative accepted / proposed (0..1)",
            tag_keys=keys).set_default_tags(tag)

    def observe(self, stats: SpecStats) -> None:
        """Fold one call's SpecStats into the cumulative series."""
        self.calls += 1
        self.rounds += stats.rounds
        self.proposed += stats.proposed
        self.accepted += stats.accepted
        self._m_calls.inc()
        if stats.rounds > 0:
            self._m_rounds.inc(stats.rounds)
        if stats.proposed > 0:
            self._m_proposed.inc(stats.proposed)
        if stats.accepted > 0:
            self._m_accepted.inc(stats.accepted)
        self._m_rate.set(self.acceptance_rate)

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0

    def stats(self) -> Dict[str, float]:
        """Flat numeric snapshot, gauge-friendly like
        EngineMetrics.stats() (all ratios 0.0 before any call)."""
        return {
            "calls": float(self.calls),
            "rounds": float(self.rounds),
            "proposed": float(self.proposed),
            "accepted": float(self.accepted),
            "acceptance_rate": self.acceptance_rate,
            "rounds_per_call": (self.rounds / self.calls
                                if self.calls else 0.0),
        }


@functools.partial(jax.jit, static_argnames=("cfg", "width"),
                   donate_argnames=("cache",))
def _draft_propose_rows(params, chunk2, cache, starts, lag, cfg, width):
    """Consume the fixed-width-2 chunk [pending-or-last, last] at
    per-row slot `starts` = n - lag (appending its K/V), then greedily
    roll `width` proposals. Row b's first proposal follows its LAST
    token, i.e. logits column `lag[b]` (lag=1: [d_pending@n-1, last@n];
    lag=0: [last@n, junk@n+1] whose junk K/V the first proposal's write
    at n+1 overwrites before any query attends it). Returns
    (proposals [B, width], cache); the cache gains K/V for the chunk
    and the first width-1 proposals (the last proposal is never an
    input)."""
    B = chunk2.shape[0]
    logits, cache = forward_cached_rows(params, chunk2, cache, starts,
                                        cfg)
    first = jnp.argmax(logits[jnp.arange(B), lag],
                       axis=-1).astype(jnp.int32)
    frontier = starts + lag          # == n: proposal j writes at n+1+j

    def step(carry, j):
        tok, cache = carry
        logits, cache = forward_cached_rows(
            params, tok[:, None], cache, frontier + 1 + j, cfg)
        nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
        return (nxt, cache), tok

    (last, cache), toks = jax.lax.scan(
        step, (first, cache), jnp.arange(width - 1))
    proposals = jnp.concatenate([toks.T, last[:, None]], axis=1) \
        if width > 1 else last[:, None]
    return proposals, cache


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache",))
def _verify_rows(params, chunk, cache, starts, cfg):
    """Target forward over [last_emitted, d_1..d_w] at per-row slot
    `starts`; returns (argmax tokens [B, w+1], cache) — entry i is the
    target's greedy continuation of chunk[:, :i+1]."""
    logits, cache = forward_cached_rows(params, chunk, cache, starts,
                                        cfg)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache


def speculative_generate(
    target_params: Params, target_cfg: LlamaConfig,
    draft_params: Params, draft_cfg: LlamaConfig,
    prompt, *, max_new_tokens: int = 32, window: int = 4,
    eos_id: Optional[int] = None,
    metrics: Optional[SpecMetrics] = None,
) -> Tuple[jax.Array, SpecStats]:
    """prompt [B, P] int32 -> (tokens, stats). B=1 returns
    [1, P + n], n <= max_new_tokens (early eos stops short, like
    `generate_stream`). B>1 returns the rectangular
    [B, P + max_new_tokens] with finished rows eos-filled past their
    terminal eos (ragged rows cannot share one array otherwise).

    Greedy only: each row's emitted tokens are IDENTICAL to
    ``generate(target_params, prompt, target_cfg, greedy=True)`` on
    that row up to eos/max_new_tokens truncation (tested). Rows advance
    independently: a row that keeps rejecting does not slow a row that
    keeps accepting — the host accept loop is vectorized with numpy and
    finished rows ride along frozen (their writes land beyond their
    frontier and are never attended). Draft and target must share the
    vocabulary. Pass a `SpecMetrics` to publish this call's acceptance
    telemetry to the util.metrics Prometheus plane."""
    if target_cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError(
            f"draft vocab {draft_cfg.vocab_size} != target vocab "
            f"{target_cfg.vocab_size}")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    prompt = jnp.asarray(prompt, jnp.int32)
    B, P = prompt.shape
    # +window+1 margin: the last round may overshoot before trimming
    max_len = P + max_new_tokens + window + 1
    for name, c in (("target", target_cfg), ("draft", draft_cfg)):
        if max_len > c.max_seq_len:
            raise ValueError(f"{name} max_seq_len {c.max_seq_len} < "
                             f"required {max_len}")

    t_cache = init_cache(target_cfg, B, max_len)
    d_cache = init_cache(draft_cfg, B, max_len)
    t_logits, t_cache = _prefill_jit(target_params, prompt, t_cache,
                                     target_cfg)
    _, d_cache = _prefill_jit(draft_params, prompt, d_cache, draft_cfg)

    stats = SpecStats()
    first_toks = np.asarray(jnp.argmax(t_logits[:, -1], axis=-1))  # graftlint: disable=host-sync -- solo spec loop pulls the prefill token once before the round loop
    emitted: List[List[int]] = [[int(first_toks[b])] for b in range(B)]
    # seq_b = prompt tokens + emitted[b]. Invariants before each round:
    #   target cache row b holds K/V for seq_b[:-1] (slots [0, n_b));
    #   draft cache row b holds K/V for seq_b[:n_b - lag_b],
    #   lag_b in {0, 1} (1 exactly when the last window fully accepted:
    #   its final draft token never became a draft input).
    n = np.full(B, P, np.int64)      # == len(seq_b) - 1
    lag = np.zeros(B, np.int64)

    def _done(b: int) -> bool:
        e = emitted[b]
        return len(e) >= max_new_tokens or \
            (eos_id is not None and e[-1] == eos_id)

    while not all(_done(b) for b in range(B)):
        last = np.array([e[-1] for e in emitted], np.int32)
        pend = np.array([e[-2] if lag[b] else e[-1]
                         for b, e in enumerate(emitted)], np.int32)
        chunk2 = np.stack([pend, last], axis=1)
        proposals, d_cache = _draft_propose_rows(
            draft_params, jnp.asarray(chunk2), d_cache,
            jnp.asarray(n - lag, jnp.int32), jnp.asarray(lag, jnp.int32),
            draft_cfg, window)
        chunk = jnp.concatenate([jnp.asarray(last)[:, None], proposals],
                                axis=1)
        verdict, t_cache = _verify_rows(
            target_params, chunk, t_cache, jnp.asarray(n, jnp.int32),
            target_cfg)
        prop = np.asarray(proposals)          # graftlint: disable=host-sync -- solo spec accept/reject runs on the host; one pull per round by design
        ver = np.asarray(verdict)             # graftlint: disable=host-sync -- ver[i] follows chunk[:, i]; paired with the proposals pull above
        match = prop == ver[:, :window]
        accept = np.cumprod(match, axis=1).sum(axis=1)  # [B], 0..window
        stats.rounds += 1
        for b in range(B):
            if _done(b):
                continue              # frozen row rode along; no emits
            a = int(accept[b])
            stats.proposed += window
            stats.accepted += a
            # accepted drafts, then the target's correction (or bonus)
            emitted[b].extend(int(t) for t in prop[b, :a])
            emitted[b].append(int(ver[b, a]))
            n[b] += a + 1
            # draft frontier: chunk + first window-1 proposals were
            # written; a fully accepted window leaves d_window unwritten.
            lag[b] = 1 if a == window else 0
            if eos_id is not None and eos_id in emitted[b]:
                del emitted[b][emitted[b].index(eos_id) + 1:]
        for b in range(B):
            del emitted[b][max_new_tokens:]

    if metrics is not None:
        metrics.observe(stats)
    if B == 1:
        out = jnp.concatenate(
            [prompt, jnp.asarray(emitted[0], jnp.int32)[None, :]],
            axis=1)
        return out, stats
    fill = eos_id if eos_id is not None else 0
    rect = np.full((B, max_new_tokens), fill, np.int32)
    for b in range(B):
        rect[b, :len(emitted[b])] = emitted[b]
    out = jnp.concatenate([prompt, jnp.asarray(rect)], axis=1)
    return out, stats
