"""SLO-aware serving fleet: replica router + engine-stats autoscaler.

PRs 1-5 made ONE `DecodeEngine` fast (fused horizon, prefix cache,
async pipeline); this module makes N of them serve as a single system.
The fleet-scale literature (Ray Serve's pow-2-choice router, Orca/vLLM
continuous batching at scale) is unanimous about where tail latency is
won once the kernel is fast: in the ROUTER (which replica gets the
request) and the SCALING POLICY (when replicas appear and disappear) —
so those are the two first-class objects here.

Three planes, one `submit()`-shaped facade (`LLMFleet`):

- ROUTING. Each request is placed by scoring replicas on their live
  `engine.stats()`-plane signals — queue depth, slot occupancy,
  pending prefill tokens, and the prompt's prefix-cache hit potential
  probed directly against each replica's radix index (`peek=True`, so
  losing candidates' LRU recency is untouched). The default router is
  power-of-two-choices (two random candidates, pick the less loaded —
  O(1) with near-best-of-N tail behavior, the Serve router's design)
  with a PREFIX-AFFINITY OVERRIDE: a replica that already holds a
  request's prefix blocks wins outright unless it is overloaded
  relative to the fleet, because re-computing a cached prefix on a
  "less loaded" replica costs more than queueing behind the warm one.

- AUTOSCALING. `EngineStatsAutoscaler` consumes per-replica
  TTFT/TPOT-p95 and occupancy gauges — NOT request rate: QPS says
  nothing about cost when one request can be 10 or 10k tokens — and
  adds or drains replicas with hysteresis (sustained breach for
  `upscale_hold_s` before +1; sustained idle for `downscale_hold_s`
  before -1; the asymmetry is deliberate, scale-up cheap and fast,
  scale-down slow and safe). Scale-down NEVER kills work:
  the victim replica is put in DRAINING (its engine refuses new
  submits, the router stops offering it), runs to empty, and only then
  leaves the pool — flush-before-removal, zero in-flight tokens lost.

- OVERLOAD. Priority classes ride the engine's own priority scheduler
  (`submit(priority=...)` passes straight through) and deadline-based
  shedding rides `DecodeEngine.submit(deadline_s=...)`: a request that
  is past its admission deadline is retired WITHOUT burning prefill,
  at submit (dead on arrival) or at admission pop (expired mid-queue).
  Shed requests surface through the same finished/pop_result path with
  `shed_ids` membership, so one polling loop serves both outcomes.

Every replica keeps the engine's token-identity invariant: routing,
scale-up, drain, and shedding change WHICH engine runs a request and
WHEN it is admitted — never what it computes. Outputs stay
token-identical to solo `generate` (greedy, and sampled with a pinned
per-request rng), which `tests/test_fleet.py` asserts as a matrix.

Fleet health exports as `llm_fleet_*` gauges through the ordinary
`ray_tpu.util.metrics` plane (tagged by fleet id, same pattern as the
engine's `llm_engine_*` series) and as a flat `stats()` snapshot.
"""

from __future__ import annotations

import json
import random
import time
from typing import Callable, Dict, List, Optional, Tuple, Union

from ray_tpu.models.engine_trace import resolve_tracer
from ray_tpu.util.metrics import Gauge

__all__ = [
    "LLMFleet",
    "FleetRouter",
    "RoundRobinRouter",
    "PowerOfTwoAffinityRouter",
    "FleetAutoscalingConfig",
    "EngineStatsAutoscaler",
    "make_router",
    "replica_score",
]


# ---------------------------------------------------------------------------
# Replica pool
# ---------------------------------------------------------------------------

RUNNING = "RUNNING"
DRAINING = "DRAINING"


class _Replica:
    """One DecodeEngine plus its fleet bookkeeping: the replica-local
    request-id -> fleet request-id map (each engine numbers its own
    requests from 0) and the RUNNING/DRAINING state the router and
    scaler act on."""

    __slots__ = ("name", "engine", "state", "rid_to_fid", "routed")

    def __init__(self, name: str, engine):
        self.name = name
        self.engine = engine
        self.state = RUNNING
        self.rid_to_fid: Dict[int, int] = {}
        self.routed = 0          # requests this replica has been given


# ---------------------------------------------------------------------------
# Routers
# ---------------------------------------------------------------------------

def replica_score(replica: _Replica, prompt: List[int],
                  *, queue_cost: float = 64.0,
                  slot_cost: float = 8.0) -> float:
    """Estimated cost (in prompt-token equivalents) of placing `prompt`
    on `replica` RIGHT NOW — the scoring function both routers and the
    bench share.

    pending_prefill_tokens is the real backlog unit (prompt tokens owed
    before the newcomer's prefill can start); queue depth and KV
    occupancy are converted to the same unit with fixed exchange rates
    (`queue_cost` per queued request ~ a short prompt's prefill,
    `slot_cost` per occupied slot-equivalent ~ the decode interference
    it adds); the prompt's own cost counts only its COLD suffix —
    tokens the replica's prefix pool cannot copy (probed with
    peek=True: scoring must not touch any replica's LRU recency; only
    the winner's trie is touched, at admission).

    Occupancy reads through `kv_used_fraction()`: on a DENSE engine
    that is live_rows / batch_slots, so the term equals the historical
    `live * slot_cost` exactly; on a PAGED engine it is the fraction
    of KV pool blocks not free-or-evictable, so a replica whose pool
    is nearly dry — about to preempt — scores as loaded even when its
    row slots look empty, and the router steers toward free KV blocks.
    All host-side reads, zero device work per decision."""
    eng = replica.engine
    queued = float(len(eng.scheduler))
    if hasattr(eng, "kv_used_fraction"):
        occupied = eng.kv_used_fraction() * len(eng.row_req)
    else:
        occupied = float(sum(r is not None for r in eng.row_req))
    pending = float(eng.pending_prefill_tokens())
    cold = float(max(len(prompt) - eng.prefix_match_tokens(prompt), 1))
    return queued * queue_cost + occupied * slot_cost + pending + cold


class FleetRouter:
    """Chooses the replica a request is submitted to. Only RUNNING
    replicas are offered (the fleet filters DRAINING out before
    calling)."""

    name = "base"

    def choose(self, replicas: List[_Replica],
               prompt: List[int]) -> _Replica:
        raise NotImplementedError


class RoundRobinRouter(FleetRouter):
    """Stats-blind baseline: replicas in rotation. Exists to be beaten
    — the bench's control arm for the pow-2 + affinity router."""

    name = "round_robin"

    def __init__(self):
        self._i = 0

    def choose(self, replicas: List[_Replica],
               prompt: List[int]) -> _Replica:
        rep = replicas[self._i % len(replicas)]
        self._i += 1
        return rep


class PowerOfTwoAffinityRouter(FleetRouter):
    """Power-of-two-choices over `replica_score`, with a prefix-
    affinity override.

    Affinity first: the replica whose radix index holds the LONGEST
    committed prefix of this prompt wins outright — IF its score stays
    within `affinity_overload_factor` of the best score in the fleet.
    The cap is what keeps affinity from defeating itself: without it,
    every request of a hot shared-prefix group piles onto the one warm
    replica until its queue dwarfs the prefill it saves (the classic
    cache-affinity hotspot). Past the cap the request routes by load
    and becomes the group's cache seed on a second replica.

    Otherwise pow-2: sample two distinct candidates with a SEEDED
    stream (deterministic tests and benches), pick the lower score.
    Two random choices get within a constant factor of scanning all N
    — the Serve router's own rationale — and the score here folds in
    everything stats() knows, not just queue length."""

    name = "pow2_affinity"

    def __init__(self, *, seed: int = 0, affinity: bool = True,
                 affinity_overload_factor: float = 4.0,
                 queue_cost: float = 64.0, slot_cost: float = 8.0):
        if affinity_overload_factor < 1.0:
            raise ValueError("affinity_overload_factor must be >= 1.0")
        self._rng = random.Random(seed)
        self.affinity = affinity
        self.affinity_overload_factor = affinity_overload_factor
        self.queue_cost = queue_cost
        self.slot_cost = slot_cost
        self.affinity_wins = 0   # decisions the prefix override took
        self.pow2_wins = 0       # decisions left to power-of-two

    def _score(self, rep: _Replica, prompt: List[int]) -> float:
        return replica_score(rep, prompt, queue_cost=self.queue_cost,
                             slot_cost=self.slot_cost)

    def choose(self, replicas: List[_Replica],
               prompt: List[int]) -> _Replica:
        if len(replicas) == 1:
            return replicas[0]
        if self.affinity:
            scores = [self._score(r, prompt) for r in replicas]
            best_score = min(scores)
            warm_i, warm_tokens = -1, 0
            for i, r in enumerate(replicas):
                m = r.engine.prefix_match_tokens(prompt)
                if m > warm_tokens:
                    warm_i, warm_tokens = i, m
            if warm_i >= 0 and scores[warm_i] <= \
                    self.affinity_overload_factor * (best_score + 1.0):
                self.affinity_wins += 1
                return replicas[warm_i]
        i = self._rng.randrange(len(replicas))
        j = self._rng.randrange(len(replicas) - 1)
        if j >= i:
            j += 1
        a, b = replicas[i], replicas[j]
        self.pow2_wins += 1
        return a if self._score(a, prompt) <= self._score(b, prompt) \
            else b


_ROUTERS = {"round_robin": RoundRobinRouter,
            "pow2": PowerOfTwoAffinityRouter,
            "pow2_affinity": PowerOfTwoAffinityRouter}


def make_router(spec: Union[str, FleetRouter]) -> FleetRouter:
    """Resolve a router spec: an instance passes through, a name
    ("round_robin" | "pow2" | "pow2_affinity") constructs the
    built-in."""
    if isinstance(spec, FleetRouter):
        return spec
    try:
        return _ROUTERS[spec]()
    except (KeyError, TypeError):
        raise ValueError(
            f"unknown fleet router {spec!r}: expected a FleetRouter "
            f"instance or one of {sorted(_ROUTERS)}")


# ---------------------------------------------------------------------------
# Autoscaler
# ---------------------------------------------------------------------------

class FleetAutoscalingConfig:
    """Scaling policy knobs for `EngineStatsAutoscaler`.

    The breach signals are the SERVING SLOs, not traffic: TTFT p95 over
    `ttft_p95_slo_s` (the tail of submit -> first token, the number a
    user feels) or mean slot occupancy over `occupancy_high` (the fleet
    is out of decode slots even if the tail has not blown up yet), or —
    when `target_custom_metric` is set — a caller-recorded scalar
    (`serve.metrics.record_autoscaling_metric`, read back through
    `custom_metric_source`) exceeding its target. Scale-down needs ALL
    clear: occupancy under `occupancy_low`, custom metric (if any)
    under target, TTFT inside SLO.

    `upscale_hold_s` / `downscale_hold_s` are the hysteresis: a breach
    (resp. idle spell) must be CONTINUOUS for that long before the
    scaler acts, and the timers reset whenever the condition breaks.
    Downscale defaults much slower than upscale — adding a replica
    wastes a little compute; removing one into a traffic return wastes
    user latency."""

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 4,
                 ttft_p95_slo_s: Optional[float] = None,
                 occupancy_high: float = 0.85,
                 occupancy_low: float = 0.30,
                 upscale_hold_s: float = 3.0,
                 downscale_hold_s: float = 30.0,
                 target_custom_metric: Optional[float] = None,
                 custom_metric_source: Optional[
                     Callable[[], Optional[float]]] = None):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if not 0.0 <= occupancy_low <= occupancy_high <= 1.0:
            raise ValueError(
                "need 0 <= occupancy_low <= occupancy_high <= 1")
        if upscale_hold_s < 0 or downscale_hold_s < 0:
            raise ValueError("hold times must be >= 0")
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.ttft_p95_slo_s = ttft_p95_slo_s
        self.occupancy_high = occupancy_high
        self.occupancy_low = occupancy_low
        self.upscale_hold_s = upscale_hold_s
        self.downscale_hold_s = downscale_hold_s
        self.target_custom_metric = target_custom_metric
        self.custom_metric_source = custom_metric_source


class EngineStatsAutoscaler:
    """Hysteresis state machine over per-replica engine stats.

    `tick(stats_list, n_replicas)` returns the scale decision for this
    instant: +1 (add a replica), -1 (drain one), or 0. The caller (the
    fleet) applies it; the scaler only decides. Mirrors the serve
    controller's AutoscalingState decision-hold pattern
    (_private/autoscaling.py) but reads the LLM-native gauges: worst
    per-replica TTFT p95 (one hot replica IS an SLO breach — means
    would hide it), mean occupancy (fleet-level headroom), and the
    optional custom metric.

    All timing flows through the injected clock, so tests drive
    hysteresis with a fake clock instead of sleeping real time."""

    def __init__(self, config: FleetAutoscalingConfig,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config
        self._clock = clock
        self._breach_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self.scale_ups = 0
        self.scale_downs = 0
        # Last tick's inputs/verdict, for stats() and the bench log.
        self.last_signals: Dict[str, float] = {}

    def _signals(self, stats_list: List[Dict[str, float]]
                 ) -> Tuple[float, float, float, Optional[float]]:
        ttft_p95 = max((s.get("ttft_s_p95", 0.0) for s in stats_list),
                       default=0.0)
        occ = (sum(s.get("slot_occupancy", 0.0) for s in stats_list)
               / len(stats_list)) if stats_list else 0.0
        qdepth = sum(s.get("queue_depth", 0.0) for s in stats_list)
        custom = None
        if self.config.custom_metric_source is not None:
            custom = self.config.custom_metric_source()
        return ttft_p95, occ, qdepth, custom

    def tick(self, stats_list: List[Dict[str, float]],
             n_replicas: int) -> int:
        """One scaling decision from the current per-replica snapshots.
        Call at the fleet's step cadence; returns +1 / 0 / -1."""
        cfg = self.config
        now = self._clock()
        ttft_p95, occ, qdepth, custom = self._signals(stats_list)

        # TTFT p95 is a sliding WINDOW over past requests — once
        # traffic stops the window goes stale at its last (bad) value.
        # A latency breach therefore only counts while the fleet is
        # actually busy (work queued or slots occupied); an idle fleet
        # quoting an old p95 must scale DOWN, not up.
        busy = occ > 0.0 or qdepth > 0.0
        breach = occ > cfg.occupancy_high
        if busy and cfg.ttft_p95_slo_s is not None and \
                ttft_p95 > cfg.ttft_p95_slo_s:
            breach = True
        if cfg.target_custom_metric is not None and custom is not None \
                and custom > cfg.target_custom_metric:
            breach = True

        idle = (not breach) and occ < cfg.occupancy_low
        if cfg.target_custom_metric is not None and custom is not None \
                and custom >= cfg.target_custom_metric:
            idle = False

        self.last_signals = {
            "ttft_p95": ttft_p95, "occupancy": occ,
            "queue_depth": qdepth,
            "custom": float("nan") if custom is None else custom,
            "breach": 1.0 if breach else 0.0,
            "idle": 1.0 if idle else 0.0,
        }

        if breach:
            self._idle_since = None
            if self._breach_since is None:
                self._breach_since = now
            if now - self._breach_since >= cfg.upscale_hold_s and \
                    n_replicas < cfg.max_replicas:
                self._breach_since = None   # re-arm: next +1 needs a
                self.scale_ups += 1         # fresh sustained breach
                return +1
            return 0
        self._breach_since = None

        if idle:
            if self._idle_since is None:
                self._idle_since = now
            if now - self._idle_since >= cfg.downscale_hold_s and \
                    n_replicas > cfg.min_replicas:
                self._idle_since = None
                self.scale_downs += 1
                return -1
            return 0
        self._idle_since = None
        return 0


# ---------------------------------------------------------------------------
# Fleet facade
# ---------------------------------------------------------------------------

_fleet_gauges: Dict[str, Gauge] = {}


class LLMFleet:
    """N `DecodeEngine` replicas behind one engine-shaped API.

    `engine_factory(name)` builds one replica's engine (the fleet
    passes a unique replica name — use it as `engine_id` so the
    per-engine `llm_engine_*` series stay separable). The fleet owns
    replica lifecycle: it starts with `initial_replicas` (or the
    autoscaler's min), the router places every `submit`, `step()`
    advances every replica one engine step and applies at most one
    scale decision, and DRAINING replicas leave the pool only once
    empty.

    The API mirrors DecodeEngine on purpose — submit / step / run /
    pending / pop_result / finished / shed_ids / stats — so a serving
    loop written against one engine drives a fleet unchanged. Request
    ids are FLEET-scoped (each engine numbers its own; the fleet maps
    engine ids back per replica)."""

    def __init__(self, engine_factory: Callable[[str], object], *,
                 initial_replicas: Optional[int] = None,
                 router: Union[str, FleetRouter] = "pow2_affinity",
                 autoscaling: Optional[FleetAutoscalingConfig] = None,
                 fleet_id: str = "fleet-0",
                 trace=None,
                 clock: Callable[[], float] = time.monotonic):
        self._factory = engine_factory
        self.router = make_router(router)
        self.fleet_id = fleet_id
        self._clock = clock
        # Fleet-level tracer: holds the `route` spans (one per submit,
        # carrying the router's scoring decision) that stitch replica
        # traces into one request story. Same knob semantics as
        # DecodeEngine(trace=...): instance / True / False / None
        # (env gate). Replica ENGINE tracing stays the factory's call —
        # dump_trace() merges whatever replicas traced.
        self.trace = resolve_tracer(trace, engine_id=fleet_id,
                                    clock=clock)
        self._retired_trace: List[dict] = []   # drained replicas' spans
        self.autoscaler = (EngineStatsAutoscaler(autoscaling, clock)
                           if autoscaling is not None else None)
        n = initial_replicas
        if n is None:
            n = autoscaling.min_replicas if autoscaling else 2
        if n < 1:
            raise ValueError("initial_replicas must be >= 1")
        if autoscaling is not None and \
                not autoscaling.min_replicas <= n \
                <= autoscaling.max_replicas:
            raise ValueError(
                f"initial_replicas {n} outside autoscaling bounds "
                f"[{autoscaling.min_replicas}, "
                f"{autoscaling.max_replicas}]")
        self.replicas: List[_Replica] = []
        self._next_replica = 0
        for _ in range(n):
            self.add_replica()
        self._next_fid = 0
        self._placement: Dict[int, Tuple[_Replica, int]] = {}
        self._done: Dict[int, List[int]] = {}
        self.finished: set = set()
        self.shed_ids: set = set()
        self.requests_routed = 0
        self.requests_shed = 0
        self.replicas_removed = 0
        self.tokens_lost_to_drain = 0   # stays 0 by construction;
        #                                 asserted in tests AND here
        # Weak registration in the serving state API: summarize_fleet /
        # the status CLI find this fleet (and attribute its replicas'
        # engines) without the fleet holding any extra lifecycle.
        from ray_tpu.util.state.serving import register_fleet
        register_fleet(self)

    # -- replica lifecycle -------------------------------------------------

    def add_replica(self) -> str:
        """Build a fresh replica via the factory and put it in the
        routing rotation; returns its name."""
        name = f"{self.fleet_id}-r{self._next_replica}"
        self._next_replica += 1
        self.replicas.append(_Replica(name, self._factory(name)))
        return name

    def drain_replica(self, name: str) -> None:
        """Move a replica to DRAINING: its engine refuses new submits
        (EngineDraining), the router no longer offers it, and `step()`
        keeps advancing it until empty, then removes it. In-flight and
        queued work all complete — flush-before-removal."""
        rep = self._replica(name)
        rep.state = DRAINING
        rep.engine.begin_drain()

    def _replica(self, name: str) -> _Replica:
        for rep in self.replicas:
            if rep.name == name:
                return rep
        raise KeyError(f"no replica named {name!r}")

    def _running(self) -> List[_Replica]:
        return [r for r in self.replicas if r.state == RUNNING]

    # -- request path ------------------------------------------------------

    def submit(self, prompt: List[int], max_new_tokens: int = 32,
               priority: int = 0, rng=None,
               deadline_s: Optional[float] = None) -> int:
        """Route and enqueue one request; returns its FLEET id.

        priority / rng / deadline_s pass straight through to the chosen
        engine's submit — the fleet adds placement, nothing else, so
        per-replica token identity is the engine's own guarantee. A
        dead-on-arrival deadline still routes (the engine sheds it
        before it can occupy a queue slot) and is visible in
        `finished` + `shed_ids` immediately."""
        running = self._running()
        if not running:
            raise RuntimeError(
                "fleet has no RUNNING replicas to route to")
        tr = self.trace
        if tr.enabled:
            # Snapshot what the router is about to see (pure peek
            # probes, no LRU perturbation) so the route span carries
            # the scoring decision, not a post-hoc reconstruction.
            t0 = tr.now()
            scores = {r.name: round(replica_score(r, prompt), 2)
                      for r in running}
            warm = {r.name: r.engine.prefix_match_tokens(prompt)
                    for r in running}
        rep = self.router.choose(running, prompt)
        rid = rep.engine.submit(prompt, max_new_tokens,
                                priority=priority, rng=rng,
                                deadline_s=deadline_s)
        fid = self._next_fid
        self._next_fid += 1
        if tr.enabled:
            tr.add("route", t0, tr.now() - t0, req_id=fid,
                   args={"replica": rep.name, "rid": rid,
                         "router": getattr(self.router, "name",
                                           type(self.router).__name__),
                         "scores": scores, "warm_tokens": warm,
                         "warm": warm.get(rep.name, 0) > 0})
        rep.rid_to_fid[rid] = fid
        self._placement[fid] = (rep, rid)
        rep.routed += 1
        self.requests_routed += 1
        self._sweep_finished(rep)    # DOA sheds surface immediately
        return fid

    def step(self) -> Dict[int, List[int]]:
        """Advance every replica one engine step; returns the merged
        {fleet_id: new tokens} emissions. Also applies at most one
        autoscaler decision and retires DRAINING replicas that have
        run empty.

        The scale decision is taken on the PRE-step snapshots: submits
        land between steps, so the backlog visible now — before this
        step consumes any of it — is the demand the fleet is actually
        facing. (Post-step stats systematically under-read: a fast
        engine may clear its whole queue within the step and report an
        idle instant while sustained traffic is breaching the SLO.)"""
        if self.autoscaler is not None:
            self._apply_scale(self.autoscaler.tick(
                [r.engine.stats() for r in self.replicas],
                len(self._running())))
        emitted: Dict[int, List[int]] = {}
        for rep in list(self.replicas):
            if not rep.engine.pending():
                self._sweep_finished(rep)
                continue
            em = rep.engine.step()
            for rid, toks in em.items():
                fid = rep.rid_to_fid.get(rid)
                if fid is not None and toks:
                    emitted.setdefault(fid, []).extend(toks)
            self._sweep_finished(rep)
        self._retire_drained()
        return emitted

    def pending(self) -> bool:
        return any(r.engine.pending() for r in self.replicas)

    def run(self) -> Dict[int, List[int]]:
        """Drain every replica; returns {fleet_id: tokens} for every
        finished request and pops them (like DecodeEngine.run)."""
        while self.pending():
            self.step()
        for rep in list(self.replicas):
            self._sweep_finished(rep)
        self._retire_drained()
        return {fid: self.pop_result(fid)
                for fid in list(self.finished)}

    def pop_result(self, fid: int) -> List[int]:
        """Tokens of a FINISHED fleet request (empty for a shed one —
        check `shed_ids` before popping, same contract as the
        engine)."""
        if fid not in self.finished:
            raise KeyError(f"fleet request {fid} unknown or "
                           f"not finished")
        self.finished.discard(fid)
        self.shed_ids.discard(fid)
        return self._done.pop(fid)

    # -- internals ---------------------------------------------------------

    def _sweep_finished(self, rep: _Replica) -> None:
        """Move the replica's finished engine requests into the fleet's
        finished set (popping them from the engine, so a drained
        replica ends truly empty)."""
        for rid in list(rep.engine.finished):
            fid = rep.rid_to_fid.pop(rid, None)
            if fid is None:
                continue
            shed = rid in rep.engine.shed_ids
            toks = rep.engine.pop_result(rid)
            self._done[fid] = toks
            self.finished.add(fid)
            self._placement.pop(fid, None)
            if shed:
                self.shed_ids.add(fid)
                self.requests_shed += 1

    def _retire_drained(self) -> None:
        """Remove DRAINING replicas that have fully flushed. The
        zero-loss invariant is checked here, not trusted: a replica
        may only leave with no queued work, no live rows, and no
        unswept results."""
        for rep in list(self.replicas):
            if rep.state != DRAINING:
                continue
            if rep.engine.pending() or rep.engine.finished or \
                    rep.rid_to_fid:
                continue    # still owes work or unswept results: kept
            etr = getattr(rep.engine, "trace", None)
            if etr is not None and etr.enabled:
                # Keep the drained replica's spans so dump_trace()
                # still tells the whole story — bounded like the rings
                # it collects from (oldest spans trimmed first).
                self._retired_trace.extend(
                    etr.chrome_events(pid=rep.name))
                cap = 4 * getattr(etr, "capacity", 16384)
                if len(self._retired_trace) > cap:
                    self._retired_trace = self._retired_trace[-cap:]
            self.replicas.remove(rep)
            self.replicas_removed += 1

    def _apply_scale(self, decision: int) -> None:
        if decision > 0:
            self.add_replica()
        elif decision < 0:
            running = self._running()
            if len(running) <= 1:
                return          # never drain the last live replica
            # Drain the replica with the least outstanding work — the
            # cheapest flush, so capacity leaves the pool fastest.
            victim = min(
                running,
                key=lambda r: (r.engine.pending_prefill_tokens()
                               + sum(x is not None
                                     for x in r.engine.row_req)))
            self.drain_replica(victim.name)

    # -- telemetry ---------------------------------------------------------

    def dump_trace(self, path: Optional[str] = None) -> List[dict]:
        """One chrome://tracing JSON for the whole fleet: the fleet
        tracer's `route` spans (pid = fleet id, tid = fleet request
        lane) merged with every replica engine's lifecycle spans
        (pid = replica name, tid = replica-local request lane) plus
        spans harvested from replicas already drained out of the pool.
        A route span's args carry the chosen replica and its
        replica-local rid, which is the join key between the two pid
        groups. Writes JSON to `path` when given; returns the event
        list (empty when nothing traced)."""
        events = list(self._retired_trace)
        for rep in self.replicas:
            etr = getattr(rep.engine, "trace", None)
            if etr is not None and etr.enabled:
                events.extend(etr.chrome_events(pid=rep.name))
        events.extend(self.trace.chrome_events(pid=self.fleet_id))
        events.sort(key=lambda e: e["ts"])
        if path:
            with open(path, "w") as f:
                json.dump(events, f)
        return events

    def stats(self) -> Dict[str, float]:
        """Flat fleet snapshot (gauge-friendly, like engine.stats()).
        Every field is also published as an `llm_fleet_<field>` gauge
        tagged with the fleet id through util.metrics."""
        running = self._running()
        draining = [r for r in self.replicas if r.state == DRAINING]
        per = [r.engine.stats() for r in self.replicas]
        out: Dict[str, float] = {
            "replicas": float(len(self.replicas)),
            "replicas_running": float(len(running)),
            "replicas_draining": float(len(draining)),
            "replicas_removed": float(self.replicas_removed),
            "requests_routed": float(self.requests_routed),
            "requests_shed": float(self.requests_shed),
            "tokens_lost_to_drain": float(self.tokens_lost_to_drain),
            "queue_depth": sum(s.get("queue_depth", 0.0) for s in per),
            "pending_prefill_tokens": sum(
                s.get("pending_prefill_tokens", 0.0) for s in per),
            "slot_occupancy_mean": (
                sum(s.get("slot_occupancy", 0.0) for s in per)
                / len(per)) if per else 0.0,
            "ttft_s_p95_max": max(
                (s.get("ttft_s_p95", 0.0) for s in per), default=0.0),
            "tpot_s_p95_max": max(
                (s.get("tpot_s_p95", 0.0) for s in per), default=0.0),
            # Tensor-parallel plane: replicas built by engine_factory
            # may themselves be tp-sharded over an ICI mesh — the
            # fleet then scales in units of whole meshes. Replicas are
            # homogeneous in practice, so max == the fleet's tp; the
            # per-replica view flows through each engine's own
            # llm_engine_* series (and serve_llm_engine_* when a
            # replica republishes via report_engine_stats).
            "tp_degree_max": max(
                (s.get("tp_degree", 1.0) for s in per), default=1.0),
            "host_transfer_bytes": sum(
                s.get("host_transfer_bytes", 0.0) for s in per),
            # Paged-KV plane: zero-copy sharing / preempt-and-swap
            # rollup (all-zero when replicas run the dense cache).
            "kv_blocks_shared": sum(
                s.get("kv_blocks_shared", 0.0) for s in per),
            "kv_block_cows": sum(
                s.get("kv_block_cows", 0.0) for s in per),
            "preemptions": sum(
                s.get("preemptions", 0.0) for s in per),
            "swap_in_bytes": sum(
                s.get("swap_in_bytes", 0.0) for s in per),
            "swap_out_bytes": sum(
                s.get("swap_out_bytes", 0.0) for s in per),
            "kv_free_blocks": sum(
                s.get("kv_free_blocks", 0.0) for s in per),
            "kv_used_fraction_mean": (
                sum(s.get("kv_used_fraction", 0.0) for s in per)
                / len(per)) if per else 0.0,
        }
        # Speculative plane (all-zero when no replica carries a draft
        # model). Rates are re-derived from the summed raw counters —
        # a proposal-weighted mean — so a busy replica's acceptance
        # dominates an idle one's instead of averaging per-replica
        # ratios.
        sp_prop = sum(s.get("spec_proposed", 0.0) for s in per)
        sp_acc = sum(s.get("spec_accepted", 0.0) for s in per)
        sp_rounds = sum(s.get("spec_rounds", 0.0) for s in per)
        out["spec_replicas"] = sum(
            s.get("spec_enabled", 0.0) for s in per)
        out["spec_dispatches"] = sum(
            s.get("spec_dispatches", 0.0) for s in per)
        out["spec_rounds"] = sp_rounds
        out["spec_proposed"] = sp_prop
        out["spec_accepted"] = sp_acc
        out["spec_acceptance_rate"] = (
            sp_acc / sp_prop if sp_prop else 0.0)
        out["spec_window_effective"] = (
            sp_prop / sp_rounds if sp_rounds else 0.0)
        out["spec_draft_tokens_wasted"] = sum(
            s.get("spec_draft_tokens_wasted", 0.0) for s in per)
        out["router_affinity_wins"] = float(
            getattr(self.router, "affinity_wins", 0))
        out["router_pow2_wins"] = float(
            getattr(self.router, "pow2_wins", 0))
        if self.autoscaler is not None:
            out["scale_ups"] = float(self.autoscaler.scale_ups)
            out["scale_downs"] = float(self.autoscaler.scale_downs)
        self._publish(out)
        return out

    def _publish(self, stats: Dict[str, float]) -> None:
        for field, value in stats.items():
            name = f"llm_fleet_{field}"
            g = _fleet_gauges.get(name)
            if g is None:
                g = _fleet_gauges[name] = Gauge(
                    name, f"LLMFleet stats field {field!r}",
                    tag_keys=("fleet",))
            g.set(float(value), tags={"fleet": self.fleet_id})
