"""SLO-aware serving fleet: replica router + engine-stats autoscaler.

PRs 1-5 made ONE `DecodeEngine` fast (fused horizon, prefix cache,
async pipeline); this module makes N of them serve as a single system.
The fleet-scale literature (Ray Serve's pow-2-choice router, Orca/vLLM
continuous batching at scale) is unanimous about where tail latency is
won once the kernel is fast: in the ROUTER (which replica gets the
request) and the SCALING POLICY (when replicas appear and disappear) —
so those are the two first-class objects here.

Four planes, one `submit()`-shaped facade (`LLMFleet`):

- ROUTING. Each request is placed by scoring replicas on their live
  `engine.stats()`-plane signals — queue depth, slot occupancy,
  pending prefill tokens, and the prompt's prefix-cache hit potential
  probed directly against each replica's radix index (`peek=True`, so
  losing candidates' LRU recency is untouched). The default router is
  power-of-two-choices (two random candidates, pick the less loaded —
  O(1) with near-best-of-N tail behavior, the Serve router's design)
  with a PREFIX-AFFINITY OVERRIDE: a replica that already holds a
  request's prefix blocks wins outright unless it is overloaded
  relative to the fleet, because re-computing a cached prefix on a
  "less loaded" replica costs more than queueing behind the warm one.

- AUTOSCALING. `EngineStatsAutoscaler` consumes per-replica
  TTFT/TPOT-p95 and occupancy gauges — NOT request rate: QPS says
  nothing about cost when one request can be 10 or 10k tokens — and
  adds or drains replicas with hysteresis (sustained breach for
  `upscale_hold_s` before +1; sustained idle for `downscale_hold_s`
  before -1; the asymmetry is deliberate, scale-up cheap and fast,
  scale-down slow and safe). Scale-down NEVER kills work:
  the victim replica is put in DRAINING (its engine refuses new
  submits, the router stops offering it), runs to empty, and only then
  leaves the pool — flush-before-removal, zero in-flight tokens lost.

- OVERLOAD. Priority classes ride the engine's own priority scheduler
  (`submit(priority=...)` passes straight through) and deadline-based
  shedding rides `DecodeEngine.submit(deadline_s=...)`: a request that
  is past its admission deadline is retired WITHOUT burning prefill,
  at submit (dead on arrival) or at admission pop (expired mid-queue).
  Shed requests surface through the same finished/pop_result path with
  `shed_ids` membership, so one polling loop serves both outcomes.

- FAULT TOLERANCE. Every `engine.step()` runs under the fleet's
  supervision: a per-replica HEALTH STATE MACHINE (RUNNING -> SUSPECT
  -> UNHEALTHY -> RETIRED, `FleetHealthConfig`) driven by step
  exceptions, a step-deadline watchdog on the injected clock,
  consecutive-slow-step probes, and a no-progress (silent) detector —
  the blueprint's raylet-heartbeat / NodeManager failure-detection
  role, done in-process. The router only offers RUNNING replicas
  whose CIRCUIT BREAKER is closed (a replica that keeps flapping into
  SUSPECT stops receiving traffic for a cooldown before it fails
  again). When a replica goes UNHEALTHY the fleet performs
  DETERMINISTIC FAILOVER: every in-flight and queued request on it is
  reconstructed from host-side bookkeeping (prompt + tokens already
  emitted + the per-request rng key the fleet pinned at submit) and
  resubmitted to a healthy replica with resume semantics — the final
  token stream is bit-identical to a fault-free run, greedy AND
  sampled, because sampling streams depend only on (key, token index)
  and the fleet derives each request's key from its FLEET id, never
  from placement. Retries get exponential backoff with deterministic
  jitter from the request seed; a request that runs out of
  `max_retries` (or of replicas) surfaces as a typed
  `RetriesExhausted` / `ReplicaUnavailable` through `pop_result()` /
  `run()` instead of hanging. `tokens_lost_to_failure` stays 0 by
  construction and is counted, not assumed.

Every replica keeps the engine's token-identity invariant: routing,
scale-up, drain, shedding, and FAILOVER change WHICH engine runs a
request and WHEN it is admitted — never what it computes. Outputs stay
token-identical to solo `generate` (greedy, and sampled with a pinned
per-request rng), which `tests/test_fleet.py` and
`tests/test_fleet_faults.py` assert as a matrix.

Fleet health exports as `llm_fleet_*` gauges plus the
`llm_fleet_replica_failures_total` / `llm_fleet_requests_recovered_total`
/ `llm_fleet_retries_total` counters through the ordinary
`ray_tpu.util.metrics` plane (tagged by fleet id, same pattern as the
engine's `llm_engine_*` series) and as a flat `stats()` snapshot.
"""

from __future__ import annotations

import heapq
import json
import random
import time
from typing import Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from ray_tpu.models.engine import _key_data
from ray_tpu.models.engine_metrics import _Agg
from ray_tpu.models.engine_trace import resolve_tracer
from ray_tpu.models.scheduler import EngineDraining, EngineOverloaded
from ray_tpu.util.metrics import Counter, Gauge

__all__ = [
    "LLMFleet",
    "FleetRouter",
    "RoundRobinRouter",
    "PowerOfTwoAffinityRouter",
    "FleetAutoscalingConfig",
    "FleetHealthConfig",
    "EngineStatsAutoscaler",
    "FleetError",
    "ReplicaUnavailable",
    "RetriesExhausted",
    "make_router",
    "replica_score",
]


# ---------------------------------------------------------------------------
# Typed errors
# ---------------------------------------------------------------------------

class FleetError(RuntimeError):
    """Base class for typed fleet serving failures (replaces the bare
    RuntimeErrors the fleet used to raise)."""


class ReplicaUnavailable(FleetError):
    """No replica can take the work: none RUNNING at submit, or every
    survivor retired with replacement disabled before a recovery could
    land."""


class RetriesExhausted(FleetError):
    """A request's replica died and its retry budget ran out.

    When raised by `run()` it aggregates: ``failed`` maps each lost
    fleet request id to its underlying error, ``partial`` carries the
    results of every request that DID finish (so a caller can keep
    them instead of re-running the world)."""

    def __init__(self, msg: str, *,
                 failed: Optional[Dict[int, Exception]] = None,
                 partial: Optional[Dict[int, List[int]]] = None):
        super().__init__(msg)
        self.failed = failed or {}
        self.partial = partial or {}


# ---------------------------------------------------------------------------
# Replica pool
# ---------------------------------------------------------------------------

RUNNING = "RUNNING"
DRAINING = "DRAINING"
SUSPECT = "SUSPECT"       # probation: router skips it, step() watches it
UNHEALTHY = "UNHEALTHY"   # condemned: failover in progress
RETIRED = "RETIRED"       # out of the pool (failed replicas only;
#                           drained replicas are simply removed)


class _Replica:
    """One DecodeEngine plus its fleet bookkeeping: the replica-local
    request-id -> fleet request-id map (each engine numbers its own
    requests from 0), the health/lifecycle state the router and scaler
    act on, and the health-probe streaks the state machine runs on."""

    __slots__ = ("name", "engine", "state", "rid_to_fid", "routed",
                 "slow_streak", "silent_streak", "good_streak",
                 "failures", "timeouts", "suspect_events",
                 "breaker_open_until", "breaker_trips",
                 "replica_class")

    def __init__(self, name: str, engine,
                 replica_class: Optional[str] = None):
        self.name = name
        self.engine = engine
        # Disaggregated fleets run two replica classes: "prefill"
        # (admission + chunked prefill only; finished KV is handed
        # off) and "decode" (imports handoffs, runs fused decode).
        # None = colocated (both workloads), the default.
        self.replica_class = replica_class
        self.state = RUNNING
        self.rid_to_fid: Dict[int, int] = {}
        self.routed = 0          # requests this replica has been given
        # Health-probe streaks (reset on a good step):
        self.slow_streak = 0     # consecutive steps over slow_step_s
        self.silent_streak = 0   # consecutive no-progress steps
        self.good_streak = 0     # consecutive clean steps (recovery)
        self.failures = 0        # step() exceptions seen
        self.timeouts = 0        # watchdog (step_deadline_s) breaches
        self.suspect_events: List[float] = []   # SUSPECT entry times
        self.breaker_open_until = 0.0           # clock time; 0 = closed
        self.breaker_trips = 0


class _FleetReq:
    """Host-side bookkeeping for one fleet request — everything
    deterministic failover needs to reconstruct it on another replica:
    the normalized prompt, the budget/priority/greedy knobs, and the
    PINNED sampling key (fleet-derived from the fleet id/seed and the
    FLEET request id, so the stream survives any re-placement)."""

    __slots__ = ("fid", "prompt", "max_new_tokens", "priority",
                 "greedy", "rng", "adapter_id", "attempts", "emitted",
                 "tokens", "recovering", "handoff", "submit_t")

    def __init__(self, fid: int, prompt: List[int],
                 max_new_tokens: int, priority: int, greedy,
                 rng: np.ndarray, adapter_id: Optional[str] = None):
        self.fid = fid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.priority = priority
        self.greedy = greedy
        self.rng = rng
        self.adapter_id = adapter_id
        self.attempts = 1        # submissions so far (retries = n-1)
        self.emitted = 0         # tokens already streamed to the caller
        self.tokens: List[int] = []   # salvage buffer while recovering
        self.recovering = False  # in the retry queue right now
        self.handoff = None      # exported engine state while the
        #                          request is between replica classes
        self.submit_t: Optional[float] = None   # fleet-clock submit
        #                          time (fleet-side TTFT in disagg)


# ---------------------------------------------------------------------------
# Routers
# ---------------------------------------------------------------------------

def replica_score(replica: _Replica, prompt: List[int],
                  *, queue_cost: float = 64.0,
                  slot_cost: float = 8.0) -> float:
    """Estimated cost (in prompt-token equivalents) of placing `prompt`
    on `replica` RIGHT NOW — the scoring function both routers and the
    bench share.

    pending_prefill_tokens is the real backlog unit (prompt tokens owed
    before the newcomer's prefill can start); queue depth and KV
    occupancy are converted to the same unit with fixed exchange rates
    (`queue_cost` per queued request ~ a short prompt's prefill,
    `slot_cost` per occupied slot-equivalent ~ the decode interference
    it adds); the prompt's own cost counts only its COLD suffix —
    tokens the replica's prefix pool cannot copy (probed with
    peek=True: scoring must not touch any replica's LRU recency; only
    the winner's trie is touched, at admission).

    Occupancy reads through `kv_used_fraction()`: on a DENSE engine
    that is live_rows / batch_slots, so the term equals the historical
    `live * slot_cost` exactly; on a PAGED engine it is the fraction
    of KV pool blocks not free-or-evictable, so a replica whose pool
    is nearly dry — about to preempt — scores as loaded even when its
    row slots look empty, and the router steers toward free KV blocks.
    All host-side reads, zero device work per decision.

    Replica CLASSES score on what they actually do (disaggregated
    fleets): a "prefill" replica's cost is its prefill backlog —
    queue + pending prompt tokens + the newcomer's cold suffix; its
    decode-slot terms are meaningless (it never decodes). A "decode"
    replica's cost is decode interference — live slots plus KV-pool
    pressure (the preemption predictor) plus queue; the prompt's cold
    suffix is irrelevant because its KV arrives pre-computed through
    the handoff. Colocated replicas (class None) keep the historical
    blended score."""
    eng = replica.engine
    queued = float(len(eng.scheduler))
    if hasattr(eng, "kv_used_fraction"):
        occupied = eng.kv_used_fraction() * len(eng.row_req)
    else:
        occupied = float(sum(r is not None for r in eng.row_req))
    klass = getattr(replica, "replica_class", None)
    if klass == "prefill":
        pending = float(eng.pending_prefill_tokens())
        cold = float(max(len(prompt)
                         - eng.prefix_match_tokens(prompt), 1))
        return queued * queue_cost + pending + cold
    if klass == "decode":
        live = float(sum(r is not None for r in eng.row_req))
        kv_pressure = (eng.kv_used_fraction()
                       if hasattr(eng, "kv_used_fraction") else 0.0)
        return (queued * queue_cost + live * slot_cost
                + kv_pressure * len(eng.row_req) * slot_cost + 1.0)
    pending = float(eng.pending_prefill_tokens())
    cold = float(max(len(prompt) - eng.prefix_match_tokens(prompt), 1))
    return queued * queue_cost + occupied * slot_cost + pending + cold


class FleetRouter:
    """Chooses the replica a request is submitted to. Only RUNNING
    replicas with a closed circuit breaker are offered (the fleet
    filters the rest out before calling).

    Routers that score on multi-LoRA adapter residency set
    `supports_adapter_affinity = True` and accept an ``adapter_id``
    keyword in `choose`; the fleet only passes the keyword to routers
    that advertise it, so existing custom routers keep working."""

    name = "base"
    supports_adapter_affinity = False

    def choose(self, replicas: List[_Replica],
               prompt: List[int]) -> _Replica:
        raise NotImplementedError


class RoundRobinRouter(FleetRouter):
    """Stats-blind baseline: replicas in rotation. Exists to be beaten
    — the bench's control arm for the pow-2 + affinity router."""

    name = "round_robin"

    def __init__(self):
        self._i = 0

    def choose(self, replicas: List[_Replica],
               prompt: List[int]) -> _Replica:
        rep = replicas[self._i % len(replicas)]
        self._i += 1
        return rep


class PowerOfTwoAffinityRouter(FleetRouter):
    """Power-of-two-choices over `replica_score`, with a prefix-
    affinity override.

    Affinity first: the replica whose radix index holds the LONGEST
    committed prefix of this prompt wins outright — IF its score stays
    within `affinity_overload_factor` of the best score in the fleet.
    The cap is what keeps affinity from defeating itself: without it,
    every request of a hot shared-prefix group piles onto the one warm
    replica until its queue dwarfs the prefill it saves (the classic
    cache-affinity hotspot). Past the cap the request routes by load
    and becomes the group's cache seed on a second replica.

    Multi-LoRA requests get the same treatment one level up: when the
    fleet passes ``adapter_id``, a replica whose AdapterPool already
    holds that adapter RESIDENT in HBM wins (lowest-score resident
    candidate), under the same overload cap — routing to a cold
    replica costs a host->device adapter transfer plus an admission
    deferral, which is the adapter analog of recomputing a cached
    prefix. Adapter affinity outranks prefix affinity: adapter rows
    bypass the prefix trie entirely, so their prefix term is always
    cold anyway.

    Otherwise pow-2: sample two distinct candidates with a SEEDED
    stream (deterministic tests and benches), pick the lower score.
    Two random choices get within a constant factor of scanning all N
    — the Serve router's own rationale — and the score here folds in
    everything stats() knows, not just queue length."""

    name = "pow2_affinity"
    supports_adapter_affinity = True

    def __init__(self, *, seed: int = 0, affinity: bool = True,
                 affinity_overload_factor: float = 4.0,
                 queue_cost: float = 64.0, slot_cost: float = 8.0):
        if affinity_overload_factor < 1.0:
            raise ValueError("affinity_overload_factor must be >= 1.0")
        self._rng = random.Random(seed)
        self.affinity = affinity
        self.affinity_overload_factor = affinity_overload_factor
        self.queue_cost = queue_cost
        self.slot_cost = slot_cost
        self.affinity_wins = 0   # decisions the prefix override took
        self.adapter_wins = 0    # decisions the adapter override took
        self.pow2_wins = 0       # decisions left to power-of-two

    def _score(self, rep: _Replica, prompt: List[int]) -> float:
        return replica_score(rep, prompt, queue_cost=self.queue_cost,
                             slot_cost=self.slot_cost)

    def choose(self, replicas: List[_Replica], prompt: List[int],
               adapter_id: Optional[str] = None) -> _Replica:
        if len(replicas) == 1:
            return replicas[0]
        if self.affinity and adapter_id is not None:
            scores = [self._score(r, prompt) for r in replicas]
            best_score = min(scores)
            warm = [
                i for i, r in enumerate(replicas)
                if getattr(r.engine, "adapter_resident",
                           lambda _aid: False)(adapter_id)]
            if warm:
                i = min(warm, key=lambda k: scores[k])
                if scores[i] <= self.affinity_overload_factor * \
                        (best_score + 1.0):
                    self.adapter_wins += 1
                    return replicas[i]
        if self.affinity:
            scores = [self._score(r, prompt) for r in replicas]
            best_score = min(scores)
            warm_i, warm_tokens = -1, 0
            for i, r in enumerate(replicas):
                m = r.engine.prefix_match_tokens(prompt)
                if m > warm_tokens:
                    warm_i, warm_tokens = i, m
            if warm_i >= 0 and scores[warm_i] <= \
                    self.affinity_overload_factor * (best_score + 1.0):
                self.affinity_wins += 1
                return replicas[warm_i]
        i = self._rng.randrange(len(replicas))
        j = self._rng.randrange(len(replicas) - 1)
        if j >= i:
            j += 1
        a, b = replicas[i], replicas[j]
        self.pow2_wins += 1
        return a if self._score(a, prompt) <= self._score(b, prompt) \
            else b


_ROUTERS = {"round_robin": RoundRobinRouter,
            "pow2": PowerOfTwoAffinityRouter,
            "pow2_affinity": PowerOfTwoAffinityRouter}


def make_router(spec: Union[str, FleetRouter]) -> FleetRouter:
    """Resolve a router spec: an instance passes through, a name
    ("round_robin" | "pow2" | "pow2_affinity") constructs the
    built-in."""
    if isinstance(spec, FleetRouter):
        return spec
    try:
        return _ROUTERS[spec]()
    except (KeyError, TypeError):
        raise ValueError(
            f"unknown fleet router {spec!r}: expected a FleetRouter "
            f"instance or one of {sorted(_ROUTERS)}")


# ---------------------------------------------------------------------------
# Autoscaler
# ---------------------------------------------------------------------------

class FleetAutoscalingConfig:
    """Scaling policy knobs for `EngineStatsAutoscaler`.

    The breach signals are the SERVING SLOs, not traffic: TTFT p95 over
    `ttft_p95_slo_s` (the tail of submit -> first token, the number a
    user feels) or mean slot occupancy over `occupancy_high` (the fleet
    is out of decode slots even if the tail has not blown up yet), or —
    when `target_custom_metric` is set — a caller-recorded scalar
    (`serve.metrics.record_autoscaling_metric`, read back through
    `custom_metric_source`) exceeding its target. Scale-down needs ALL
    clear: occupancy under `occupancy_low`, custom metric (if any)
    under target, TTFT inside SLO.

    `upscale_hold_s` / `downscale_hold_s` are the hysteresis: a breach
    (resp. idle spell) must be CONTINUOUS for that long before the
    scaler acts, and the timers reset whenever the condition breaks.
    Downscale defaults much slower than upscale — adding a replica
    wastes a little compute; removing one into a traffic return wastes
    user latency."""

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 4,
                 ttft_p95_slo_s: Optional[float] = None,
                 tpot_p95_slo_s: Optional[float] = None,
                 occupancy_high: float = 0.85,
                 occupancy_low: float = 0.30,
                 upscale_hold_s: float = 3.0,
                 downscale_hold_s: float = 30.0,
                 target_custom_metric: Optional[float] = None,
                 custom_metric_source: Optional[
                     Callable[[], Optional[float]]] = None):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if not 0.0 <= occupancy_low <= occupancy_high <= 1.0:
            raise ValueError(
                "need 0 <= occupancy_low <= occupancy_high <= 1")
        if upscale_hold_s < 0 or downscale_hold_s < 0:
            raise ValueError("hold times must be >= 0")
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.ttft_p95_slo_s = ttft_p95_slo_s
        # TPOT tail SLO: the decode-side twin of ttft_p95_slo_s. In a
        # disaggregated fleet the decode class scales on this (TTFT
        # gates the prefill class); a colocated fleet may set both.
        self.tpot_p95_slo_s = tpot_p95_slo_s
        self.occupancy_high = occupancy_high
        self.occupancy_low = occupancy_low
        self.upscale_hold_s = upscale_hold_s
        self.downscale_hold_s = downscale_hold_s
        self.target_custom_metric = target_custom_metric
        self.custom_metric_source = custom_metric_source


class FleetHealthConfig:
    """Fault-tolerance knobs for the fleet's per-replica health state
    machine, retry policy, and circuit breaker.

    Health probes (all evaluated by the fleet around each
    `engine.step()`, on the fleet's injected clock):

    - ``step_deadline_s`` — the WATCHDOG: a step that takes at least
      this long is a timeout event; ``unhealthy_after_timeouts`` of
      them (cumulative) condemn the replica. None disables.
    - ``slow_step_s`` — softer probe: ``suspect_after_slow``
      CONSECUTIVE steps at least this slow put the replica on
      SUSPECT probation (routed around, still stepped). None disables.
    - ``suspect_after_silent`` / ``unhealthy_after_silent`` —
      no-progress detection: a step that returns without advancing the
      engine at all (its step counter frozen while work is pending —
      the failure mode of a wedged or hijacked step) is a silent
      event; consecutive silents escalate SUSPECT then UNHEALTHY.
    - ``max_step_failures`` — a step() EXCEPTION condemns the replica
      once this many have been seen (default 1: fail fast; raise it to
      tolerate transient errors via SUSPECT first).
    - ``recover_after`` — clean consecutive steps that promote a
      SUSPECT replica back to RUNNING.

    Retry/backoff (per request, on replica failure): the first
    failover resubmits immediately; retry n >= 2 waits
    ``backoff_base_s * backoff_factor**(n-2)`` capped at
    ``backoff_max_s``, stretched by up to 50% deterministic jitter
    derived from the REQUEST's rng key (reproducible chaos runs).
    After ``max_retries`` retries the request surfaces as
    `RetriesExhausted`.

    Circuit breaker (per replica): ``breaker_trips`` entries into
    SUSPECT within ``breaker_window_s`` open the breaker for
    ``breaker_cooldown_s`` — the router stops offering the replica
    even after it recovers to RUNNING, until the cooldown lapses
    (half-open). Failover RESUBMISSIONS ignore the breaker:
    a recovery must land somewhere, and the breaker's job is load
    placement, not correctness.

    ``replace_failed`` — a condemned replica is REPLACED (a fresh
    replica from the factory joins as it retires), not merely counted
    out, so capacity survives the failure; the autoscaler never sees
    the dead replica in its replica count."""

    def __init__(self, *, step_deadline_s: Optional[float] = None,
                 slow_step_s: Optional[float] = None,
                 suspect_after_slow: int = 3,
                 suspect_after_silent: int = 2,
                 unhealthy_after_silent: int = 4,
                 unhealthy_after_timeouts: int = 2,
                 max_step_failures: int = 1,
                 recover_after: int = 2,
                 max_retries: int = 3,
                 backoff_base_s: float = 0.02,
                 backoff_factor: float = 2.0,
                 backoff_max_s: float = 1.0,
                 breaker_trips: int = 3,
                 breaker_window_s: float = 30.0,
                 breaker_cooldown_s: float = 5.0,
                 replace_failed: bool = True):
        if step_deadline_s is not None and step_deadline_s <= 0:
            raise ValueError("step_deadline_s must be > 0")
        if slow_step_s is not None and slow_step_s <= 0:
            raise ValueError("slow_step_s must be > 0")
        if step_deadline_s is not None and slow_step_s is not None \
                and slow_step_s > step_deadline_s:
            raise ValueError("slow_step_s must be <= step_deadline_s")
        for nm, v in (("suspect_after_slow", suspect_after_slow),
                      ("suspect_after_silent", suspect_after_silent),
                      ("unhealthy_after_silent", unhealthy_after_silent),
                      ("unhealthy_after_timeouts",
                       unhealthy_after_timeouts),
                      ("max_step_failures", max_step_failures),
                      ("recover_after", recover_after),
                      ("breaker_trips", breaker_trips)):
            if v < 1:
                raise ValueError(f"{nm} must be >= 1")
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if backoff_base_s < 0 or backoff_max_s < 0:
            raise ValueError("backoff times must be >= 0")
        if backoff_factor < 1.0:
            raise ValueError("backoff_factor must be >= 1.0")
        if breaker_window_s <= 0 or breaker_cooldown_s <= 0:
            raise ValueError("breaker window/cooldown must be > 0")
        self.step_deadline_s = step_deadline_s
        self.slow_step_s = slow_step_s
        self.suspect_after_slow = suspect_after_slow
        self.suspect_after_silent = suspect_after_silent
        self.unhealthy_after_silent = unhealthy_after_silent
        self.unhealthy_after_timeouts = unhealthy_after_timeouts
        self.max_step_failures = max_step_failures
        self.recover_after = recover_after
        self.max_retries = max_retries
        self.backoff_base_s = backoff_base_s
        self.backoff_factor = backoff_factor
        self.backoff_max_s = backoff_max_s
        self.breaker_trips = breaker_trips
        self.breaker_window_s = breaker_window_s
        self.breaker_cooldown_s = breaker_cooldown_s
        self.replace_failed = replace_failed


class EngineStatsAutoscaler:
    """Hysteresis state machine over per-replica engine stats.

    `tick(stats_list, n_replicas)` returns the scale decision for this
    instant: +1 (add a replica), -1 (drain one), or 0. The caller (the
    fleet) applies it; the scaler only decides. Mirrors the serve
    controller's AutoscalingState decision-hold pattern
    (_private/autoscaling.py) but reads the LLM-native gauges: worst
    per-replica TTFT p95 (one hot replica IS an SLO breach — means
    would hide it), mean occupancy (fleet-level headroom), and the
    optional custom metric.

    All timing flows through the injected clock, so tests drive
    hysteresis with a fake clock instead of sleeping real time."""

    def __init__(self, config: FleetAutoscalingConfig,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config
        self._clock = clock
        self._breach_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self.scale_ups = 0
        self.scale_downs = 0
        # Last tick's inputs/verdict, for stats() and the bench log.
        self.last_signals: Dict[str, float] = {}

    def _signals(self, stats_list: List[Dict[str, float]]
                 ) -> Tuple[float, float, float, float, Optional[float]]:
        ttft_p95 = max((s.get("ttft_s_p95", 0.0) for s in stats_list),
                       default=0.0)
        tpot_p95 = max((s.get("tpot_s_p95", 0.0) for s in stats_list),
                       default=0.0)
        occ = (sum(s.get("slot_occupancy", 0.0) for s in stats_list)
               / len(stats_list)) if stats_list else 0.0
        qdepth = sum(s.get("queue_depth", 0.0) for s in stats_list)
        custom = None
        if self.config.custom_metric_source is not None:
            custom = self.config.custom_metric_source()
        return ttft_p95, tpot_p95, occ, qdepth, custom

    def tick(self, stats_list: List[Dict[str, float]],
             n_replicas: int) -> int:
        """One scaling decision from the current per-replica snapshots.
        Call at the fleet's step cadence; returns +1 / 0 / -1."""
        cfg = self.config
        now = self._clock()
        ttft_p95, tpot_p95, occ, qdepth, custom = \
            self._signals(stats_list)

        # TTFT/TPOT p95 are sliding WINDOWS over past requests — once
        # traffic stops the window goes stale at its last (bad) value.
        # A latency breach therefore only counts while the fleet is
        # actually busy (work queued or slots occupied); an idle fleet
        # quoting an old p95 must scale DOWN, not up.
        busy = occ > 0.0 or qdepth > 0.0
        breach = occ > cfg.occupancy_high
        if busy and cfg.ttft_p95_slo_s is not None and \
                ttft_p95 > cfg.ttft_p95_slo_s:
            breach = True
        if busy and cfg.tpot_p95_slo_s is not None and \
                tpot_p95 > cfg.tpot_p95_slo_s:
            breach = True
        if cfg.target_custom_metric is not None and custom is not None \
                and custom > cfg.target_custom_metric:
            breach = True

        idle = (not breach) and occ < cfg.occupancy_low
        if cfg.target_custom_metric is not None and custom is not None \
                and custom >= cfg.target_custom_metric:
            idle = False

        self.last_signals = {
            "ttft_p95": ttft_p95, "tpot_p95": tpot_p95,
            "occupancy": occ,
            "queue_depth": qdepth,
            "custom": float("nan") if custom is None else custom,
            "breach": 1.0 if breach else 0.0,
            "idle": 1.0 if idle else 0.0,
        }

        if breach:
            self._idle_since = None
            if self._breach_since is None:
                self._breach_since = now
            if now - self._breach_since >= cfg.upscale_hold_s and \
                    n_replicas < cfg.max_replicas:
                self._breach_since = None   # re-arm: next +1 needs a
                self.scale_ups += 1         # fresh sustained breach
                return +1
            return 0
        self._breach_since = None

        if idle:
            if self._idle_since is None:
                self._idle_since = now
            if now - self._idle_since >= cfg.downscale_hold_s and \
                    n_replicas > cfg.min_replicas:
                self._idle_since = None
                self.scale_downs += 1
                return -1
            return 0
        self._idle_since = None
        return 0


# ---------------------------------------------------------------------------
# Fleet facade
# ---------------------------------------------------------------------------

_fleet_gauges: Dict[str, Gauge] = {}
_fleet_counters: Dict[str, Counter] = {}


class LLMFleet:
    """N `DecodeEngine` replicas behind one engine-shaped API.

    `engine_factory(name)` builds one replica's engine (the fleet
    passes a unique replica name — use it as `engine_id` so the
    per-engine `llm_engine_*` series stay separable). The fleet owns
    replica lifecycle: it starts with `initial_replicas` (or the
    autoscaler's min), the router places every `submit`, `step()`
    advances every replica one engine step — under the health state
    machine's supervision — and applies at most one scale decision;
    DRAINING replicas leave the pool only once empty, UNHEALTHY ones
    fail over their work and are replaced.

    The API mirrors DecodeEngine on purpose — submit / step / run /
    pending / pop_result / finished / shed_ids / stats — so a serving
    loop written against one engine drives a fleet unchanged. Request
    ids are FLEET-scoped (each engine numbers its own; the fleet maps
    engine ids back per replica). The fleet pins every request's
    sampling key at submit (derived from `rng_seed` and the FLEET id
    when the caller passes none), which is what makes failover
    deterministic: the stream depends on the request, never on the
    replica that happens to run it.

    ``fault_injector`` (a `models.fault_injection.FaultInjector`) is
    armed on every replica the factory builds — including autoscale
    and failure replacements — so chaos schedules keep biting
    mid-churn."""

    def __init__(self, engine_factory: Callable[[str], object], *,
                 initial_replicas: Optional[int] = None,
                 router: Union[str, FleetRouter] = "pow2_affinity",
                 autoscaling: Optional[FleetAutoscalingConfig] = None,
                 health: Optional[FleetHealthConfig] = None,
                 fleet_id: str = "fleet-0",
                 rng_seed: int = 0,
                 fault_injector=None,
                 trace=None,
                 clock: Callable[[], float] = time.monotonic,
                 disaggregated: bool = False,
                 prefill_replicas: Optional[int] = None,
                 decode_replicas: Optional[int] = None,
                 prefill_autoscaling: Optional[
                     FleetAutoscalingConfig] = None,
                 decode_autoscaling: Optional[
                     FleetAutoscalingConfig] = None):
        self._factory = engine_factory
        self.router = make_router(router)
        self.fleet_id = fleet_id
        self._clock = clock
        self.health = health if health is not None else \
            FleetHealthConfig()
        self._injector = fault_injector
        # Fleet-level tracer: holds the `route` spans (one per submit,
        # carrying the router's scoring decision) that stitch replica
        # traces into one request story. Same knob semantics as
        # DecodeEngine(trace=...): instance / True / False / None
        # (env gate). Replica ENGINE tracing stays the factory's call —
        # dump_trace() merges whatever replicas traced.
        self.trace = resolve_tracer(trace, engine_id=fleet_id,
                                    clock=clock)
        self._retired_trace: List[dict] = []   # removed replicas' spans
        # Disaggregated prefill/decode (DistServe/Splitwise shape):
        # the replica pool splits into a "prefill" class (admission +
        # chunked prefill only; finished KV is exported) and a
        # "decode" class (imports handoffs, runs fused decode), each
        # scaled by its OWN autoscaler — TTFT p95 gates prefill
        # capacity, TPOT p95 gates decode capacity. Colocated fleets
        # (the default) keep the single shared pool and scaler.
        self.disaggregated = bool(disaggregated)
        if not self.disaggregated and (
                prefill_replicas is not None
                or decode_replicas is not None
                or prefill_autoscaling is not None
                or decode_autoscaling is not None):
            raise ValueError(
                "prefill_*/decode_* fleet knobs require "
                "disaggregated=True")
        if self.disaggregated and (autoscaling is not None
                                   or initial_replicas is not None):
            raise ValueError(
                "disaggregated=True sizes and scales per class: use "
                "prefill_replicas/decode_replicas and "
                "prefill_autoscaling/decode_autoscaling instead of "
                "initial_replicas/autoscaling")
        self.autoscaler = (EngineStatsAutoscaler(autoscaling, clock)
                           if autoscaling is not None else None)
        self._prefill_scaler = (
            EngineStatsAutoscaler(prefill_autoscaling, clock)
            if prefill_autoscaling is not None else None)
        self._decode_scaler = (
            EngineStatsAutoscaler(decode_autoscaling, clock)
            if decode_autoscaling is not None else None)
        # Fleet-level adapter table: {adapter_id: lora_init-shaped
        # host tree}. register_adapter fans out to every replica and
        # REPLAYS onto replicas that join later (autoscale, failure
        # replacement), so routing never depends on when a replica was
        # born relative to a registration.
        self._adapters: Dict[str, object] = {}
        self.replicas: List[_Replica] = []
        self._next_replica = 0
        if self.disaggregated:
            n_pre = prefill_replicas
            if n_pre is None:
                n_pre = (prefill_autoscaling.min_replicas
                         if prefill_autoscaling else 1)
            n_dec = decode_replicas
            if n_dec is None:
                n_dec = (decode_autoscaling.min_replicas
                         if decode_autoscaling else 1)
            for klass, n_k, cfg_k in (
                    ("prefill", n_pre, prefill_autoscaling),
                    ("decode", n_dec, decode_autoscaling)):
                if n_k < 1:
                    raise ValueError(
                        f"{klass}_replicas must be >= 1")
                if cfg_k is not None and not \
                        cfg_k.min_replicas <= n_k \
                        <= cfg_k.max_replicas:
                    raise ValueError(
                        f"{klass}_replicas {n_k} outside autoscaling "
                        f"bounds [{cfg_k.min_replicas}, "
                        f"{cfg_k.max_replicas}]")
            for _ in range(n_pre):
                self.add_replica(replica_class="prefill")
            for _ in range(n_dec):
                self.add_replica(replica_class="decode")
        else:
            n = initial_replicas
            if n is None:
                n = autoscaling.min_replicas if autoscaling else 2
            if n < 1:
                raise ValueError("initial_replicas must be >= 1")
            if autoscaling is not None and \
                    not autoscaling.min_replicas <= n \
                    <= autoscaling.max_replicas:
                raise ValueError(
                    f"initial_replicas {n} outside autoscaling bounds "
                    f"[{autoscaling.min_replicas}, "
                    f"{autoscaling.max_replicas}]")
            for _ in range(n):
                self.add_replica()
        # Handoff plane: fids whose exported engine state is parked on
        # the host (no decode replica could import right now), plus
        # the fleet's own submit->first-token latency window — prefill
        # engines never emit tokens, so the fleet measures the
        # user-visible TTFT itself and feeds it to the prefill scaler.
        self._handoff_parked: List[int] = []
        self.handoffs = 0
        self._ttft_agg = _Agg()
        self._next_fid = 0
        self._placement: Dict[int, Tuple[_Replica, int]] = {}
        self._requests: Dict[int, _FleetReq] = {}
        self._done: Dict[int, List[int]] = {}
        self.finished: set = set()
        self.shed_ids: set = set()
        self.failed: Dict[int, FleetError] = {}
        self.failed_ids: set = set()
        # Retry queue: (ready_at, seq, fid) min-heap; seq keeps pops
        # FIFO among retries due at the same instant.
        self._retry: List[Tuple[float, int, int]] = []
        self._retry_seq = 0
        # Tokens salvaged from a dead replica that were never streamed
        # through step()'s emissions — surfaced in the NEXT step's
        # merged dict so streaming callers see a gapless sequence.
        self._pending_emit: Dict[int, List[int]] = {}
        self.requests_routed = 0
        self.requests_shed = 0
        self.requests_failed = 0
        self.requests_recovered = 0
        self.retries = 0
        self.replicas_removed = 0
        self.replicas_failed = 0
        self.tokens_lost_to_drain = 0   # stays 0 by construction;
        #                                 asserted in tests AND here
        self.tokens_lost_to_failure = 0  # ditto, for the failover path
        # Per-request sampling-key root: two 32-bit halves mixed from
        # rng_seed (splitmix-style), XOR-folded with the fleet request
        # id in `_fid_key`.
        s = (rng_seed * 0x9E3779B97F4A7C15 + 0xD1B54A32D192ED03) \
            & 0xFFFFFFFFFFFFFFFF
        self._seed0 = (s >> 32) & 0xFFFFFFFF
        self._seed1 = s & 0xFFFFFFFF
        # Weak registration in the serving state API: summarize_fleet /
        # the status CLI find this fleet (and attribute its replicas'
        # engines) without the fleet holding any extra lifecycle.
        from ray_tpu.util.state.serving import register_fleet
        register_fleet(self)

    # -- replica lifecycle -------------------------------------------------

    def add_replica(self,
                    replica_class: Optional[str] = None) -> str:
        """Build a fresh replica via the factory and put it in the
        routing rotation; returns its name. Arms the fleet's fault
        injector (when one is configured) so chaos schedules cover
        replacements too.

        ``replica_class`` ("prefill" | "decode" | None) is a FLEET
        placement attribute stamped onto the engine after construction
        — any engine_factory works unchanged. A "prefill" engine gets
        `prefill_only = True`: its step() parks completed prefills for
        export instead of decoding them."""
        if replica_class not in (None, "prefill", "decode"):
            raise ValueError(
                f"replica_class must be 'prefill', 'decode' or None, "
                f"got {replica_class!r}")
        name = f"{self.fleet_id}-r{self._next_replica}"
        self._next_replica += 1
        engine = self._factory(name)
        if replica_class is not None:
            engine.replica_class = replica_class
            if replica_class == "prefill":
                engine.prefill_only = True
        if self._injector is not None:
            self._injector.arm(engine, name)
        if self._adapters and \
                getattr(engine, "adapter_pool", None) is not None:
            for aid, params in self._adapters.items():
                engine.register_adapter(aid, params)
        self.replicas.append(_Replica(name, engine, replica_class))
        return name

    def register_adapter(self, adapter_id: str, lora_params) -> None:
        """Admit a LoRA adapter fleet-wide: register its weights on
        every pooled replica that carries an AdapterPool (and on every
        future replica, via the fleet table). Raises if NO replica can
        serve adapters — a silent no-op would route adapter traffic
        into per-engine submit errors later."""
        pools = [r for r in self.replicas
                 if getattr(r.engine, "adapter_pool", None) is not None]
        if not pools:
            raise ValueError(
                "register_adapter: no replica was built with lora= "
                "(engine_factory must enable the adapter pool)")
        for rep in pools:
            rep.engine.register_adapter(adapter_id, lora_params)
        self._adapters[adapter_id] = lora_params

    def unregister_adapter(self, adapter_id: str) -> None:
        """Drop an adapter fleet-wide (per-replica removal defers
        until that replica's last live row using it retires)."""
        self._adapters.pop(adapter_id, None)
        for rep in self.replicas:
            if getattr(rep.engine, "adapter_pool", None) is not None:
                rep.engine.unregister_adapter(adapter_id)

    def adapter_ids(self) -> List[str]:
        return sorted(self._adapters)

    def drain_replica(self, name: str) -> None:
        """Move a replica to DRAINING: its engine refuses new submits
        (EngineDraining), the router no longer offers it, and `step()`
        keeps advancing it until empty, then removes it. In-flight and
        queued work all complete — flush-before-removal."""
        rep = self._replica(name)
        rep.state = DRAINING
        rep.engine.begin_drain()

    def _replica(self, name: str) -> _Replica:
        for rep in self.replicas:
            if rep.name == name:
                return rep
        raise KeyError(f"no replica named {name!r}")

    def _running(self) -> List[_Replica]:
        return [r for r in self.replicas if r.state == RUNNING]

    def _routable(self) -> List[_Replica]:
        """RUNNING replicas whose circuit breaker is closed. Falls back
        to ALL RUNNING replicas when every breaker is open — serving
        somewhere beats serving nowhere."""
        running = self._running()
        now = self._clock()
        closed = [r for r in running if now >= r.breaker_open_until]
        return closed or running

    # -- request path ------------------------------------------------------

    def _fid_key(self, fid: int) -> np.ndarray:
        """The pinned per-request sampling key: a distinct uint32[2]
        stream mixed host-side from the fleet seed and the FLEET
        request id. Deriving from the fleet id — never the replica or
        its engine-local request numbering — is the failover
        determinism guarantee for sampled requests: any replica that
        (re)runs request `fid` samples the identical stream."""
        mix0 = (fid * 0x9E3779B9 + 0x7F4A7C15) & 0xFFFFFFFF
        mix1 = (fid * 0x85EBCA6B + 0xC2B2AE35) & 0xFFFFFFFF
        return np.array([self._seed0 ^ mix0, self._seed1 ^ mix1],
                        np.uint32)

    def submit(self, prompt: List[int], max_new_tokens: int = 32,
               priority: int = 0, rng=None,
               deadline_s: Optional[float] = None,
               greedy: Optional[bool] = None,
               adapter_id: Optional[str] = None) -> int:
        """Route and enqueue one request; returns its FLEET id.

        priority / deadline_s / greedy pass straight through to the
        chosen engine's submit. The sampling key does NOT pass through
        untouched: when ``rng`` is None the fleet derives a per-request
        key from its own seed and the fleet request id and pins it, so
        the request's sampled stream is a function of the REQUEST, not
        of whichever replica runs (or re-runs, after a failure) it. A
        dead-on-arrival deadline still routes (the engine sheds it
        before it can occupy a queue slot) and is visible in
        `finished` + `shed_ids` immediately. Raises
        `ReplicaUnavailable` when no RUNNING replica exists.

        ``adapter_id`` selects a registered LoRA adapter (None = base
        model): the router scores on HBM residency when it advertises
        adapter affinity, and the id passes through to the engine's
        adapter-gated admission."""
        routable = self._routable()
        if self.disaggregated:
            # New requests land on the prefill class — that is the
            # whole point of the split. Fall back to whatever runs
            # (decode replicas are full colocated engines) only when
            # the prefill class is momentarily empty mid-churn.
            pre = [r for r in routable
                   if r.replica_class == "prefill"]
            routable = pre or routable
        if not routable:
            raise ReplicaUnavailable(
                "fleet has no RUNNING replicas to route to")
        if adapter_id is not None and adapter_id not in self._adapters:
            raise KeyError(
                f"unknown adapter_id {adapter_id!r}: call "
                "register_adapter first")
        prompt = [int(t) for t in prompt]
        fid = self._next_fid
        key = self._fid_key(fid) if rng is None else rng
        tr = self.trace
        if tr.enabled:
            # Snapshot what the router is about to see (pure peek
            # probes, no LRU perturbation) so the route span carries
            # the scoring decision, not a post-hoc reconstruction.
            t0 = tr.now()
            scores = {r.name: round(replica_score(r, prompt), 2)
                      for r in routable}
            warm = {r.name: r.engine.prefix_match_tokens(prompt)
                    for r in routable}
        rep = self._choose(routable, prompt, adapter_id)
        # adapter_id rides as a kwarg only when set: stub/legacy
        # engines without the multi-LoRA plane keep working.
        ad_kw = {} if adapter_id is None else {"adapter_id": adapter_id}
        rid = rep.engine.submit(prompt, max_new_tokens,
                                priority=priority, rng=key,
                                deadline_s=deadline_s, greedy=greedy,
                                **ad_kw)
        self._next_fid += 1
        if tr.enabled:
            tr.add("route", t0, tr.now() - t0, req_id=fid,
                   args={"replica": rep.name, "rid": rid,
                         "router": getattr(self.router, "name",
                                           type(self.router).__name__),
                         "scores": scores, "warm_tokens": warm,
                         "warm": warm.get(rep.name, 0) > 0})
        # Pin the key in canonical host form (raw uint32[2] bits):
        # failover resubmission must replay the SAME stream whether the
        # caller passed a legacy key array, a typed key, or nothing.
        self._requests[fid] = _FleetReq(
            fid, prompt, max_new_tokens, priority, greedy,
            _key_data(key), adapter_id)
        if self.disaggregated:
            self._requests[fid].submit_t = self._clock()
        rep.rid_to_fid[rid] = fid
        self._placement[fid] = (rep, rid)
        rep.routed += 1
        self.requests_routed += 1
        self._sweep_finished(rep)    # DOA sheds surface immediately
        return fid

    def step(self) -> Dict[int, List[int]]:
        """Advance every replica one engine step; returns the merged
        {fleet_id: new tokens} emissions. Also applies at most one
        autoscaler decision, runs the health state machine over every
        step (exceptions, watchdog, slow/silent probes — failing
        replicas fail over their work here), resubmits due retries,
        and retires DRAINING replicas that have run empty.

        The scale decision is taken on the PRE-step snapshots: submits
        land between steps, so the backlog visible now — before this
        step consumes any of it — is the demand the fleet is actually
        facing. (Post-step stats systematically under-read: a fast
        engine may clear its whole queue within the step and report an
        idle instant while sustained traffic is breaching the SLO.)"""
        if self.autoscaler is not None:
            self._apply_scale(self.autoscaler.tick(
                [r.engine.stats() for r in self.replicas],
                len(self._running())))
        if self.disaggregated:
            self._tick_class_scalers()
        emitted: Dict[int, List[int]] = {}
        if self._pending_emit:
            # Tokens salvaged from a failed replica that step() never
            # streamed: surface them now so the caller's stream is
            # gapless across the failover.
            emitted.update(self._pending_emit)
            self._pending_emit = {}
        self._drain_retries()
        for rep in list(self.replicas):
            if rep.state in (UNHEALTHY, RETIRED):
                continue
            if not rep.engine.pending():
                self._sweep_finished(rep)
                # No step ran: streaks can't accumulate on idleness,
                # and an idle SUSPECT replica (routed around, so it
                # can never earn good steps) recovers on clean sweeps.
                rep.slow_streak = 0
                rep.silent_streak = 0
                self._note_good(rep)
                continue
            steps_before = getattr(rep.engine, "steps_total", 0)
            t0 = self._clock()
            try:
                em = rep.engine.step()
            except Exception as exc:   # noqa: BLE001 — any step error
                #                        is a replica health event
                self._on_step_error(rep, exc)
                continue
            dt = self._clock() - t0
            for rid, toks in em.items():
                fid = rep.rid_to_fid.get(rid)
                if fid is not None and toks:
                    emitted.setdefault(fid, []).extend(toks)
                    meta = self._requests.get(fid)
                    if meta is not None:
                        if meta.emitted == 0 and \
                                meta.submit_t is not None:
                            # Fleet-side TTFT: submit -> first token,
                            # SPANNING the handoff (the number a user
                            # feels; prefill engines never emit, so no
                            # engine window covers it).
                            self._ttft_agg.add(
                                self._clock() - meta.submit_t)
                        meta.emitted += len(toks)
            self._sweep_finished(rep)
            progressed = getattr(rep.engine, "steps_total",
                                 steps_before + 1) != steps_before
            self._health_after_step(rep, dt, progressed)
        if self.disaggregated:
            self._process_handoffs()
        self._retire_drained()
        return emitted

    def pending(self) -> bool:
        return bool(self._retry) or bool(self._handoff_parked) or any(
            r.engine.pending() for r in self.replicas
            if r.state != RETIRED)

    def run(self) -> Dict[int, List[int]]:
        """Drain every replica; returns {fleet_id: tokens} for every
        finished request and pops them (like DecodeEngine.run). If any
        request was LOST — its replica died and retries ran out, or no
        replica remained to recover onto — raises `RetriesExhausted`
        (or `ReplicaUnavailable` when no retry budget was even
        consumed) carrying the per-request errors in ``.failed`` and
        every successful result in ``.partial``, instead of hanging on
        tokens that will never arrive."""
        while self.pending():
            self.step()
        for rep in list(self.replicas):
            self._sweep_finished(rep)
        self._retire_drained()
        results: Dict[int, List[int]] = {}
        errors: Dict[int, FleetError] = {}
        for fid in list(self.finished):
            if fid in self.failed:
                self.finished.discard(fid)
                self.failed_ids.discard(fid)
                errors[fid] = self.failed.pop(fid)
            else:
                results[fid] = self.pop_result(fid)
        if errors:
            kind = (RetriesExhausted
                    if any(isinstance(e, RetriesExhausted)
                           for e in errors.values())
                    else ReplicaUnavailable)
            err = kind(
                f"{len(errors)} request(s) lost to replica failure: "
                f"{sorted(errors)}", failed=errors, partial=results) \
                if kind is RetriesExhausted else kind(
                f"{len(errors)} request(s) lost to replica failure: "
                f"{sorted(errors)}")
            if kind is ReplicaUnavailable:
                err.failed = errors          # same introspection shape
                err.partial = results
            raise err
        return results

    def pop_result(self, fid: int) -> List[int]:
        """Tokens of a FINISHED fleet request (empty for a shed one —
        check `shed_ids` before popping, same contract as the engine).
        For a request whose replica died with retries exhausted,
        raises its typed `RetriesExhausted` / `ReplicaUnavailable`
        (check `failed_ids` first to branch without try/except)."""
        if fid in self.failed:
            self.finished.discard(fid)
            self.failed_ids.discard(fid)
            raise self.failed.pop(fid)
        if fid not in self.finished:
            raise KeyError(f"fleet request {fid} unknown or "
                           f"not finished")
        self.finished.discard(fid)
        self.shed_ids.discard(fid)
        return self._done.pop(fid)

    # -- health state machine + failover -----------------------------------

    def _note_good(self, rep: _Replica) -> None:
        rep.good_streak += 1
        if rep.state == SUSPECT and \
                rep.good_streak >= self.health.recover_after:
            rep.state = RUNNING
            if self.trace.enabled:
                self.trace.instant("replica_recovered", lane="events",
                                   args={"replica": rep.name})

    def _suspect(self, rep: _Replica, why: str) -> None:
        """Put a replica on probation (RUNNING -> SUSPECT): the router
        skips it, step() keeps watching it. Entering SUSPECT counts
        toward the circuit breaker — `breaker_trips` entries within
        `breaker_window_s` open it for `breaker_cooldown_s`, so a
        flapping replica stops taking traffic BEFORE its next failure.
        DRAINING replicas stay DRAINING (already unrouted)."""
        rep.good_streak = 0
        if rep.state != RUNNING:
            return
        rep.state = SUSPECT
        if self.trace.enabled:
            self.trace.instant("replica_suspect", lane="events",
                               args={"replica": rep.name, "why": why})
        now = self._clock()
        cfg = self.health
        rep.suspect_events.append(now)
        rep.suspect_events = [
            t for t in rep.suspect_events
            if now - t <= cfg.breaker_window_s]
        if len(rep.suspect_events) >= cfg.breaker_trips:
            rep.breaker_open_until = now + cfg.breaker_cooldown_s
            rep.breaker_trips += 1
            rep.suspect_events.clear()
            if self.trace.enabled:
                self.trace.instant(
                    "breaker_open", lane="events",
                    args={"replica": rep.name,
                          "until": rep.breaker_open_until})

    def _on_step_error(self, rep: _Replica, exc: Exception) -> None:
        rep.failures += 1
        if self.trace.enabled:
            self.trace.instant(
                "replica_step_error", lane="events",
                args={"replica": rep.name, "failures": rep.failures,
                      "error": f"{type(exc).__name__}: {exc}"})
        if rep.failures >= self.health.max_step_failures:
            self._fail_replica(rep, exc)
        else:
            self._suspect(rep, "step_error")

    def _health_after_step(self, rep: _Replica, dt: float,
                           progressed: bool) -> None:
        """Classify one completed (non-raising) step: watchdog timeout,
        silent (no engine progress while work is pending), slow, or
        good — and advance the replica's health state accordingly."""
        cfg = self.health
        if cfg.step_deadline_s is not None and \
                dt >= cfg.step_deadline_s:
            rep.timeouts += 1
            if self.trace.enabled:
                self.trace.instant(
                    "replica_watchdog_timeout", lane="events",
                    args={"replica": rep.name, "step_s": dt,
                          "timeouts": rep.timeouts})
            if rep.timeouts >= cfg.unhealthy_after_timeouts:
                self._fail_replica(rep, FleetError(
                    f"replica {rep.name}: {rep.timeouts} watchdog "
                    f"timeouts (step >= {cfg.step_deadline_s}s)"))
                return
            self._suspect(rep, "watchdog_timeout")
            return
        if not progressed:
            rep.silent_streak += 1
            if rep.silent_streak >= cfg.unhealthy_after_silent:
                self._fail_replica(rep, FleetError(
                    f"replica {rep.name}: silent for "
                    f"{rep.silent_streak} steps (no engine progress "
                    "with work pending)"))
                return
            if rep.silent_streak >= cfg.suspect_after_silent:
                self._suspect(rep, "silent")
            return
        if cfg.slow_step_s is not None and dt >= cfg.slow_step_s:
            rep.silent_streak = 0
            rep.slow_streak += 1
            if rep.slow_streak >= cfg.suspect_after_slow:
                self._suspect(rep, "slow_steps")
            return
        rep.slow_streak = 0
        rep.silent_streak = 0
        self._note_good(rep)

    def _fail_replica(self, rep: _Replica, cause: Exception) -> None:
        """Condemn a replica and fail its work over: harvest results
        it already finished, reconstruct every in-flight and queued
        request from host bookkeeping (prompt + emitted tokens + the
        pinned key), halt the engine (pipeline discarded, paged-KV
        refcounts released), retire the replica, schedule the
        reconstructed requests for resubmission with backoff, and —
        by default — add a replacement replica."""
        if rep.state == RETIRED:
            return
        rep.state = UNHEALTHY
        self.replicas_failed += 1
        self._count("replica_failures", 1)
        if self.trace.enabled:
            self.trace.instant(
                "replica_failed", lane="events",
                args={"replica": rep.name,
                      "error": f"{type(cause).__name__}: {cause}",
                      "inflight": len(rep.rid_to_fid)})
        # Results the replica finished before dying are ordinary
        # completions: sweep them first (host-side state survives any
        # step() exception — nothing below touches the device).
        try:
            self._sweep_finished(rep)
        except Exception:
            pass
        salvaged: List[Tuple[int, List[int]]] = []
        results = getattr(rep.engine, "results", {})
        for rid, fid in list(rep.rid_to_fid.items()):
            req = results.get(rid)
            toks = list(req.tokens) if req is not None else []
            meta = self._requests.get(fid)
            if meta is not None:
                # Tokens already streamed to the caller must all be in
                # the salvage (req.tokens accrues at drain, BEFORE the
                # fleet ever sees an emission) — counted, not trusted.
                self.tokens_lost_to_failure += max(
                    0, meta.emitted - len(toks))
                gap = toks[meta.emitted:]
                if gap:
                    self._pending_emit.setdefault(fid, []).extend(gap)
                    meta.emitted = len(toks)
            salvaged.append((fid, toks))
            self._placement.pop(fid, None)
        rep.rid_to_fid.clear()
        try:
            rep.engine.halt()
        except Exception:
            pass               # the engine may be arbitrarily broken
        self._harvest_trace(rep)
        rep.state = RETIRED
        if rep in self.replicas:
            self.replicas.remove(rep)
        self.replicas_removed += 1
        for fid, toks in salvaged:
            self._schedule_retry(fid, toks, cause)
        if self.health.replace_failed:
            # Replacement inherits the dead replica's class: losing a
            # decode replica must not quietly shrink decode capacity
            # into a colocated pool.
            name = self.add_replica(replica_class=rep.replica_class)
            if self.trace.enabled:
                self.trace.instant(
                    "replica_replaced", lane="events",
                    args={"failed": rep.name, "replacement": name})

    def _schedule_retry(self, fid: int, toks: List[int],
                        cause: Exception) -> None:
        meta = self._requests.get(fid)
        if meta is None:
            return
        if len(toks) >= meta.max_new_tokens:
            # The salvage IS the complete answer (the replica died
            # between finishing and being swept): finish directly.
            self._done[fid] = toks
            self.finished.add(fid)
            self._requests.pop(fid, None)
            return
        n = meta.attempts           # next submission = retry #n
        if n > self.health.max_retries:
            self._fail_request(fid, RetriesExhausted(
                f"fleet request {fid}: replica failed "
                f"({type(cause).__name__}: {cause}) and all "
                f"{self.health.max_retries} retries are spent"))
            return
        meta.tokens = toks
        meta.recovering = True
        delay = self._backoff_delay(meta, n)
        heapq.heappush(self._retry,
                       (self._clock() + delay, self._retry_seq, fid))
        self._retry_seq += 1
        if self.trace.enabled:
            self.trace.instant(
                "failover_scheduled", fid,
                args={"retry": n, "delay_s": round(delay, 4),
                      "resume_tokens": len(toks)})

    def _backoff_delay(self, meta: _FleetReq, n: int) -> float:
        """Retry n's wait. The first failover is immediate (the
        failure is already detected — waiting buys nothing); later
        retries back off exponentially, stretched by up to 50%
        deterministic jitter mixed from the request's own key — so a
        herd of failed-over requests de-synchronizes the same way
        every run (reproducible chaos)."""
        if n <= 1:
            return 0.0
        cfg = self.health
        base = min(cfg.backoff_max_s,
                   cfg.backoff_base_s * cfg.backoff_factor ** (n - 2))
        seed0 = int(meta.rng[0]) if meta.rng is not None else meta.fid
        frac = (((seed0 & 0xFFFFFFFF) * 0x9E3779B9
                 + n * 0x85EBCA6B) & 0xFFFF) / 65535.0
        return base * (1.0 + 0.5 * frac)

    def _fail_request(self, fid: int, err: FleetError) -> None:
        meta = self._requests.pop(fid, None)
        if meta is not None and meta.tokens:
            err.partial = {fid: list(meta.tokens)}
        self.failed[fid] = err
        self.failed_ids.add(fid)
        self.finished.add(fid)    # wakes pollers; pop_result raises
        self.requests_failed += 1

    def _drain_retries(self) -> None:
        """Resubmit every retry whose backoff has lapsed. Retries
        route over ALL RUNNING replicas — the circuit breaker is
        ignored here (a recovery must land somewhere; the breaker
        shapes new-traffic placement, not correctness). With zero
        RUNNING replicas: wait while any survivor could still recover
        or drain out (SUSPECT/DRAINING), else fail the request with
        `ReplicaUnavailable` — never hang `run()`."""
        now = self._clock()
        while self._retry and self._retry[0][0] <= now:
            ready, seq, fid = heapq.heappop(self._retry)
            meta = self._requests.get(fid)
            if meta is None:
                continue
            running = self._running()
            if not running:
                if any(r.state in (SUSPECT, DRAINING)
                       for r in self.replicas):
                    # A survivor may yet recover (or a drain finish):
                    # park the retry and re-check next step.
                    heapq.heappush(self._retry, (ready, seq, fid))
                    return
                self._fail_request(fid, ReplicaUnavailable(
                    f"fleet request {fid}: no RUNNING replica left to "
                    "recover onto (replacement disabled or exhausted)"))
                continue
            if self.disaggregated:
                # Recoveries re-enter through the prefill class: the
                # recompute replay IS a prefill, and the finished
                # frontier rides the ordinary handoff to decode. Only
                # when no prefill replica runs does a recovery land on
                # decode (a decode engine is a full colocated engine).
                pre = [r for r in running
                       if r.replica_class == "prefill"]
                running = pre or running
            self._resubmit(meta, running, ready, seq)

    def _choose(self, cands: List[_Replica], prompt: List[int],
                adapter_id: Optional[str]) -> _Replica:
        """Route, passing adapter_id only to routers that advertise
        adapter affinity (back-compat with custom routers)."""
        if adapter_id is not None and \
                getattr(self.router, "supports_adapter_affinity",
                        False):
            return self.router.choose(cands, prompt,
                                      adapter_id=adapter_id)
        return self.router.choose(cands, prompt)

    def _resubmit(self, meta: _FleetReq, cands: List[_Replica],
                  ready: float, seq: int) -> None:
        rep = self._choose(cands, meta.prompt, meta.adapter_id)
        ad_kw = ({} if meta.adapter_id is None
                 else {"adapter_id": meta.adapter_id})
        try:
            rid = rep.engine.submit(
                meta.prompt, meta.max_new_tokens,
                priority=meta.priority, rng=meta.rng,
                greedy=meta.greedy,
                resume_tokens=meta.tokens or None,
                **ad_kw)
        except (EngineDraining, EngineOverloaded):
            # Raced a drain/overload on the chosen replica: park the
            # retry one backoff-base further out, attempt unconsumed.
            heapq.heappush(self._retry,
                           (self._clock() + self.health.backoff_base_s,
                            seq, meta.fid))
            return
        meta.attempts += 1
        meta.recovering = False
        rep.rid_to_fid[rid] = meta.fid
        self._placement[meta.fid] = (rep, rid)
        rep.routed += 1
        self.retries += 1
        self._count("retries", 1)
        if self.trace.enabled:
            self.trace.instant(
                "failover", meta.fid,
                args={"replica": rep.name, "rid": rid,
                      "attempt": meta.attempts,
                      "resume_tokens": len(meta.tokens)})
        self._sweep_finished(rep)

    def _harvest_trace(self, rep: _Replica) -> None:
        """Keep a leaving replica's spans so dump_trace() still tells
        the whole story — bounded like the rings it collects from
        (oldest spans trimmed first)."""
        etr = getattr(rep.engine, "trace", None)
        if etr is None or not etr.enabled:
            return
        self._retired_trace.extend(etr.chrome_events(pid=rep.name))
        cap = 4 * getattr(etr, "capacity", 16384)
        if len(self._retired_trace) > cap:
            self._retired_trace = self._retired_trace[-cap:]

    # -- internals ---------------------------------------------------------

    def _sweep_finished(self, rep: _Replica) -> None:
        """Move the replica's finished engine requests into the fleet's
        finished set (popping them from the engine, so a drained
        replica ends truly empty)."""
        for rid in list(rep.engine.finished):
            fid = rep.rid_to_fid.pop(rid, None)
            if fid is None:
                continue
            shed = rid in rep.engine.shed_ids
            toks = rep.engine.pop_result(rid)
            meta = self._requests.pop(fid, None)
            if meta is not None and meta.attempts > 1:
                self.requests_recovered += 1
                self._count("requests_recovered", 1)
            self._done[fid] = toks
            self.finished.add(fid)
            self._placement.pop(fid, None)
            if shed:
                self.shed_ids.add(fid)
                self.requests_shed += 1

    def _retire_drained(self) -> None:
        """Remove DRAINING replicas that have fully flushed. The
        zero-loss invariant is checked here, not trusted: a replica
        may only leave with no queued work, no live rows, and no
        unswept results."""
        for rep in list(self.replicas):
            if rep.state != DRAINING:
                continue
            if rep.engine.pending() or rep.engine.finished or \
                    rep.rid_to_fid:
                continue    # still owes work or unswept results: kept
            self._harvest_trace(rep)
            self.replicas.remove(rep)
            self.replicas_removed += 1

    def _apply_scale(self, decision: int,
                     replica_class: Optional[str] = None) -> None:
        if decision > 0:
            self.add_replica(replica_class=replica_class)
        elif decision < 0:
            pool = self._running()
            if replica_class is not None:
                pool = [r for r in pool
                        if r.replica_class == replica_class]
            if len(pool) <= 1:
                return    # never drain the last live replica
            #             # (of its class, in a disaggregated fleet)
            # Drain the replica with the least outstanding work — the
            # cheapest flush, so capacity leaves the pool fastest.
            victim = min(
                pool,
                key=lambda r: (r.engine.pending_prefill_tokens()
                               + sum(x is not None
                                     for x in r.engine.row_req)))
            self.drain_replica(victim.name)

    # -- disaggregated prefill/decode handoff ------------------------------

    def _class_replicas(self, klass: str) -> List[_Replica]:
        return [r for r in self.replicas
                if r.replica_class == klass and r.state != RETIRED]

    def _tick_class_scalers(self) -> None:
        """One scale decision PER CLASS: the prefill scaler gates on
        TTFT p95 (admission latency — add prefill replicas when the
        first token lags), the decode scaler on TPOT p95 (steady-state
        decode latency — add decode replicas when streams stutter).
        Which signal each class uses is the config's choice
        (ttft_p95_slo_s / tpot_p95_slo_s); the split is what makes the
        two SLOs independently tunable."""
        for klass, scaler in (("prefill", self._prefill_scaler),
                              ("decode", self._decode_scaler)):
            if scaler is None:
                continue
            reps = self._class_replicas(klass)
            stats_list = [r.engine.stats() for r in reps]
            if klass == "prefill":
                # Prefill engines never emit tokens, so their engine
                # TTFT windows are empty forever: inject the fleet's
                # own submit->first-token tail (measured ACROSS the
                # handoff) so the scaler sees what users feel.
                t = self._ttft_agg.percentile(95.0)
                for s in stats_list:
                    s["ttft_s_p95"] = t
            n_running = sum(1 for r in reps if r.state == RUNNING)
            self._apply_scale(scaler.tick(stats_list, n_running),
                              replica_class=klass)

    def _process_handoffs(self) -> None:
        """Drain the handoff pipeline once per fleet step: re-place
        parked exports first (a decode replica may have appeared),
        then export every prefill-complete request and import it on a
        decode replica. DRAINING prefill replicas still export — the
        handoff IS their flush path; only condemned replicas are
        skipped (their work goes through ordinary failover)."""
        if self._handoff_parked:
            parked, self._handoff_parked = self._handoff_parked, []
            for fid in parked:
                self._place_handoff(fid)
        for rep in list(self.replicas):
            if rep.replica_class != "prefill" or \
                    rep.state in (UNHEALTHY, RETIRED):
                continue
            eng = rep.engine
            for rid in list(eng.handoff_ready()):
                fid = rep.rid_to_fid.get(rid)
                meta = self._requests.get(fid) \
                    if fid is not None else None
                if meta is None:
                    continue
                h = eng.export_request(rid)
                rep.rid_to_fid.pop(rid, None)
                self._placement.pop(fid, None)
                meta.handoff = h
                self.handoffs += 1
                self._count("handoffs", 1)
                if self.trace.enabled:
                    self.trace.instant(
                        "handoff", fid,
                        args={"from": rep.name,
                              "prompt_tokens": len(meta.prompt),
                              "resume_tokens": len(h["tokens"])})
                self._place_handoff(fid)

    def _place_handoff(self, fid: int) -> None:
        """Import one exported request on a decode-class replica. No
        importable replica right now -> the payload parks on the host
        (the KV lives in numpy arrays inside `meta.handoff`, safe
        across any replica's death) and is retried every step; the
        request only fails when the decode class is GONE."""
        meta = self._requests.get(fid)
        if meta is None or meta.handoff is None:
            return
        cands = [r for r in self._routable()
                 if r.replica_class == "decode"]
        if not cands:
            if any(r.replica_class == "decode"
                   and r.state in (RUNNING, SUSPECT, DRAINING)
                   for r in self.replicas):
                self._handoff_parked.append(fid)
                return
            self._fail_request(fid, ReplicaUnavailable(
                f"fleet request {fid}: no decode-class replica left "
                "to import the handoff onto"))
            return
        rep = self._choose(cands, meta.prompt, meta.adapter_id)
        try:
            rid = rep.engine.import_request(meta.handoff)
        except (EngineDraining, EngineOverloaded):
            self._handoff_parked.append(fid)
            return
        meta.handoff = None
        rep.rid_to_fid[rid] = fid
        self._placement[fid] = (rep, rid)
        rep.routed += 1
        if self.trace.enabled:
            self.trace.instant(
                "handoff_placed", fid,
                args={"replica": rep.name, "rid": rid})
        self._sweep_finished(rep)

    def handoff_requests(self) -> List[Dict[str, object]]:
        """One dict per request whose export is parked between replica
        classes — the state API's fleet-side `status="handoff"`
        source. Host-only."""
        out = []
        for fid in self._handoff_parked:
            meta = self._requests.get(fid)
            if meta is None or meta.handoff is None:
                continue
            out.append({
                "req_id": fid,
                "prompt_tokens": len(meta.prompt),
                "max_new_tokens": meta.max_new_tokens,
                "tokens_out": len(meta.handoff["tokens"]),
                "priority": meta.priority,
                "attempts": meta.attempts,
            })
        return out

    def adapter_miss_rate(self) -> float:
        """Fleet-wide adapter HBM-residency miss rate over the live
        pool counters (1 - hits/lookups; 0.0 before any lookup).
        Exposed as the `llm_fleet_adapter_miss_rate` gauge and usable
        directly as an autoscaling `custom_metric_source` — a decode
        class thrashing adapter slots wants MORE replicas (each added
        replica's pool spreads the working set), which plain occupancy
        and latency signals under-read."""
        lk = hit = 0.0
        for r in self.replicas:
            pool = getattr(r.engine, "adapter_pool", None)
            if pool is None:
                continue
            s = pool.stats()
            lk += s.get("adapter_lookups", 0.0)
            hit += s.get("adapter_hits", 0.0)
        return (1.0 - hit / lk) if lk else 0.0

    # -- telemetry ---------------------------------------------------------

    def recovering_requests(self) -> List[Dict[str, object]]:
        """One dict per request currently parked in the retry queue —
        the state API's `status="recovering"` source. Host-only."""
        out = []
        for ready, _seq, fid in sorted(self._retry):
            meta = self._requests.get(fid)
            if meta is None or not meta.recovering:
                continue
            out.append({
                "req_id": fid,
                "prompt_tokens": len(meta.prompt),
                "max_new_tokens": meta.max_new_tokens,
                "tokens_out": len(meta.tokens),
                "priority": meta.priority,
                "attempts": meta.attempts,
                "retry_ready_at": ready,
            })
        return out

    def replica_health(self) -> Dict[str, str]:
        """{replica name -> health/lifecycle state} for every pooled
        replica (the state API / status CLI health column)."""
        return {r.name: r.state for r in self.replicas}

    def dump_trace(self, path: Optional[str] = None) -> List[dict]:
        """One chrome://tracing JSON for the whole fleet: the fleet
        tracer's `route` spans (pid = fleet id, tid = fleet request
        lane) merged with every replica engine's lifecycle spans
        (pid = replica name, tid = replica-local request lane) plus
        spans harvested from replicas already drained or failed out of
        the pool. A route span's args carry the chosen replica and its
        replica-local rid, which is the join key between the two pid
        groups. Writes JSON to `path` when given; returns the event
        list (empty when nothing traced)."""
        events = list(self._retired_trace)
        for rep in self.replicas:
            etr = getattr(rep.engine, "trace", None)
            if etr is not None and etr.enabled:
                events.extend(etr.chrome_events(pid=rep.name))
        events.extend(self.trace.chrome_events(pid=self.fleet_id))
        events.sort(key=lambda e: e["ts"])
        if path:
            with open(path, "w") as f:
                json.dump(events, f)
        return events

    def stats(self) -> Dict[str, float]:
        """Flat fleet snapshot (gauge-friendly, like engine.stats()).
        Every field is also published as an `llm_fleet_<field>` gauge
        tagged with the fleet id through util.metrics."""
        running = self._running()
        draining = [r for r in self.replicas if r.state == DRAINING]
        suspect = [r for r in self.replicas if r.state == SUSPECT]
        now = self._clock()
        per = [r.engine.stats() for r in self.replicas]
        out: Dict[str, float] = {
            "replicas": float(len(self.replicas)),
            "replicas_running": float(len(running)),
            "replicas_draining": float(len(draining)),
            "replicas_suspect": float(len(suspect)),
            "replicas_removed": float(self.replicas_removed),
            "replicas_failed": float(self.replicas_failed),
            "breakers_open": float(sum(
                1 for r in self.replicas
                if now < r.breaker_open_until)),
            "requests_routed": float(self.requests_routed),
            "requests_shed": float(self.requests_shed),
            "requests_failed": float(self.requests_failed),
            "requests_recovered": float(self.requests_recovered),
            "retries": float(self.retries),
            "retry_queue_depth": float(len(self._retry)),
            "tokens_lost_to_drain": float(self.tokens_lost_to_drain),
            "tokens_lost_to_failure": float(
                self.tokens_lost_to_failure),
            "queue_depth": sum(s.get("queue_depth", 0.0) for s in per),
            "pending_prefill_tokens": sum(
                s.get("pending_prefill_tokens", 0.0) for s in per),
            "slot_occupancy_mean": (
                sum(s.get("slot_occupancy", 0.0) for s in per)
                / len(per)) if per else 0.0,
            "ttft_s_p95_max": max(
                (s.get("ttft_s_p95", 0.0) for s in per), default=0.0),
            "tpot_s_p95_max": max(
                (s.get("tpot_s_p95", 0.0) for s in per), default=0.0),
            # Tensor-parallel plane: replicas built by engine_factory
            # may themselves be tp-sharded over an ICI mesh — the
            # fleet then scales in units of whole meshes. Replicas are
            # homogeneous in practice, so max == the fleet's tp; the
            # per-replica view flows through each engine's own
            # llm_engine_* series (and serve_llm_engine_* when a
            # replica republishes via report_engine_stats).
            "tp_degree_max": max(
                (s.get("tp_degree", 1.0) for s in per), default=1.0),
            "host_transfer_bytes": sum(
                s.get("host_transfer_bytes", 0.0) for s in per),
            # Paged-KV plane: zero-copy sharing / preempt-and-swap
            # rollup (all-zero when replicas run the dense cache).
            "kv_blocks_shared": sum(
                s.get("kv_blocks_shared", 0.0) for s in per),
            "kv_block_cows": sum(
                s.get("kv_block_cows", 0.0) for s in per),
            "preemptions": sum(
                s.get("preemptions", 0.0) for s in per),
            "swap_in_bytes": sum(
                s.get("swap_in_bytes", 0.0) for s in per),
            "swap_out_bytes": sum(
                s.get("swap_out_bytes", 0.0) for s in per),
            "kv_free_blocks": sum(
                s.get("kv_free_blocks", 0.0) for s in per),
            "kv_used_fraction_mean": (
                sum(s.get("kv_used_fraction", 0.0) for s in per)
                / len(per)) if per else 0.0,
            # Quantized-KV plane: replicas are homogeneous in
            # practice, so the mean bytes/token IS the fleet's KV cost
            # per cached token; quant_replicas counts how many run a
            # low-bit pool (0 = dense fleet).
            "kv_quant_replicas": sum(
                s.get("kv_quant_enabled", 0.0) for s in per),
            "kv_bytes_per_token_mean": (
                sum(s.get("kv_bytes_per_token", 0.0) for s in per)
                / len(per)) if per else 0.0,
        }
        # Speculative plane (all-zero when no replica carries a draft
        # model). Rates are re-derived from the summed raw counters —
        # a proposal-weighted mean — so a busy replica's acceptance
        # dominates an idle one's instead of averaging per-replica
        # ratios.
        sp_prop = sum(s.get("spec_proposed", 0.0) for s in per)
        sp_acc = sum(s.get("spec_accepted", 0.0) for s in per)
        sp_rounds = sum(s.get("spec_rounds", 0.0) for s in per)
        out["spec_replicas"] = sum(
            s.get("spec_enabled", 0.0) for s in per)
        out["spec_dispatches"] = sum(
            s.get("spec_dispatches", 0.0) for s in per)
        out["spec_rounds"] = sp_rounds
        out["spec_proposed"] = sp_prop
        out["spec_accepted"] = sp_acc
        out["spec_acceptance_rate"] = (
            sp_acc / sp_prop if sp_prop else 0.0)
        out["spec_window_effective"] = (
            sp_prop / sp_rounds if sp_rounds else 0.0)
        out["spec_draft_tokens_wasted"] = sum(
            s.get("spec_draft_tokens_wasted", 0.0) for s in per)
        # Multi-LoRA plane (all-zero when no replica carries an
        # adapter pool). Hit rate re-derived from summed counters, like
        # the spec plane.
        ad_lk = sum(s.get("adapter_lookups", 0.0) for s in per)
        ad_hit = sum(s.get("adapter_hits", 0.0) for s in per)
        out["adapter_replicas"] = sum(
            s.get("adapter_enabled", 0.0) for s in per)
        out["adapters_registered"] = float(len(self._adapters))
        out["adapter_lookups"] = ad_lk
        out["adapter_hits"] = ad_hit
        out["adapter_hit_rate"] = ad_hit / ad_lk if ad_lk else 0.0
        out["adapter_prefetches"] = sum(
            s.get("adapter_prefetches", 0.0) for s in per)
        out["adapter_evictions"] = sum(
            s.get("adapter_evictions", 0.0) for s in per)
        out["adapter_prefetch_deferrals"] = sum(
            s.get("adapter_prefetch_deferrals", 0.0) for s in per)
        # Disaggregated prefill/decode plane (all-zero for colocated
        # fleets). `handoffs` counts fleet-level export->import moves;
        # the per-engine out/in counters and byte totals roll up so a
        # leak (out != in + parked) is visible from one snapshot.
        out["disaggregated"] = 1.0 if self.disaggregated else 0.0
        out["replicas_prefill"] = float(
            len(self._class_replicas("prefill")))
        out["replicas_decode"] = float(
            len(self._class_replicas("decode")))
        out["handoffs"] = float(self.handoffs)
        out["handoff_parked"] = float(len(self._handoff_parked))
        out["handoffs_out"] = sum(
            s.get("handoffs_out", 0.0) for s in per)
        out["handoffs_in"] = sum(
            s.get("handoffs_in", 0.0) for s in per)
        out["handoff_out_bytes"] = sum(
            s.get("handoff_out_bytes", 0.0) for s in per)
        out["handoff_in_bytes"] = sum(
            s.get("handoff_in_bytes", 0.0) for s in per)
        out["adapter_miss_rate"] = self.adapter_miss_rate()
        out["ttft_s_p95_fleet"] = self._ttft_agg.percentile(95.0)
        if self._prefill_scaler is not None:
            out["prefill_scale_ups"] = float(
                self._prefill_scaler.scale_ups)
            out["prefill_scale_downs"] = float(
                self._prefill_scaler.scale_downs)
        if self._decode_scaler is not None:
            out["decode_scale_ups"] = float(
                self._decode_scaler.scale_ups)
            out["decode_scale_downs"] = float(
                self._decode_scaler.scale_downs)
        out["router_affinity_wins"] = float(
            getattr(self.router, "affinity_wins", 0))
        out["router_adapter_wins"] = float(
            getattr(self.router, "adapter_wins", 0))
        out["router_pow2_wins"] = float(
            getattr(self.router, "pow2_wins", 0))
        if self.autoscaler is not None:
            out["scale_ups"] = float(self.autoscaler.scale_ups)
            out["scale_downs"] = float(self.autoscaler.scale_downs)
        self._publish(out)
        return out

    def _publish(self, stats: Dict[str, float]) -> None:
        for field, value in stats.items():
            name = f"llm_fleet_{field}"
            g = _fleet_gauges.get(name)
            if g is None:
                g = _fleet_gauges[name] = Gauge(
                    name, f"LLMFleet stats field {field!r}",
                    tag_keys=("fleet",))
            g.set(float(value), tags={"fleet": self.fleet_id})

    def _count(self, event: str, value: float) -> None:
        """Monotonic fault-plane counters (`llm_fleet_<event>_total`),
        incremented at event time — unlike the gauges, which republish
        whole snapshots on stats()."""
        name = f"llm_fleet_{event}_total"
        c = _fleet_counters.get(name)
        if c is None:
            c = _fleet_counters[name] = Counter(
                name, f"LLMFleet fault-tolerance event {event!r}",
                tag_keys=("fleet",))
        c.inc(float(value), tags={"fleet": self.fleet_id})
