"""ray_tpu.models — flagship JAX model families.

The reference ships no model code (models are torch user code fed to
TorchTrainer); here model families are first-class so the train/serve/rllib
libraries and benchmarks have TPU-native flagships. Llama-2 is the
north-star benchmark model (BASELINE.md: ≥40% MFU on v5e).
"""

from ray_tpu.models.llama import (
    LlamaConfig,
    llama_init,
    llama_forward,
    llama_hidden,
    llama_loss,
    llama_param_specs,
)
from ray_tpu.models.mlp import MLPConfig, mlp_init, mlp_forward
from ray_tpu.models.vit import (
    ViTConfig,
    vit_init,
    vit_forward,
    vit_loss,
    vit_param_specs,
)
from ray_tpu.models.moe import (
    MoeConfig,
    moe_init,
    moe_forward,
    moe_loss,
    moe_param_specs,
)
from ray_tpu.models.lora import (
    LoraConfig,
    lora_init,
    lora_merge,
    lora_num_params,
    lora_param_specs,
    lora_stack_specs,
    make_lora_train_step,
)
from ray_tpu.models.adapter_pool import AdapterPool
from ray_tpu.models.t5 import (
    T5Config,
    t5_init,
    t5_forward,
    t5_encode,
    t5_decode,
    t5_loss,
    t5_param_specs,
)
from ray_tpu.models.engine import DecodeEngine
from ray_tpu.models.engine_metrics import EngineMetrics
from ray_tpu.models.engine_trace import EngineTracer, NullEngineTracer
from ray_tpu.models.fault_injection import FaultInjector, InjectedFault
from ray_tpu.models.fleet import (
    EngineStatsAutoscaler,
    FleetAutoscalingConfig,
    FleetError,
    FleetHealthConfig,
    FleetRouter,
    LLMFleet,
    PowerOfTwoAffinityRouter,
    ReplicaUnavailable,
    RetriesExhausted,
    RoundRobinRouter,
)
from ray_tpu.models.prefix_cache import PrefixCacheIndex
from ray_tpu.models.scheduler import (
    AdapterAffinityPolicy,
    EngineDraining,
    EngineOverloaded,
    FIFOPolicy,
    PrefixAffinityPolicy,
    PriorityPolicy,
    SchedulerPolicy,
    SubmitTimeout,
)

__all__ = [
    "LlamaConfig",
    "llama_init",
    "llama_forward",
    "llama_hidden",
    "llama_loss",
    "llama_param_specs",
    "ViTConfig",
    "vit_init",
    "vit_forward",
    "vit_loss",
    "vit_param_specs",
    "MLPConfig",
    "mlp_init",
    "mlp_forward",
    "MoeConfig",
    "moe_init",
    "moe_forward",
    "moe_loss",
    "moe_param_specs",
    "LoraConfig",
    "lora_init",
    "lora_merge",
    "lora_num_params",
    "lora_param_specs",
    "lora_stack_specs",
    "make_lora_train_step",
    "AdapterPool",
    "AdapterAffinityPolicy",
    "T5Config",
    "t5_init",
    "t5_forward",
    "t5_encode",
    "t5_decode",
    "t5_loss",
    "t5_param_specs",
    "DecodeEngine",
    "EngineDraining",
    "EngineMetrics",
    "EngineOverloaded",
    "EngineTracer",
    "NullEngineTracer",
    "EngineStatsAutoscaler",
    "FaultInjector",
    "FIFOPolicy",
    "FleetAutoscalingConfig",
    "FleetError",
    "FleetHealthConfig",
    "FleetRouter",
    "InjectedFault",
    "LLMFleet",
    "PowerOfTwoAffinityRouter",
    "PrefixAffinityPolicy",
    "PrefixCacheIndex",
    "PriorityPolicy",
    "ReplicaUnavailable",
    "RetriesExhausted",
    "RoundRobinRouter",
    "SchedulerPolicy",
    "SubmitTimeout",
]
