"""Sharded train-step builder.

Produces the jitted SPMD training step the reference leaves to torch user
code (python/ray/train/torch/train_loop_utils.py:158 `prepare_model`): the
whole step — fwd, bwd, optimizer — is ONE compiled XLA program over the
mesh; XLA inserts all collectives (gradient reduce over dp/fsdp, weight
all-gathers for fsdp, tp reductions) from the sharding annotations.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.sharding import logical_to_mesh, LogicalAxisRules

Pytree = Any


def batch_sharding_fn(mesh: Mesh,
                      batch_logical: Tuple[Optional[str], ...],
                      rules: Optional[LogicalAxisRules] = None):
    """Rank-adaptive batch-leaf sharding: batch_logical is truncated /
    None-padded to each leaf's rank (labels are rank-1, tokens rank-2,
    images rank-4 — all shard their leading batch axis, trailing axes
    replicate unless batch_logical names them). Shared by every
    train-step builder (full fine-tune, LoRA)."""
    def shard_for(x: jax.Array) -> NamedSharding:
        logical = tuple(batch_logical[:x.ndim]) + \
            (None,) * max(0, x.ndim - len(batch_logical))
        return NamedSharding(mesh, logical_to_mesh(logical, rules))
    return shard_for


def make_sharded_train_step(
    loss_fn: Callable[[Pytree, Dict[str, jax.Array]], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    param_specs: Pytree,
    batch_logical: Tuple[Optional[str], ...] = ("batch", None),
    rules: Optional[LogicalAxisRules] = None,
    donate: bool = True,
):
    """Returns (init_fn, step_fn).

    init_fn(params) -> (sharded_params, sharded_opt_state): device_puts the
    param tree per `param_specs`; optimizer state inherits its params'
    sharding via GSPMD propagation through a jitted `optimizer.init`.

    step_fn(params, opt_state, batch) -> (params, opt_state, metrics).
    Shardings are inferred from the committed inputs; params/opt_state
    buffers are donated so the step is in-place in HBM.
    """
    param_shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_specs,
        is_leaf=lambda x: isinstance(x, P))
    _batch_sharding_for = batch_sharding_fn(mesh, batch_logical, rules)

    def init_fn(params):
        params = jax.tree_util.tree_map(
            jax.device_put, params, param_shardings)
        opt_state = jax.jit(optimizer.init)(params)
        return params, opt_state

    @functools.partial(jax.jit, donate_argnums=(0, 1) if donate else ())
    def step_fn(params, opt_state, batch):
        from ray_tpu.ops.attention import spmd_mesh_scope

        # Trace-time mesh announcement: kernel dispatch (Pallas flash
        # attention) picks shard_map-wrapped forms that GSPMD can't
        # auto-partition.
        with spmd_mesh_scope(mesh):
            batch = jax.tree_util.tree_map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, _batch_sharding_for(x)), batch)
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics = {"loss": loss, "grad_norm": optax.global_norm(grads)}
            return params, opt_state, metrics

    return init_fn, step_fn
