"""Refcounted allocator over the engine's paged KV block pool.

The paged DecodeEngine keeps EVERY request's K/V in fixed-size token
blocks of one device pool ``[L, NB, T, KV, D]`` and addresses them
through per-request block tables — the vLLM/PagedAttention memory
plane. This module is the pure-host ledger for that pool: which block
ids are free, and how many holders reference each allocated block.

Reference counting is what turns prefix-cache hits into zero-copy
SHARES: a warm admission increfs the matched blocks instead of copying
them (the PR-4 ``_prefix_copy_in`` device-to-device gather disappears),
the trie holds one reference of its own for every cached block, and a
block returns to the free list only when its LAST holder drops it —
so a shared block can never be recycled under a live reader (the
refcount-never-evicted property, tested). Everything here is host-side
integers: alloc/incref/decref cost zero device dispatches.

Block id 0 is RESERVED as the null/scratch block, same convention as
the prefix pool: unoccupied block-table entries point at it, padded
gather/scatter programs write garbage into it, and it is never handed
out by ``alloc``.
"""

from __future__ import annotations

from typing import List, Optional


class BlockPool:
    """Host ledger of a device block pool: free list + refcounts.

    ``alloc(n)`` hands out n block ids (each with refcount 1) or None
    if fewer than n are free — the caller decides whether to evict
    cold prefix-cache blocks or preempt a victim request. ``incref``
    adds a holder (a warm admission sharing a cached block, or the
    trie registering a row's freshly filled block); ``decref`` drops
    one, freeing the block when the count reaches zero. All O(1) per
    block, pure host state."""

    def __init__(self, n_blocks: int, *, label: str = "kv"):
        if n_blocks < 2:
            raise ValueError(
                "n_blocks must be >= 2 (block 0 is the reserved "
                "null/scratch block); raise kv_pool_bytes or shrink "
                "kv_block_tokens")
        self.n_blocks = n_blocks
        # Which plane this ledger backs — the speculative engine runs
        # TWO pools side by side (target "kv" + "draft_kv"), and the
        # label keeps their snapshots distinguishable in the state API.
        self.label = label
        # Stack of free ids, low ids on top (pop order is deterministic
        # so engine runs — and their compiled gather shapes — replay
        # identically across processes).
        self._free: List[int] = list(range(n_blocks - 1, 0, -1))
        self._refs = [0] * n_blocks

    # -- introspection -----------------------------------------------------

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_total(self) -> int:
        return self.n_blocks - 1          # scratch block 0 excluded

    @property
    def blocks_in_use(self) -> int:
        return self.blocks_total - len(self._free)

    def ref(self, bid: int) -> int:
        """Current holder count of a block (0 = free)."""
        return self._refs[bid]

    def snapshot(self) -> dict:
        """Plain-dict ledger view for the state API / status CLI:
        totals plus how sharing is distributed (blocks with >1 holder
        are the zero-copy prefix shares; `refs_max` is the hottest
        block's holder count). Pure host arithmetic over the refcount
        list — no allocation state is touched."""
        shared = sum(1 for r in self._refs if r > 1)
        return {
            "label": self.label,
            "blocks_total": self.blocks_total,
            "blocks_in_use": self.blocks_in_use,
            "free_blocks": len(self._free),
            "blocks_shared": shared,
            "refs_max": max(self._refs) if self._refs else 0,
        }

    # -- alloc / share / release -------------------------------------------

    def alloc(self, n: int) -> Optional[List[int]]:
        """Take n blocks off the free list, each with refcount 1.
        All-or-nothing: returns None (and takes nothing) when fewer
        than n are free, so a caller never holds a partial chain."""
        if n < 0:
            raise ValueError("alloc(n) needs n >= 0")
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        for bid in ids:
            self._refs[bid] = 1
        return ids

    def incref(self, ids) -> None:
        """Add one holder to each block (shared admission / trie
        registration). Blocks must be allocated — sharing a free block
        is a ledger bug, not a recoverable condition."""
        for bid in ids:
            if self._refs[bid] <= 0:
                raise ValueError(
                    f"incref on free block {bid}: sharing requires an "
                    "existing holder")
            self._refs[bid] += 1

    def decref(self, ids) -> List[int]:
        """Drop one holder from each block; returns the ids FREED by
        this call (refcount hit zero), in drop order."""
        freed: List[int] = []
        for bid in ids:
            r = self._refs[bid]
            if r <= 0:
                raise ValueError(f"decref on free block {bid}")
            r -= 1
            self._refs[bid] = r
            if r == 0:
                self._free.append(bid)
                freed.append(bid)
        return freed
