"""ray_tpu.collective — eager host-driven collective communication.

Parity target: python/ray/util/collective/ (group management + allreduce/
allgather/broadcast/reduce/reducescatter/send/recv across actors). The
in-program TPU collective plane is GSPMD/XLA over ICI (ray_tpu.parallel);
this package is the host/DCN plane.
"""

from ray_tpu.collective.coordinator import ReduceOp
from ray_tpu.collective.collective import (
    init_collective_group,
    create_collective_group,
    destroy_collective_group,
    is_group_initialized,
    get_rank,
    get_collective_group_size,
    allreduce,
    allgather,
    broadcast,
    reduce,
    reducescatter,
    alltoall,
    barrier,
    busy_section,
    send,
    recv,
)

__all__ = [
    "busy_section",
    "ReduceOp",
    "init_collective_group",
    "create_collective_group",
    "destroy_collective_group",
    "is_group_initialized",
    "get_rank",
    "get_collective_group_size",
    "allreduce",
    "allgather",
    "broadcast",
    "reduce",
    "reducescatter",
    "alltoall",
    "barrier",
    "send",
    "recv",
]
