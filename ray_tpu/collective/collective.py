"""Eager host-driven collectives across actors/tasks.

API parity with the reference's python/ray/util/collective/collective.py
(init_collective_group :120, create_collective_group :151, allreduce :258,
send :350 / recv :376 in the NCCL group). Backend difference, by design:
on TPU the *in-program* collective plane is XLA ops over ICI inserted by
GSPMD (ray_tpu.parallel); this module is the out-of-program host plane —
numpy tensors rendezvous through a coordinator actor over the object
store (the DCN path), matching the role of the reference's gloo backend.

Launch-order discipline: every rank of a group must issue the same
collective ops in the same order (the same contract NCCL imposes). Each
process keeps a per-group sequence counter; mismatched orders deadlock,
exactly as they would on NCCL — use the `timeout_s` escape hatch to turn
deadlocks into errors.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.collective.coordinator import (COORDINATOR_NAME,
                                            COORDINATOR_NAMESPACE,
                                            CollectiveCoordinator, ReduceOp)

_DEFAULT_TIMEOUT_S = 120.0
# Per-PROCESS incarnation tokens, keyed by (group, rank). Cached at module
# level so re-initializing a group from the same process reuses the token
# (no epoch bump): only a genuinely restarted process (fresh module state)
# mints a new token. Without the cache, each rank's re-init would
# invalidate every other rank's epoch forever (livelock).
_incarnations: Dict[tuple, str] = {}


def _incarnation(group_name: str, rank: int) -> str:
    key = (group_name, rank)
    if key not in _incarnations:
        import uuid as _uuid

        _incarnations[key] = _uuid.uuid4().hex
    return _incarnations[key]


class _GroupState:
    def __init__(self, group_name: str, rank: int, world_size: int,
                 coordinator, epoch: int = 0):
        self.group_name = group_name
        self.rank = rank
        self.world_size = world_size
        self.coordinator = coordinator
        self.epoch = epoch
        self.seq = 0
        self._seq_lock = threading.Lock()

    def next_seq(self) -> int:
        with self._seq_lock:
            self.seq += 1
            return self.seq


# PROCESS-global, not thread-local: a rank has ONE logical op sequence
# (NCCL launch-order discipline) regardless of which thread issues the
# op. Thread-local state broke on reused actor workers — setup() on one
# dispatcher thread and the first collective on another saw different
# _GroupStates, so one rank's seq counter silently diverged from its
# peers' (observed as a barrier timing out with mismatched seq).
_process_groups: Dict[str, _GroupState] = {}


def _groups() -> Dict[str, _GroupState]:
    return _process_groups


def _get_or_create_coordinator():
    from ray_tpu.core.actor import get_actor

    try:
        return get_actor(COORDINATOR_NAME, namespace=COORDINATOR_NAMESPACE)
    except ValueError:
        pass
    try:
        cls = ray_tpu.remote(CollectiveCoordinator)
        return cls.options(name=COORDINATOR_NAME,
                           namespace=COORDINATOR_NAMESPACE,
                           lifetime="detached").remote()
    except Exception:
        # Lost the creation race; resolve the winner's actor.
        return get_actor(COORDINATOR_NAME, namespace=COORDINATOR_NAMESPACE)


def _my_actor_id_hex() -> Optional[str]:
    ctx = ray_tpu.get_runtime_context()
    actor_id = ctx.current_actor_id
    if actor_id is None:
        return None
    return actor_id.hex() if hasattr(actor_id, "hex") else str(actor_id)


def init_collective_group(world_size: int, rank: int,
                          backend: str = "store",
                          group_name: str = "default") -> None:
    """Initialize this process's membership in a collective group.

    Reference: python/ray/util/collective/collective.py:120. `backend`
    accepts "store" (the only host backend; "nccl"/"gloo" map to it for
    API compatibility).
    """
    if rank < 0 or rank >= world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    coordinator = _get_or_create_coordinator()
    # The incarnation token makes an actor RESTART visible: the
    # coordinator bumps the group epoch so the restarted rank's reset
    # seq counter can never match stale rendezvous state (ADVICE r1).
    epoch = ray_tpu.get(coordinator.declare_group.remote(
        group_name, world_size,
        {_my_actor_id_hex() or f"rank-{rank}": rank},
        incarnations={rank: _incarnation(group_name, rank)}))
    _groups()[group_name] = _GroupState(group_name, rank, world_size,
                                        coordinator, epoch)


def create_collective_group(actors: List[Any], world_size: int,
                            ranks: List[int],
                            backend: str = "store",
                            group_name: str = "default") -> None:
    """Driver-side declarative group setup over existing actors.

    Reference: python/ray/util/collective/collective.py:151. Actors join
    lazily: their first collective op resolves their rank from the
    coordinator's membership table by actor id.
    """
    if len(actors) != len(ranks) or len(actors) != world_size:
        raise ValueError("need exactly one actor per rank")
    coordinator = _get_or_create_coordinator()
    members = {a._actor_id.hex(): r for a, r in zip(actors, ranks)}
    ray_tpu.get(coordinator.declare_group.remote(group_name, world_size,
                                                 members))


def _resolve_group(group_name: str) -> _GroupState:
    state = _groups().get(group_name)
    if state is not None:
        return state
    # Declaratively-created group: look up our rank by actor id.
    coordinator = _get_or_create_coordinator()
    info = ray_tpu.get(coordinator.group_info.remote(group_name))
    if info is None:
        raise ValueError(f"collective group {group_name!r} does not exist; "
                         "call init_collective_group or "
                         "create_collective_group first")
    me = _my_actor_id_hex()
    rank = info["members"].get(me)
    if rank is None:
        raise ValueError(
            f"this process is not a member of group {group_name!r}")
    epoch = ray_tpu.get(coordinator.declare_group.remote(
        group_name, info["world_size"],
        incarnations={rank: _incarnation(group_name, rank)}))
    state = _GroupState(group_name, rank, info["world_size"], coordinator,
                        epoch)
    # setdefault, not assignment: two threads racing a rank's first op
    # must converge on ONE state (one seq counter) — a private instance
    # per thread would re-split the sequence this module just unified.
    return _groups().setdefault(group_name, state)


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups()


def destroy_collective_group(group_name: str = "default") -> None:
    state = _groups().pop(group_name, None)
    coordinator = state.coordinator if state else _get_or_create_coordinator()
    ray_tpu.get(coordinator.destroy_group.remote(group_name))


def get_rank(group_name: str = "default") -> int:
    return _resolve_group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _resolve_group(group_name).world_size


# ---- ops ----


def _as_numpy(tensor) -> np.ndarray:
    return np.asarray(tensor)


# A missing rank with a busy heartbeat fresher than this is considered
# alive-and-working; waiters extend their deadline rather than raising.
_BUSY_FRESH_S = 15.0
# Hard cap on how long busy peers can extend a waiter past its timeout —
# a wedged-but-heartbeating peer must not hang the group forever.
_BUSY_EXTENSION_CAP_S = 3600.0


def _run_op(group_name: str, op_kind: str, payload, meta: dict,
            timeout_s: float) -> Any:
    state = _resolve_group(group_name)
    seq = state.next_seq()
    ray_tpu.get(state.coordinator.contribute.remote(
        group_name, op_kind, seq, state.rank, state.world_size, payload,
        meta, epoch=state.epoch))
    deadline = time.monotonic() + timeout_s
    hard_deadline = deadline + _BUSY_EXTENSION_CAP_S
    delay = 0.001
    while True:
        ready, result = ray_tpu.get(state.coordinator.poll.remote(
            group_name, op_kind, seq, state.rank, epoch=state.epoch))
        if ready:
            return result
        now = time.monotonic()
        if now > deadline:
            # Compile-aware handshake: a peer that has not reached this
            # op yet but is heartbeating busy_section (e.g. mid
            # jit-compile) is alive — keep waiting. Only raise when a
            # missing rank is silent.
            missing = ray_tpu.get(state.coordinator.pending_ranks.remote(
                group_name, op_kind, seq, epoch=state.epoch))
            busy = ray_tpu.get(state.coordinator.busy_ranks.remote(
                group_name, max_age_s=_BUSY_FRESH_S))
            busy_missing = {r: busy[r] for r in missing if r in busy}
            if busy_missing and now < hard_deadline:
                deadline = now + min(timeout_s, 30.0)
            else:
                detail = ""
                if busy_missing:
                    detail = (" (busy-extension cap reached; busy: "
                              f"{busy_missing})")
                raise TimeoutError(
                    f"collective {op_kind} seq={seq} timed out after "
                    f"{timeout_s}s in group {group_name!r} "
                    f"(rank {state.rank}, missing ranks {missing})"
                    f"{detail}; check that all ranks issue the same ops "
                    "in the same order")
        time.sleep(delay)
        delay = min(delay * 2, 0.05)


class busy_section:
    """Context manager: report this rank alive-but-busy (long local work
    such as a first-use jit compile) so peers waiting on a collective
    extend their timeout instead of flaking. Heartbeats from a daemon
    thread; peers stop extending ~15 s after the last heartbeat, so a
    crash mid-section still fails fast.

    with collective.busy_section(group, reason="grad jit-compile"):
        loss, grads = jitted_grad(...)   # may compile for minutes
    collective.allreduce(flat, group_name=group)
    """

    def __init__(self, group_name: str = "default", reason: str = "busy",
                 heartbeat_s: float = 5.0):
        self.group_name = group_name
        self.reason = reason
        self.heartbeat_s = heartbeat_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def __enter__(self):
        state = _resolve_group(self.group_name)

        def beat():
            while not self._stop.is_set():
                try:
                    ray_tpu.get(state.coordinator.busy_heartbeat.remote(
                        self.group_name, state.rank, self.reason))
                except Exception:
                    pass
                self._stop.wait(self.heartbeat_s)

        self._thread = threading.Thread(target=beat, daemon=True,
                                        name="collective-busy-heartbeat")
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        # Deliberately do NOT clear_busy here: a peer whose extended
        # deadline fires in the window between this exit and our next
        # contribute landing would see us missing AND not busy — a
        # spurious timeout. The entry ages out of the _BUSY_FRESH_S
        # freshness window on its own once heartbeats stop, which also
        # bounds the extra wait after a crash mid-section.
        return False


def allreduce(tensor, group_name: str = "default",
              op: str = ReduceOp.SUM,
              timeout_s: float = _DEFAULT_TIMEOUT_S) -> np.ndarray:
    """Reference: collective.py:258 (in-place on GPU; value-returning here —
    host numpy tensors are copies by construction)."""
    return _run_op(group_name, "allreduce", _as_numpy(tensor),
                   {"reduce_op": op}, timeout_s)


def allgather(tensor, group_name: str = "default",
              timeout_s: float = _DEFAULT_TIMEOUT_S) -> List[np.ndarray]:
    return _run_op(group_name, "allgather", _as_numpy(tensor), {}, timeout_s)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default",
              timeout_s: float = _DEFAULT_TIMEOUT_S) -> np.ndarray:
    return _run_op(group_name, "broadcast", _as_numpy(tensor),
                   {"src_rank": src_rank}, timeout_s)


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: str = ReduceOp.SUM,
           timeout_s: float = _DEFAULT_TIMEOUT_S) -> Optional[np.ndarray]:
    """Non-dst ranks receive None."""
    return _run_op(group_name, "reduce", _as_numpy(tensor),
                   {"reduce_op": op, "dst_rank": dst_rank}, timeout_s)


def reducescatter(tensor, group_name: str = "default",
                  op: str = ReduceOp.SUM,
                  timeout_s: float = _DEFAULT_TIMEOUT_S) -> np.ndarray:
    """Each rank receives its axis-0 shard of the reduced tensor."""
    return _run_op(group_name, "reducescatter", _as_numpy(tensor),
                   {"reduce_op": op}, timeout_s)


def alltoall(tensor_list: List[Any], group_name: str = "default",
             timeout_s: float = _DEFAULT_TIMEOUT_S) -> List[np.ndarray]:
    """tensor_list[i] goes to rank i; returns one chunk from every rank."""
    state = _resolve_group(group_name)
    if len(tensor_list) != state.world_size:
        raise ValueError("alltoall needs exactly world_size tensors")
    return _run_op(group_name, "alltoall",
                   [_as_numpy(t) for t in tensor_list], {}, timeout_s)


def barrier(group_name: str = "default",
            timeout_s: float = _DEFAULT_TIMEOUT_S) -> None:
    _run_op(group_name, "barrier", None, {}, timeout_s)


def send(tensor, dst_rank: int, group_name: str = "default",
         tag: int = 0) -> None:
    """P2P send (reference: nccl_collective_group.py:350)."""
    state = _resolve_group(group_name)
    ray_tpu.get(state.coordinator.p2p_send.remote(
        group_name, state.rank, dst_rank, tag, _as_numpy(tensor),
        epoch=state.epoch))


def recv(src_rank: int, group_name: str = "default", tag: int = 0,
         timeout_s: float = _DEFAULT_TIMEOUT_S) -> np.ndarray:
    """P2P recv (reference: nccl_collective_group.py:376)."""
    state = _resolve_group(group_name)
    deadline = time.monotonic() + timeout_s
    delay = 0.001
    while True:
        ready, payload = ray_tpu.get(state.coordinator.p2p_recv.remote(
            group_name, src_rank, state.rank, tag, epoch=state.epoch))
        if ready:
            return payload
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"recv from rank {src_rank} tag={tag} timed out")
        time.sleep(delay)
        delay = min(delay * 2, 0.05)
