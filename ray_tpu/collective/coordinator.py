"""Collective coordinator actor — rendezvous + host-side reduction.

This is the DCN/host plane of the collective layer (reference:
python/ray/util/collective/collective_group/ — NCCL/Gloo groups). On TPU,
in-program collectives are XLA ops over ICI (jax.lax.psum et al., see
ray_tpu.parallel); this coordinator serves the *eager, host-driven* path
the reference's gloo backend serves: numpy tensors moved between actor
processes through the object store, reduced on the coordinator.

One named coordinator actor exists per collective group namespace. All
ranks of a group must issue the same ops in the same order (NCCL-style
launch-order discipline); each op gets a monotonically increasing sequence
number on every rank, and the coordinator keys rendezvous state on
(group, op, seq).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

COORDINATOR_NAME = "_ray_tpu_collective_coordinator"
COORDINATOR_NAMESPACE = "ray_tpu.collective"


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


def _reduce(op: str, tensors: List[np.ndarray]) -> np.ndarray:
    stack = np.stack(tensors)
    if op == ReduceOp.SUM:
        return stack.sum(axis=0)
    if op == ReduceOp.PRODUCT:
        return stack.prod(axis=0)
    if op == ReduceOp.MIN:
        return stack.min(axis=0)
    if op == ReduceOp.MAX:
        return stack.max(axis=0)
    raise ValueError(f"unknown reduce op: {op}")


class _Rendezvous:
    """State for one in-flight collective op instance."""

    __slots__ = ("world_size", "payloads", "result", "fetched")

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.payloads: Dict[int, Any] = {}
        self.result: Any = None
        self.fetched: set = set()


class CollectiveCoordinator:
    """Named actor holding group membership and op rendezvous state."""

    def __init__(self):
        # group_name -> {"world_size": int, "members": {actor_id_hex: rank},
        #                "epoch": int, "incarnations": {rank: token}}
        self._groups: Dict[str, Dict[str, Any]] = {}
        # (group, epoch, op_kind, seq) -> _Rendezvous
        self._ops: Dict[Tuple[str, int, str, int], _Rendezvous] = {}
        # (group, src, dst, tag) -> FIFO of payloads (p2p mailbox)
        self._mailbox: Dict[Tuple[str, int, int, int], List[Any]] = {}
        # (group, rank) -> (reason, last_heartbeat time.time()) — the
        # compile-aware handshake: a rank doing long local work (jit
        # compile) heartbeats here; waiters extend their op timeout only
        # while a missing rank's heartbeat stays fresh.
        self._busy: Dict[Tuple[str, int], Tuple[str, float]] = {}

    # ---- membership ----

    def declare_group(self, group_name: str, world_size: int,
                      members: Optional[Dict[str, int]] = None,
                      incarnations: Optional[Dict[int, str]] = None) -> int:
        """Register a group (declarative driver-side setup); returns the
        group EPOCH.

        members maps actor-id hex -> rank, used by actors that never called
        init_collective_group locally (reference: create_collective_group,
        python/ray/util/collective/collective.py:151). Declarations merge:
        each rank's init_collective_group contributes its own entry.

        incarnations maps rank -> per-process token. A rank re-declaring
        with a NEW token is a restarted actor whose local op sequence
        reset to 0: the epoch bumps and all in-flight rendezvous state of
        the group is dropped, so the restarted rank can never silently
        match a stale (group, op, seq) entry — peers of the dead epoch
        fail fast instead (ADVICE r1: stale-rendezvous hazard).
        """
        group = self._groups.setdefault(
            group_name, {"world_size": world_size, "members": {},
                         "epoch": 0, "incarnations": {}})
        if group["world_size"] != world_size:
            raise ValueError(
                f"group {group_name!r} redeclared with world_size "
                f"{world_size}, was {group['world_size']}")
        group["members"].update(members or {})
        for rank, token in (incarnations or {}).items():
            old = group["incarnations"].get(rank)
            if old is not None and old != token:
                group["epoch"] += 1
                for key in [k for k in self._ops if k[0] == group_name]:
                    del self._ops[key]
                # Stale p2p payloads are the same hazard as stale
                # rendezvous: drop the group's mailbox too.
                for key in [k for k in self._mailbox
                            if k[0] == group_name]:
                    del self._mailbox[key]
            group["incarnations"][rank] = token
        return group["epoch"]

    def group_info(self, group_name: str) -> Optional[Dict[str, Any]]:
        return self._groups.get(group_name)

    def rank_of(self, group_name: str, actor_id_hex: str) -> Optional[int]:
        group = self._groups.get(group_name)
        if group is None:
            return None
        return group["members"].get(actor_id_hex)

    def destroy_group(self, group_name: str) -> None:
        self._groups.pop(group_name, None)
        for key in [k for k in self._ops if k[0] == group_name]:
            del self._ops[key]
        for key in [k for k in self._mailbox if k[0] == group_name]:
            del self._mailbox[key]
        for key in [k for k in self._busy if k[0] == group_name]:
            del self._busy[key]

    # ---- busy handshake (compile-aware timeouts) ----

    def busy_heartbeat(self, group: str, rank: int, reason: str) -> None:
        """A rank reports it is alive but stuck in long LOCAL work (e.g.
        a jit compile) before it can reach its next collective op."""
        import time as _time

        self._busy[(group, rank)] = (reason, _time.time())

    def clear_busy(self, group: str, rank: int) -> None:
        self._busy.pop((group, rank), None)

    def busy_ranks(self, group: str,
                   max_age_s: float = 15.0) -> Dict[int, str]:
        """Ranks of `group` with a fresh busy heartbeat."""
        import time as _time

        now = _time.time()
        return {rank: reason
                for (g, rank), (reason, ts) in self._busy.items()
                if g == group and now - ts <= max_age_s}

    def pending_ranks(self, group: str, op_kind: str, seq: int,
                      epoch: int = 0) -> List[int]:
        """Ranks that have NOT yet contributed to (op_kind, seq)."""
        self._check_epoch(group, epoch)
        rdv = self._ops.get((group, epoch, op_kind, seq))
        if rdv is None:
            g = self._groups.get(group)
            world = g["world_size"] if g else 0
            return list(range(world))
        return [r for r in range(rdv.world_size)
                if r not in rdv.payloads]

    # ---- collective rendezvous ----

    def _check_epoch(self, group: str, epoch: int) -> None:
        g = self._groups.get(group)
        current = g["epoch"] if g else 0
        if epoch != current:
            raise RuntimeError(
                f"collective group {group!r} epoch {epoch} is stale "
                f"(current {current}): a member actor restarted — "
                "re-init_collective_group on every rank")

    def contribute(self, group: str, op_kind: str, seq: int, rank: int,
                   world_size: int, payload: Any,
                   meta: Optional[dict] = None, epoch: int = 0) -> None:
        self._check_epoch(group, epoch)
        key = (group, epoch, op_kind, seq)
        rdv = self._ops.get(key)
        if rdv is None:
            rdv = self._ops[key] = _Rendezvous(world_size)
        rdv.payloads[rank] = payload
        if len(rdv.payloads) == rdv.world_size and rdv.result is None:
            rdv.result = self._finalize(op_kind, rdv, meta or {})

    def poll(self, group: str, op_kind: str, seq: int,
             rank: int, epoch: int = 0) -> Tuple[bool, Any]:
        """Returns (ready, result-for-rank); cleans up after all fetched."""
        self._check_epoch(group, epoch)
        key = (group, epoch, op_kind, seq)
        rdv = self._ops.get(key)
        if rdv is None or rdv.result is None:
            return False, None
        result = rdv.result[rank] if isinstance(rdv.result, dict) \
            else rdv.result
        rdv.fetched.add(rank)
        if len(rdv.fetched) == rdv.world_size:
            del self._ops[key]
        return True, result

    def _finalize(self, op_kind: str, rdv: _Rendezvous, meta: dict) -> Any:
        kind = op_kind.split(":")[0]
        by_rank = [rdv.payloads[r] for r in range(rdv.world_size)]
        if kind == "allreduce":
            return _reduce(meta.get("reduce_op", ReduceOp.SUM), by_rank)
        if kind == "allgather":
            return list(by_rank)
        if kind == "broadcast":
            return by_rank[meta.get("src_rank", 0)]
        if kind == "reduce":
            # Only dst rank receives the reduced tensor.
            reduced = _reduce(meta.get("reduce_op", ReduceOp.SUM), by_rank)
            dst = meta.get("dst_rank", 0)
            return {r: (reduced if r == dst else None)
                    for r in range(rdv.world_size)}
        if kind == "reducescatter":
            reduced = _reduce(meta.get("reduce_op", ReduceOp.SUM), by_rank)
            chunks = np.array_split(reduced, rdv.world_size, axis=0)
            return {r: chunks[r] for r in range(rdv.world_size)}
        if kind == "alltoall":
            # payload per rank is a list of world_size chunks.
            return {r: [by_rank[s][r] for s in range(rdv.world_size)]
                    for r in range(rdv.world_size)}
        if kind == "barrier":
            return True
        raise ValueError(f"unknown collective kind: {kind}")

    # ---- p2p mailbox ----

    def p2p_send(self, group: str, src: int, dst: int, tag: int,
                 payload: Any, epoch: int = 0) -> None:
        self._check_epoch(group, epoch)
        self._mailbox.setdefault((group, src, dst, tag), []).append(payload)

    def p2p_recv(self, group: str, src: int, dst: int,
                 tag: int, epoch: int = 0) -> Tuple[bool, Any]:
        self._check_epoch(group, epoch)
        key = (group, src, dst, tag)
        queue = self._mailbox.get(key)
        if queue:
            payload = queue.pop(0)
            if not queue:
                del self._mailbox[key]
            return True, payload
        return False, None
