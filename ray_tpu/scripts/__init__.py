"""CLI package."""
