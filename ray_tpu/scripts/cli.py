"""ray-tpu CLI — cluster lifecycle + introspection.

Reference: python/ray/scripts/scripts.py (`ray start` :571, stop, status,
list, timeline, memory, job submit). Invoke as `python -m ray_tpu.scripts
<command>`. `start --head` runs the head node processes and writes the
cluster address to /tmp/ray_tpu/cluster_address so later commands (and
`start` on worker machines) can find it.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

ADDRESS_FILE = "/tmp/ray_tpu/cluster_address"


def _write_address(address: str, pid: int) -> None:
    os.makedirs(os.path.dirname(ADDRESS_FILE), exist_ok=True)
    with open(ADDRESS_FILE, "w") as f:
        json.dump({"address": address, "pid": pid}, f)


def _read_address() -> dict:
    try:
        with open(ADDRESS_FILE) as f:
            return json.load(f)
    except FileNotFoundError:
        raise SystemExit(
            "no running cluster found (missing "
            f"{ADDRESS_FILE}); start one with: "
            "python -m ray_tpu.scripts start --head")


def _connect(address: str = None):
    import ray_tpu

    addr = address or _read_address()["address"]
    if not ray_tpu.is_initialized():
        ray_tpu.init(address=addr)


def cmd_start(args) -> None:
    from ray_tpu._private.node import Node
    from ray_tpu.core.config import Config

    resources = {}
    if args.num_cpus is not None:
        resources["CPU"] = float(args.num_cpus)
    if args.num_tpus is not None:
        resources["TPU"] = float(args.num_tpus)
    for spec in args.resources or []:
        name, val = spec.split("=", 1)
        resources[name] = float(val)

    config = Config.from_env(None)
    dash = None
    client_proxy = None
    if args.head:
        node = Node(config, resources=resources or None)
    else:
        address = args.address or _read_address()["address"]
        node = Node(config, resources=resources or None,
                    gcs_address=address)
    node.start()
    # Everything after start() runs under try/finally: a failure (e.g.
    # dashboard port in use) must still tear the GCS/raylet children down
    # — they live in their own sessions and would otherwise be orphaned.
    try:
        if args.head:
            _write_address(node.gcs_address, os.getpid())
            print(f"ray_tpu head started; address={node.gcs_address}")
            if args.dashboard_port:
                import ray_tpu
                from ray_tpu.dashboard import start_dashboard

                ray_tpu.init(address=node.gcs_address)
                try:
                    dash = start_dashboard(port=args.dashboard_port)
                    print(f"dashboard: "
                          f"http://127.0.0.1:{args.dashboard_port}")
                except Exception as e:
                    # The head is useful without a dashboard (e.g. port
                    # 8265 taken by another cluster) — warn, keep going.
                    print(f"warning: dashboard disabled: {e}")
            if args.client_server_port:
                import ray_tpu
                from ray_tpu.util.client import ClientProxyServer

                ray_tpu.init(address=node.gcs_address,
                             ignore_reinit_error=True)
                try:
                    client_proxy = ClientProxyServer(
                        port=args.client_server_port).start()
                    print(f"client proxy: ray://127.0.0.1:"
                          f"{client_proxy.port}")
                except Exception as e:
                    print(f"warning: client proxy disabled: {e}")
        else:
            print(f"ray_tpu node started; joined {node.gcs_address}")

        # Both modes stay resident and tear the node down on
        # SIGTERM/SIGINT (`stop` sends SIGTERM).
        if not args.block:
            print("(head process stays resident; `stop` tears it down)")
        stop = []
        signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
        signal.signal(signal.SIGINT, lambda *a: stop.append(1))
        while not stop:
            time.sleep(0.5)
    finally:
        if client_proxy is not None:
            client_proxy.stop()
        if dash is not None:
            dash.stop()
        node.shutdown()


def cmd_stop(args) -> None:
    info = _read_address()
    pid = info.get("pid")
    if pid:
        try:
            os.kill(pid, signal.SIGTERM)
            print(f"sent SIGTERM to head process {pid}")
        except ProcessLookupError:
            print("head process already gone")
    try:
        os.remove(ADDRESS_FILE)
    except FileNotFoundError:
        pass


def cmd_status(args) -> None:
    _connect(args.address)
    from ray_tpu.util import state

    res = state.cluster_resources()
    nodes = state.list_nodes()
    alive = [n for n in nodes if n.get("state") == "ALIVE"]
    print(f"nodes: {len(alive)} alive / {len(nodes)} total")
    print("resources (available / total):")
    for key in sorted(res["total"]):
        print(f"  {key}: {res['available'].get(key, 0):g} / "
              f"{res['total'][key]:g}")


def _list_events(limit=100):
    from ray_tpu.util.events import list_events

    return list_events(limit=limit)


def cmd_list(args) -> None:
    _connect(args.address)
    from ray_tpu.util import state

    fn = {
        "tasks": state.list_tasks,
        "actors": state.list_actors,
        "nodes": state.list_nodes,
        "objects": state.list_objects,
        "placement-groups": state.list_placement_groups,
        "jobs": state.list_jobs,
        "events": _list_events,
    }[args.what]
    rows = fn(limit=args.limit)
    print(json.dumps(rows, indent=2, default=str))


def cmd_summary(args) -> None:
    _connect(args.address)
    from ray_tpu.util import state

    fn = {"tasks": state.summarize_tasks,
          "actors": state.summarize_actors}[args.what]
    print(json.dumps(fn(), indent=2))


def cmd_timeline(args) -> None:
    _connect(args.address)
    from ray_tpu.util.timeline import timeline

    events = timeline(args.output)
    print(f"wrote {len(events)} events to {args.output}")


def cmd_memory(args) -> None:
    _connect(args.address)
    from ray_tpu.util import state

    rows = state.list_objects(limit=args.limit)
    print(json.dumps(rows, indent=2, default=str))


def cmd_logs(args) -> None:
    """List or tail worker log files of the latest (or given) session
    (reference: `ray logs` CLI, python/ray/scripts)."""
    import glob

    base = args.session or max(
        glob.glob("/tmp/ray_tpu/session_*"), default=None,
        key=lambda p: os.path.getmtime(p))
    if base is None:
        print("no ray_tpu session found under /tmp/ray_tpu")
        return
    log_dir = os.path.join(base, "logs")
    files = sorted(glob.glob(os.path.join(log_dir, "*")))
    if args.filename:
        path = os.path.join(log_dir, args.filename)
        with open(path, "r", errors="replace") as f:
            content = f.readlines()
        for line in content[-args.tail:]:
            print(line.rstrip())
        return
    for path in files:
        size = os.path.getsize(path)
        print(f"{os.path.basename(path)}\t{size} bytes")


def cmd_job(args) -> None:
    from ray_tpu.job_submission import JobSubmissionClient

    address = args.address or _read_address()["address"]
    client = JobSubmissionClient(address)
    if args.job_cmd == "submit":
        import shlex

        entrypoint = [a for a in args.entrypoint if a != "--"]
        sid = client.submit_job(entrypoint=shlex.join(entrypoint))
        print(f"submitted job {sid}")
        if args.wait:
            for chunk in client.tail_job_logs(sid):
                sys.stdout.write(chunk)
            print(f"status: {client.get_job_status(sid)}")
    elif args.job_cmd == "status":
        print(client.get_job_status(args.id))
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.id))
    elif args.job_cmd == "stop":
        print(client.stop_job(args.id))
    elif args.job_cmd == "list":
        for info in client.list_jobs():
            print(f"{info.submission_id}  {info.status:10s}  "
                  f"{info.entrypoint}")


def cmd_serve(args) -> None:
    _connect(args.address)
    from ray_tpu import serve

    if args.serve_cmd == "deploy":
        handles = serve.deploy_config_file(args.config)
        print(f"deployed applications: {sorted(handles)}")
    elif args.serve_cmd == "status":
        print(json.dumps(serve.status(), indent=2, default=str))
    elif args.serve_cmd == "shutdown":
        serve.shutdown()
        print("serve shut down")


def cmd_up(args) -> None:
    from ray_tpu.autoscaler.launcher import create_or_update_cluster

    state = create_or_update_cluster(args.config)
    print(f"cluster {state['cluster_name']} up; "
          f"head address={state['head_address']} "
          f"workers={len(state['workers'])}")


def cmd_down(args) -> None:
    from ray_tpu.autoscaler.launcher import teardown_cluster

    teardown_cluster(args.config)
    print("cluster down")


def cmd_exec(args) -> None:
    from ray_tpu.autoscaler.launcher import exec_on_cluster

    print(exec_on_cluster(args.config, args.cmd,
                          all_nodes=args.all_nodes), end="")


def cmd_attach(args) -> None:
    import subprocess as _sp

    from ray_tpu.autoscaler.launcher import attach_command

    raise SystemExit(_sp.call(attach_command(args.config)))


def cmd_runs(args) -> None:
    from ray_tpu.air.integrations.tracking import format_runs, list_runs

    print(format_runs(list_runs(tracking_root=args.root,
                                experiment=args.experiment)))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ray_tpu",
                                description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("start", help="start head or worker node")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", help="existing cluster address to join")
    sp.add_argument("--num-cpus", type=float)
    sp.add_argument("--num-tpus", type=float)
    sp.add_argument("--resources", nargs="*",
                    help="extra resources, e.g. TPU-v5e-8-head=1")
    sp.add_argument("--dashboard-port", type=int, default=8265,
                    help="0 disables the dashboard")
    sp.add_argument("--client-server-port", type=int, default=0,
                    help="host a ray:// client proxy on this port")
    sp.add_argument("--block", action="store_true")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop the local cluster")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("up", help="launch a cluster from a YAML config")
    sp.add_argument("config", help="cluster YAML path")
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear down a launched cluster")
    sp.add_argument("config", help="cluster YAML path")
    sp.set_defaults(fn=cmd_down)

    sp = sub.add_parser("exec", help="run a command on the cluster head")
    sp.add_argument("config", help="cluster YAML path")
    sp.add_argument("cmd", help="shell command")
    sp.add_argument("--all-nodes", action="store_true")
    sp.set_defaults(fn=cmd_exec)

    sp = sub.add_parser("attach",
                        help="interactive shell on the cluster head")
    sp.add_argument("config", help="cluster YAML path")
    sp.set_defaults(fn=cmd_attach)

    sp = sub.add_parser("status", help="cluster resource summary")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("list", help="list cluster state")
    sp.add_argument("what", choices=["tasks", "actors", "nodes", "objects",
                                     "placement-groups", "jobs", "events"])
    sp.add_argument("--limit", type=int, default=100)
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("summary", help="state summaries")
    sp.add_argument("what", choices=["tasks", "actors"])
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_summary)

    sp = sub.add_parser("timeline", help="dump chrome trace of tasks")
    sp.add_argument("--output", default="/tmp/ray_tpu_timeline.json")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser("memory", help="object store contents")
    sp.add_argument("--limit", type=int, default=100)
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_memory)

    sp = sub.add_parser("runs",
                        help="list locally tracked experiment runs")
    sp.add_argument("--root", default=None,
                    help="tracking root (default: RAY_TPU_TRACKING_ROOT"
                         " or ~/ray_tpu_results/tracking)")
    sp.add_argument("--experiment", default=None)
    sp.set_defaults(fn=cmd_runs)

    sp = sub.add_parser("logs", help="list/tail session worker logs")
    sp.add_argument("filename", nargs="?", default=None,
                    help="log file to print (omit to list)")
    sp.add_argument("--session", help="session dir (default: latest)")
    sp.add_argument("--tail", type=int, default=200)
    sp.set_defaults(fn=cmd_logs)

    sp = sub.add_parser("serve", help="serve deploy/status/shutdown")
    ssub = sp.add_subparsers(dest="serve_cmd", required=True)
    s = ssub.add_parser("deploy")
    s.add_argument("config", help="YAML/JSON ServeDeploySchema file")
    s.add_argument("--address")
    s.set_defaults(fn=cmd_serve)
    for name in ("status", "shutdown"):
        s = ssub.add_parser(name)
        s.add_argument("--address")
        s.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("job", help="job submission")
    jsub = sp.add_subparsers(dest="job_cmd", required=True)
    j = jsub.add_parser("submit")
    j.add_argument("entrypoint", nargs=argparse.REMAINDER)
    j.add_argument("--wait", action="store_true")
    j.add_argument("--address")
    j.set_defaults(fn=cmd_job)
    for name in ("status", "logs", "stop"):
        j = jsub.add_parser(name)
        j.add_argument("id")
        j.add_argument("--address")
        j.set_defaults(fn=cmd_job)
    j = jsub.add_parser("list")
    j.add_argument("--address")
    j.set_defaults(fn=cmd_job)

    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
