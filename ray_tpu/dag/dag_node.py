"""DAG nodes: build lazily with .bind(), run with .execute().

Reference: python/ray/dag/dag_node.py (DAGNode, ``.bind()``), input_node.py.
Execution walks the DAG bottom-up, submitting each node as a task/actor call
whose upstream results are passed as ObjectRefs (so the object store, not
the driver, carries intermediate data).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    def _resolve(self, value, input_value, cache: dict):
        if isinstance(value, DAGNode):
            return value._execute(input_value, cache)
        if isinstance(value, (list, tuple)):
            return type(value)(self._resolve(v, input_value, cache)
                               for v in value)
        return value

    def _resolved_args(self, input_value, cache: dict) -> Tuple[tuple, dict]:
        args = tuple(self._resolve(a, input_value, cache)
                     for a in self._bound_args)
        kwargs = {k: self._resolve(v, input_value, cache)
                  for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute(self, input_value, cache: dict):
        if id(self) not in cache:
            cache[id(self)] = self._execute_impl(input_value, cache)
        return cache[id(self)]

    def _execute_impl(self, input_value, cache: dict):
        raise NotImplementedError

    def execute(self, input_value: Any = None):
        """Submit the DAG; returns the root's ObjectRef(s)."""
        return self._execute(input_value, {})

    def experimental_compile(self, **kwargs):
        from ray_tpu.dag.compiled_dag import CompiledDAG

        return CompiledDAG(self, **kwargs)


class InputNode(DAGNode):
    """Placeholder for the value passed to ``dag.execute(x)``."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_impl(self, input_value, cache):
        return input_value


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args: tuple, kwargs: dict):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_impl(self, input_value, cache):
        args, kwargs = self._resolved_args(input_value, cache)
        return self._remote_fn.remote(*args, **kwargs)


class ActorClassNode(DAGNode):
    def __init__(self, actor_cls, args: tuple, kwargs: dict):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._handle = None

    def _execute_impl(self, input_value, cache):
        if self._handle is None:
            args, kwargs = self._resolved_args(input_value, cache)
            self._handle = self._actor_cls.remote(*args, **kwargs)
        return self._handle

    def __getattr__(self, name: str) -> "_DagMethod":
        if name.startswith("_"):
            raise AttributeError(name)
        return _DagMethod(self, name)


class _DagMethod:
    """`Actor.bind(...).method.bind(args)` — method-call node factory."""

    def __init__(self, node: "ActorClassNode", method: str):
        self._node = node
        self._method = method

    def bind(self, *args, **kwargs) -> "ActorMethodNode":
        return ActorMethodNode(self._node, self._method, args, kwargs)


class ActorMethodNode(DAGNode):
    def __init__(self, handle_or_node, method: str, args: tuple,
                 kwargs: dict):
        super().__init__(args, kwargs)
        self._target = handle_or_node
        self._method = method

    def _execute_impl(self, input_value, cache):
        target = self._target
        if isinstance(target, DAGNode):
            target = target._execute(input_value, cache)
        args, kwargs = self._resolved_args(input_value, cache)
        return getattr(target, self._method).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Groups several leaves: execute() returns a list of refs."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__((), {})
        self._outputs = outputs

    def _execute_impl(self, input_value, cache):
        return [o._execute(input_value, cache) for o in self._outputs]
