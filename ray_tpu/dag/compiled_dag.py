"""Compiled DAGs — static actor pipelines over mutable shm channels.

Reference: python/ray/dag/compiled_dag_node.py:391 (CompiledDAG: allocate
channels, install a per-actor execution loop, drive steady-state iterations
with zero per-step driver RPCs; channels in python/ray/experimental/channel/).

Compilation:
1. Walk the DAG (InputNode / ActorMethodNode / MultiOutputNode). Each
   cross-process edge gets a native mutable shm channel
   (ray_tpu/experimental/channel/); same-actor edges stay local values.
2. Each participating actor receives one ``__dag_loop__`` task carrying its
   plan (methods + channel bindings); the loop (exec_loop.run_dag_loop)
   runs until teardown closes the input channels.
3. ``execute(x)`` writes x into the input channel and returns a
   CompiledDAGRef; ``.get()`` reads the output channels — both directly on
   the caller's thread through shared memory, no RPCs, no event loop.

Graphs with non-actor nodes (FunctionNode) fall back to eager per-call
task submission, same API.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import serialization as ser
from ray_tpu.dag.dag_node import (ActorClassNode, ActorMethodNode, DAGNode,
                                  FunctionNode, InputNode, MultiOutputNode)

logger = logging.getLogger(__name__)


class CompiledDAGRef:
    """Result handle for one compiled-DAG execution (reference:
    python/ray/experimental/compiled_dag_ref.py)."""

    def __init__(self, dag: "CompiledDAG", index: int, output_index: int):
        self._dag = dag
        self._index = index
        self._output_index = output_index
        self._value: Any = None
        self._fetched = False

    def get(self, timeout: Optional[float] = None):
        if not self._fetched:
            self._dag._fetch_until(self._index, timeout)
            self._value = self._dag._take_result(self._index,
                                                 self._output_index)
            self._fetched = True
        if isinstance(self._value, Exception):
            raise self._value
        return self._value


class CompiledDAG:
    def __init__(self, root: DAGNode, *, buffer_size_bytes: int = 16 << 20,
                 submit_timeout: float = 30.0,
                 max_inflight_executions: int = 8):
        self._root = root
        self._buffer_size = buffer_size_bytes
        self._timeout = submit_timeout
        # Channel ring depth == max executions in flight before get()
        # (reference: CompiledDAG _max_inflight_executions).
        self._max_inflight = max(2, min(max_inflight_executions, 64))
        self._eager = False
        self._input_chan = None
        self._input_path: Optional[str] = None
        self._output_chans: List = []
        self._all_chan_paths: List[str] = []
        self._loop_refs: List = []
        # Per-execution result rows, trimmed once every output is taken.
        self._pending: Dict[int, List[Any]] = {}
        self._taken: Dict[int, int] = {}
        self._executions = 0
        self._fetched_upto = 0
        self._fetch_col = 0  # resume column for a mid-row timeout
        self._torn_down = False
        self._compile()

    # ------------------------------------------------------------ compile
    def _collect(self) -> Tuple[List[DAGNode], List[DAGNode]]:
        """Post-order node list + explicit output list."""
        order: List[DAGNode] = []
        seen: set = set()
        root = self._root
        outputs = (list(root._outputs) if isinstance(root, MultiOutputNode)
                   else [root])

        def visit(node: DAGNode):
            if id(node) in seen:
                return
            seen.add(id(node))
            for dep in list(node._bound_args) + \
                    list(node._bound_kwargs.values()):
                if isinstance(dep, DAGNode):
                    visit(dep)
            if isinstance(node, ActorMethodNode) and \
                    isinstance(node._target, DAGNode):
                visit(node._target)
            order.append(node)

        for out in outputs:
            visit(out)
        return order, outputs

    def _compile(self) -> None:
        from ray_tpu.core.actor import ActorHandle

        order, outputs = self._collect()
        method_nodes = [n for n in order if isinstance(n, ActorMethodNode)]
        has_input = any(isinstance(n, InputNode) for n in order)
        if not method_nodes or not has_input or \
                any(isinstance(n, FunctionNode) for n in order) or \
                not all(isinstance(out, ActorMethodNode) for out in outputs):
            # Not a pure input-driven actor pipeline (a DAG without an
            # InputNode would free-run, decoupled from execute()): keep
            # the eager path.
            self._eager = True
            return

        from ray_tpu.experimental.channel import Channel

        def actor_of(node: ActorMethodNode) -> ActorHandle:
            target = node._target
            if isinstance(target, ActorClassNode):
                return target._execute(None, {})
            if isinstance(target, ActorHandle):
                return target
            raise TypeError(
                f"compiled DAG methods must bind to actors, got {target!r}")

        node_actor: Dict[int, ActorHandle] = {
            id(n): actor_of(n) for n in method_nodes}

        # Which actors read the driver input?
        input_consumer_actors: List[bytes] = []
        for n in method_nodes:
            for dep in list(n._bound_args) + \
                    list(n._bound_kwargs.values()):
                if isinstance(dep, InputNode):
                    aid = node_actor[id(n)]._actor_id.binary()
                    if aid not in input_consumer_actors:
                        input_consumer_actors.append(aid)

        plans: Dict[bytes, Dict] = {}
        actor_handles: Dict[bytes, ActorHandle] = {}
        for n in method_nodes:
            handle = node_actor[id(n)]
            aid = handle._actor_id.binary()
            actor_handles[aid] = handle
            plans.setdefault(aid, {"in_chans": [], "steps": [],
                                   "out_chans": [], "consts": []})

        if input_consumer_actors:
            self._input_path = Channel.create(
                n_readers=len(input_consumer_actors),
                capacity=self._buffer_size,
                n_slots=self._max_inflight)
            self._all_chan_paths.append(self._input_path)
            self._input_chan = Channel(self._input_path)
            for rid, aid in enumerate(input_consumer_actors):
                plans[aid]["in_chans"].append((self._input_path, rid))
                plans[aid]["_input_idx"] = len(plans[aid]["in_chans"]) - 1

        # Steps in topo order; cross-actor edges become channels.
        step_index: Dict[int, Tuple[bytes, int]] = {}
        for n in method_nodes:
            aid = node_actor[id(n)]._actor_id.binary()
            plan = plans[aid]

            def argspec(dep):
                if isinstance(dep, InputNode):
                    return ("chan", plan["_input_idx"])
                if isinstance(dep, ActorMethodNode):
                    src_aid, src_idx = step_index[id(dep)]
                    if src_aid == aid:
                        return ("local", src_idx)
                    path = Channel.create(n_readers=1,
                                          capacity=self._buffer_size,
                                          n_slots=self._max_inflight)
                    self._all_chan_paths.append(path)
                    src_plan = plans[src_aid]
                    src_plan["out_chans"].append(path)
                    src_plan["steps"][src_idx]["outs"].append(
                        len(src_plan["out_chans"]) - 1)
                    plan["in_chans"].append((path, 0))
                    return ("chan", len(plan["in_chans"]) - 1)
                if isinstance(dep, DAGNode):
                    raise TypeError(f"unsupported DAG dep: {dep!r}")
                plan["consts"].append(ser.dumps(dep))
                return ("const", len(plan["consts"]) - 1)

            step = {
                "method": n._method,
                "args": [argspec(a) for a in n._bound_args],
                "kwargs": {k: argspec(v)
                           for k, v in n._bound_kwargs.items()},
                "outs": [],
            }
            plan["steps"].append(step)
            step_index[id(n)] = (aid, len(plan["steps"]) - 1)

        # Output channels (producer actor -> driver).
        for out in outputs:
            src_aid, src_idx = step_index[id(out)]
            path = Channel.create(n_readers=1, capacity=self._buffer_size,
                                  n_slots=self._max_inflight)
            self._all_chan_paths.append(path)
            src_plan = plans[src_aid]
            src_plan["out_chans"].append(path)
            src_plan["steps"][src_idx]["outs"].append(
                len(src_plan["out_chans"]) - 1)
            self._output_chans.append(Channel(path, reader_id=0))

        from ray_tpu.core.actor import ActorMethod

        for aid, plan in plans.items():
            plan.pop("_input_idx", None)
            # Direct ActorMethod: __getattr__ blocks dunder-prefixed names.
            self._loop_refs.append(ActorMethod(
                actor_handles[aid], "__dag_loop__", {}).remote(plan))

    # ------------------------------------------------------------ execute
    def execute(self, input_value: Any = None):
        if self._eager:
            return self._root._execute(input_value, {})
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        if self._executions - self._fetched_upto >= self._max_inflight:
            raise RuntimeError(
                f"{self._max_inflight} executions already in flight; call "
                "get() on earlier results first (or raise "
                "max_inflight_executions)")
        if self._input_chan is not None:
            self._input_chan.write(input_value, timeout=self._timeout)
        self._executions += 1
        refs = [CompiledDAGRef(self, self._executions - 1, i)
                for i in range(len(self._output_chans))]
        self._pending[self._executions - 1] = \
            [None] * len(self._output_chans)
        if isinstance(self._root, MultiOutputNode):
            return refs
        return refs[0]

    def _fetch_until(self, index: int, timeout: Optional[float]) -> None:
        from ray_tpu.experimental.channel.exec_loop import _ErrorEnvelope

        while self._fetched_upto <= index:
            row = self._pending[self._fetched_upto]
            # Resume from _fetch_col: a mid-row timeout must not re-read
            # channels whose value for this execution was already
            # consumed (each read advances that channel's reader seq).
            while self._fetch_col < len(self._output_chans):
                chan = self._output_chans[self._fetch_col]
                value = chan.read(timeout if timeout is not None
                                  else self._timeout)
                if isinstance(value, _ErrorEnvelope):
                    value = value.error
                row[self._fetch_col] = value
                self._fetch_col += 1
            self._fetched_upto += 1
            self._fetch_col = 0

    def _take_result(self, execution_index: int, output_index: int):
        value = self._pending[execution_index][output_index]
        taken = self._taken.get(execution_index, 0) + 1
        if taken >= len(self._output_chans):
            # Every output consumed: drop the row (unbounded otherwise).
            self._pending.pop(execution_index, None)
            self._taken.pop(execution_index, None)
        else:
            self._taken[execution_index] = taken
        return value

    def teardown(self) -> None:
        if self._torn_down or self._eager:
            self._torn_down = True
            return
        self._torn_down = True
        if self._input_chan is not None:
            self._input_chan.close()
        for chan in self._output_chans:
            chan.close()
        # Loops exit on ChannelClosed; collect so the actors free up.
        import ray_tpu

        for ref in self._loop_refs:
            try:
                ray_tpu.get(ref, timeout=10.0)
            except Exception:
                pass
        if self._input_chan is not None:
            self._input_chan.destroy()
        for chan in self._output_chans:
            chan.destroy()
        import os

        for path in self._all_chan_paths:
            try:
                os.unlink(path)
            except OSError:
                pass

    def __del__(self):
        try:
            if not getattr(self, "_torn_down", True):
                self.teardown()
        except Exception:
            pass
