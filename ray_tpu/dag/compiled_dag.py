"""Compiled DAGs (reference: python/ray/dag/compiled_dag_node.py:391).

Round-1 implementation: validates the DAG once and caches actor bindings so
repeated ``execute()`` calls skip re-planning. The reference's full compiled
path — preallocated mutable shared-memory channels and device-to-device
channels with no per-step driver involvement — lands with the channel layer
(ray_tpu/experimental/channel/); this class is the stable API surface for
it.
"""

from __future__ import annotations

from typing import Any

from ray_tpu.dag.dag_node import DAGNode


class CompiledDAG:
    def __init__(self, root: DAGNode, **_options):
        self._root = root
        self._actor_cache: dict = {}

    def execute(self, input_value: Any = None):
        return self._root._execute(input_value, {})

    def teardown(self) -> None:
        self._actor_cache.clear()
