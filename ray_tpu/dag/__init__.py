"""Lazy task/actor DAGs (reference: python/ray/dag/).

``fn.bind(...)`` / ``actor.method.bind(...)`` build a DAG without executing;
``dag.execute(...)`` submits it. ``experimental_compile`` (compiled graphs
with preallocated channels, reference python/ray/dag/compiled_dag_node.py)
lands with the channel layer.
"""

from ray_tpu.dag.dag_node import (ActorClassNode, ActorMethodNode, DAGNode,
                                  FunctionNode, InputNode, MultiOutputNode)

__all__ = ["DAGNode", "FunctionNode", "ActorClassNode", "ActorMethodNode",
           "InputNode", "MultiOutputNode"]
