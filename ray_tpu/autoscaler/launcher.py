"""Cluster launcher: `ray_tpu up / down / exec` from a YAML config.

Reference: python/ray/autoscaler/_private/commands.py (up/down/attach),
command_runner.py (SSHCommandRunner/DockerCommandRunner), updater.py
(NodeUpdater: wait-ready → rsync files → setup commands → start ray).

TPU-native shape: the head runs `ray_tpu start --head`; workers run
`ray_tpu start --address=<head>` with their slice identity; the
autoscaler (autoscaler/autoscaler.py) then scales workers through the
same provider. Two command runners:

- ``SSHCommandRunner``: subprocess ssh/scp against real machines — the
  production path (GCE TPU VMs land here).
- ``LocalCommandRunner``: runs commands on THIS host — exercised by the
  test tier (an `up` against provider=local brings a real head up on
  localhost), mirroring the reference's fake-multinode testing pattern.

Cluster YAML::

    cluster_name: demo
    provider:
      type: local            # local | gce (autoscaler/gce.py)
      head_ip: 127.0.0.1
      worker_ips: []         # ssh targets for type: local
    auth:
      ssh_user: tpu
      ssh_private_key: ~/.ssh/key.pem
    file_mounts:
      /remote/path: /local/path
    setup_commands:
      - pip list >/dev/null
    head_start_command: python -m ray_tpu.scripts start --head
    worker_start_command: python -m ray_tpu.scripts start --address={head_address}
    stop_command: python -m ray_tpu.scripts stop
"""

from __future__ import annotations

import json
import logging
import os
import shlex
import subprocess
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


@dataclass
class ClusterConfig:
    cluster_name: str
    provider: Dict[str, Any] = field(default_factory=dict)
    auth: Dict[str, Any] = field(default_factory=dict)
    file_mounts: Dict[str, str] = field(default_factory=dict)
    setup_commands: List[str] = field(default_factory=list)
    head_start_command: str = \
        "python -m ray_tpu.scripts start --head"
    worker_start_command: str = \
        "python -m ray_tpu.scripts start --address={head_address}"
    stop_command: str = "python -m ray_tpu.scripts stop"
    # Docker mode (reference: command_runner.py DockerCommandRunner):
    # {"image": ..., "container_name": ..., "run_options": [...]} — node
    # commands exec inside the container; file mounts docker-cp in.
    docker: Dict[str, Any] = field(default_factory=dict)
    # Per-node update retries before a node is declared failed and (for
    # docker/provider nodes) replaced (reference: updater.py).
    update_retries: int = 2

    @classmethod
    def load(cls, path: str) -> "ClusterConfig":
        import yaml

        with open(path) as f:
            raw = yaml.safe_load(f) or {}
        known = {f_.name for f_ in cls.__dataclass_fields__.values()}
        unknown = set(raw) - known
        if unknown:
            raise ValueError(f"unknown cluster config keys: "
                             f"{sorted(unknown)}")
        if "cluster_name" not in raw:
            raise ValueError("cluster_name is required")
        return cls(**raw)


class CommandRunner:
    """Run commands / sync files on one node (reference:
    command_runner.py interface)."""

    def run(self, cmd: str, timeout: float = 600.0) -> str:
        raise NotImplementedError

    def sync_files(self, mounts: Dict[str, str]) -> None:
        raise NotImplementedError


class LocalCommandRunner(CommandRunner):
    """Commands on this host (test tier / single-machine clusters)."""

    def __init__(self, env: Optional[Dict[str, str]] = None):
        self._env = {**os.environ, **(env or {})}

    def run(self, cmd: str, timeout: float = 600.0) -> str:
        proc = subprocess.run(cmd, shell=True, capture_output=True,
                              text=True, timeout=timeout, env=self._env)
        if proc.returncode != 0:
            raise RuntimeError(
                f"command failed ({proc.returncode}): {cmd}\n"
                f"{proc.stderr[-2000:]}")
        return proc.stdout

    def sync_files(self, mounts: Dict[str, str]) -> None:
        import shutil

        for remote, local in mounts.items():
            remote = os.path.expanduser(remote)
            local = os.path.expanduser(local)
            if os.path.abspath(remote) == os.path.abspath(local):
                continue
            os.makedirs(os.path.dirname(remote) or "/", exist_ok=True)
            if os.path.isdir(local):
                # Delta mirror (deletes removed files) when rsync exists;
                # plain copy otherwise (reference: updater rsync-up).
                try:
                    from ray_tpu.autoscaler.updater import rsync

                    rsync(local.rstrip("/") + "/", remote)
                    continue
                except FileNotFoundError:
                    pass
                except Exception as e:
                    logger.debug("rsync failed (%s); copytree fallback", e)
                shutil.copytree(local, remote, dirs_exist_ok=True)
            else:
                shutil.copy2(local, remote)


class SSHCommandRunner(CommandRunner):
    """ssh/scp against a real machine (reference: SSHCommandRunner)."""

    SSH_OPTS = ["-o", "StrictHostKeyChecking=no",
                "-o", "UserKnownHostsFile=/dev/null",
                "-o", "ConnectTimeout=10",
                "-o", "LogLevel=ERROR"]

    def __init__(self, ip: str, auth: Dict[str, Any]):
        self.ip = ip
        self.user = auth.get("ssh_user", os.environ.get("USER", "root"))
        self.key = auth.get("ssh_private_key")

    def _ssh_base(self) -> List[str]:
        base = ["ssh"] + self.SSH_OPTS
        if self.key:
            base += ["-i", os.path.expanduser(self.key)]
        return base + [f"{self.user}@{self.ip}"]

    def run(self, cmd: str, timeout: float = 600.0) -> str:
        argv = self._ssh_base() + [cmd]
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(
                f"[{self.ip}] command failed ({proc.returncode}): {cmd}\n"
                f"{proc.stderr[-2000:]}")
        return proc.stdout

    def sync_files(self, mounts: Dict[str, str]) -> None:
        for remote, local in mounts.items():
            local = os.path.expanduser(local)
            # rsync delta mirroring over ssh (reference: updater.py
            # rsync up) — only changed files travel; removed files are
            # deleted remotely. scp -r fallback when rsync is missing.
            try:
                from ray_tpu.autoscaler.updater import rsync

                src = local.rstrip("/") + "/" if os.path.isdir(local) \
                    else local
                rsync(src, f"{self.user}@{self.ip}:{remote}",
                      ssh_argv=self._ssh_base()[:-1])
                continue
            except FileNotFoundError:
                pass
            except Exception as e:
                logger.debug("[%s] rsync failed (%s); scp fallback",
                             self.ip, e)
            scp = ["scp", "-r"] + self.SSH_OPTS
            if self.key:
                scp += ["-i", os.path.expanduser(self.key)]
            scp += [local, f"{self.user}@{self.ip}:{remote}"]
            proc = subprocess.run(scp, capture_output=True, text=True,
                                  timeout=600)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"[{self.ip}] scp failed: {proc.stderr[-1000:]}")


def _runner_for(config: ClusterConfig, ip: str,
                docker_tag: str = "") -> CommandRunner:
    ptype = config.provider.get("type", "local")
    if ptype == "local" and ip in ("127.0.0.1", "localhost"):
        base: CommandRunner = LocalCommandRunner()
    else:
        base = SSHCommandRunner(ip, config.auth)
    if config.docker.get("image"):
        from ray_tpu.autoscaler.updater import DockerCommandRunner

        return DockerCommandRunner(
            base, config.docker,
            docker_tag or f"{config.cluster_name}_{ip.replace('.', '_')}")
    return base


def _state_path(cluster_name: str) -> str:
    d = os.path.expanduser("~/.ray_tpu")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"cluster-{cluster_name}.json")


def create_or_update_cluster(config_path: str) -> Dict[str, Any]:
    """`ray_tpu up`: bring the head up (files → setup → start), then the
    statically-listed workers (reference: commands.py
    create_or_update_cluster + NodeUpdater)."""
    config = ClusterConfig.load(config_path)
    head_ip = config.provider.get("head_ip", "127.0.0.1")
    runner = _runner_for(config, head_ip)
    logger.info("[%s] syncing files to head %s", config.cluster_name,
                head_ip)
    runner.sync_files(config.file_mounts)
    for cmd in config.setup_commands:
        logger.info("[%s] setup: %s", config.cluster_name, cmd)
        runner.run(cmd)
    logger.info("[%s] starting head: %s", config.cluster_name,
                config.head_start_command)
    # Idempotent up: reuse a live head only when it belongs to THIS
    # cluster (our recorded state matches); a foreign cluster on the
    # same host is an error, not something to adopt. A stale address
    # file (dead pid) is cleared; a head still booting (start process
    # alive, no address file yet) is waited on, not double-started.
    prior = {}
    if os.path.exists(_state_path(config.cluster_name)):
        with open(_state_path(config.cluster_name)) as f:
            prior = json.load(f)
    head_info = None
    try:
        head_info = json.loads(runner.run(f"cat {ADDRESS_FILE}"))
    except Exception:
        head_info = None
    if head_info is not None:
        alive = runner.run(
            f"kill -0 {head_info['pid']} 2>/dev/null && echo yes || "
            f"echo no").strip() == "yes"
        if alive and prior.get("head_address") == head_info["address"]:
            logger.info("[%s] head already running at %s",
                        config.cluster_name, head_info["address"])
        elif alive:
            raise RuntimeError(
                f"a different cluster's head is already running on "
                f"{head_ip} (address {head_info['address']}); bring it "
                f"down first")
        else:
            runner.run(f"rm -f {ADDRESS_FILE}")
            _start_detached(runner, config.head_start_command, "head")
    else:
        # [.] keeps the probe's own shell cmdline from matching.
        booting = runner.run(
            "pgrep -f 'ray_tpu[.]scripts start --head' >/dev/null && "
            "echo yes || echo no").strip() == "yes"
        if not booting:
            _start_detached(runner, config.head_start_command, "head")
        else:
            # Possibly a head still booting — give it a bounded window;
            # a wedged leftover process must not stall `up` forever.
            logger.info("[%s] a head process exists; waiting for it",
                        config.cluster_name)
            try:
                head_address = _wait_head_address(runner, timeout_s=30)
            except RuntimeError:
                raise RuntimeError(
                    f"a 'start --head' process exists on {head_ip} but "
                    f"never wrote {ADDRESS_FILE}; clean it up (e.g. "
                    f"`ray_tpu down` or kill it) and retry `up`")
    # `ray_tpu start --head` stays resident and writes the address file;
    # poll it for the gcs address (workers + state need it).
    head_address = _wait_head_address(runner)
    # Workers go through the per-node update state machine (reference:
    # updater.py NodeUpdater): wait → sync → setup → start, with retry +
    # replacement; `up` converges even when some nodes fail.
    from ray_tpu.autoscaler.updater import FAILED, NodeUpdater

    workers: List[str] = []
    node_updates: List[Dict[str, Any]] = []
    for ip in config.provider.get("worker_ips", []):
        wrunner = _runner_for(config, ip)

        def replace(ip=ip, wrunner=wrunner):
            # Fresh state for the retry: recreate the container in docker
            # mode (a half-set-up container is torn down), fresh runner
            # otherwise.
            stop = getattr(wrunner, "stop_container", None)
            if stop is not None:
                stop()
            return _runner_for(config, ip)

        upd = NodeUpdater(
            ip=ip, runner=wrunner, file_mounts=config.file_mounts,
            setup_commands=config.setup_commands,
            start_command=config.worker_start_command.format(
                head_address=head_address),
            tag=f"worker-{ip}",
            max_update_retries=config.update_retries,
            replace_node=replace,
            start_detached=_start_detached)
        status = upd.update()
        node_updates.append(upd.summary())
        if status == FAILED:
            logger.error("[%s] worker %s failed to update after %d "
                         "attempts: %s", config.cluster_name, ip,
                         upd.attempts, upd.error)
        else:
            workers.append(ip)
    state = {"cluster_name": config.cluster_name, "head_ip": head_ip,
             "head_address": head_address, "workers": workers,
             "node_updates": node_updates,
             "config_path": os.path.abspath(config_path)}
    with open(_state_path(config.cluster_name), "w") as f:
        json.dump(state, f)
    return state


# Written by `start --head` on the target host (single head per host).
from ray_tpu.scripts.cli import ADDRESS_FILE  # noqa: E402


def _start_detached(runner: CommandRunner, cmd: str, tag: str) -> None:
    """`ray_tpu start` stays resident (SIGTERM tears the node down);
    launch it as a detached daemon, logging under ~/.ray_tpu."""
    log = f"~/.ray_tpu/{tag}.log"
    runner.run("mkdir -p ~/.ray_tpu && nohup " + cmd +
               f" > {log} 2>&1 < /dev/null & echo started")


def _wait_head_address(runner: CommandRunner,
                       timeout_s: float = 90.0) -> str:
    import time

    deadline = time.monotonic() + timeout_s
    last = ""
    while time.monotonic() < deadline:
        try:
            out = runner.run(f"cat {ADDRESS_FILE}")
            return json.loads(out)["address"]
        except Exception as e:
            last = str(e)
            time.sleep(1.0)
    raise RuntimeError(f"head never wrote {ADDRESS_FILE}: {last}")


def teardown_cluster(config_path: str) -> None:
    """`ray_tpu down`: stop workers then the head."""
    config = ClusterConfig.load(config_path)
    state_file = _state_path(config.cluster_name)
    state = {}
    if os.path.exists(state_file):
        with open(state_file) as f:
            state = json.load(f)
    for ip in state.get("workers",
                        config.provider.get("worker_ips", [])):
        try:
            _runner_for(config, ip).run(config.stop_command)
        except Exception as e:
            logger.warning("worker %s stop failed: %s", ip, e)
    head_ip = state.get("head_ip",
                        config.provider.get("head_ip", "127.0.0.1"))
    try:
        _runner_for(config, head_ip).run(config.stop_command)
    except Exception as e:
        logger.warning("head %s stop failed (already down?): %s",
                       head_ip, e)
    if os.path.exists(state_file):
        os.remove(state_file)


def exec_on_cluster(config_path: str, cmd: str,
                    all_nodes: bool = False) -> str:
    """`ray_tpu exec` (the scriptable core of `attach`): run a command
    on the head (or every node)."""
    config = ClusterConfig.load(config_path)
    state_file = _state_path(config.cluster_name)
    state = {}
    if os.path.exists(state_file):
        with open(state_file) as f:
            state = json.load(f)
    head_ip = state.get("head_ip",
                        config.provider.get("head_ip", "127.0.0.1"))
    out = _runner_for(config, head_ip).run(cmd)
    if all_nodes:
        for ip in state.get("workers",
                            config.provider.get("worker_ips", [])):
            out += _runner_for(config, ip).run(cmd)
    return out


def attach_command(config_path: str) -> List[str]:
    """argv for an interactive shell on the head (`ray_tpu attach`)."""
    config = ClusterConfig.load(config_path)
    head_ip = config.provider.get("head_ip", "127.0.0.1")
    runner = _runner_for(config, head_ip)
    if isinstance(runner, SSHCommandRunner):
        return runner._ssh_base()
    return [os.environ.get("SHELL", "/bin/bash")]
