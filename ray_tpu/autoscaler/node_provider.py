"""NodeProvider plugin interface + built-in providers.

Reference: python/ray/autoscaler/node_provider.py (NodeProvider ABC) and
_private/fake_multi_node/node_provider.py:237 (FakeMultiNodeProvider —
"launches" nodes as local processes, the workhorse for autoscaler tests
without a cloud). Cloud providers (GCE TPU pods) implement the same
interface.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Minimal provider contract: create/terminate/list typed nodes."""

    def __init__(self, provider_config: Dict[str, Any]):
        self.provider_config = provider_config

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def node_tags(self, provider_node_id: str) -> Dict[str, str]:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class FakeMultiNodeProvider(NodeProvider):
    """Launches raylet processes on this machine with the resource shape
    declared per node type — real control plane, simulated hardware.

    provider_config: {"gcs_address": ..., "node_types": {name:
    {"resources": {...}, "max_workers": N}}}.
    """

    def __init__(self, provider_config: Dict[str, Any]):
        super().__init__(provider_config)
        from ray_tpu._private.cluster_utils import Cluster

        self._gcs_address = provider_config["gcs_address"]
        self._cluster = Cluster(_existing_address=self._gcs_address)
        self._nodes: Dict[str, Any] = {}
        self._tags: Dict[str, Dict[str, str]] = {}
        self._lock = threading.Lock()

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return list(self._nodes)

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        cfg = self.provider_config["node_types"][node_type]
        created = []
        for _ in range(count):
            pid = f"fake-{node_type}-{uuid.uuid4().hex[:8]}"
            node = self._cluster.add_node(
                resources=dict(cfg.get("resources", {})),
                slice_id=cfg.get("slice_id", ""))
            with self._lock:
                self._nodes[pid] = node
                self._tags[pid] = {"node_type": node_type,
                                   "launch_time": str(time.time())}
            created.append(pid)
        return created

    def terminate_node(self, provider_node_id: str) -> None:
        with self._lock:
            node = self._nodes.pop(provider_node_id, None)
            self._tags.pop(provider_node_id, None)
        if node is not None:
            self._cluster.remove_node(node)

    def node_tags(self, provider_node_id: str) -> Dict[str, str]:
        with self._lock:
            return dict(self._tags.get(provider_node_id, {}))

    def shutdown(self) -> None:
        for pid in self.non_terminated_nodes():
            self.terminate_node(pid)
