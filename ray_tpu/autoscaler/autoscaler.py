"""StandardAutoscaler + Monitor — demand-driven node scaling.

Reference: python/ray/autoscaler/_private/monitor.py:126 (Monitor polls
GCS load), autoscaler.py:172 (StandardAutoscaler reconcile loop), and
resource_demand_scheduler.py (bin-packing pending demands onto node
types). TPU-first difference: a node type may declare a slice topology;
slice-typed groups scale atomically (all hosts of a slice or none) since
a partial slice cannot form an ICI mesh.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)


def _fits(demand: Dict[str, float], free: Dict[str, float]) -> bool:
    return all(free.get(k, 0.0) >= v for k, v in demand.items() if v > 0)


def _consume(demand: Dict[str, float], free: Dict[str, float]) -> None:
    for k, v in demand.items():
        free[k] = free.get(k, 0.0) - v


class ResourceDemandScheduler:
    """Bin-packs unmet demands onto the cheapest set of new nodes.

    Reference: resource_demand_scheduler.py — first fit onto existing
    free capacity, then first-fit-decreasing onto hypothetical nodes of
    each type up to its max_workers."""

    def __init__(self, node_types: Dict[str, dict]):
        self.node_types = node_types

    def get_nodes_to_launch(
            self, pending_demands: List[Dict[str, float]],
            cluster_free: List[Dict[str, float]],
            current_counts: Dict[str, int]) -> Dict[str, int]:
        free = [dict(f) for f in cluster_free]
        unmet: List[Dict[str, float]] = []
        for demand in pending_demands:
            placed = False
            for node_free in free:
                if _fits(demand, node_free):
                    _consume(demand, node_free)
                    placed = True
                    break
            if not placed:
                unmet.append(demand)
        if not unmet:
            return {}

        to_launch: Dict[str, int] = {}
        hypothetical: List[Tuple[str, Dict[str, float]]] = []
        # Largest demands first — classic FFD packing.
        for demand in sorted(unmet,
                             key=lambda d: -sum(d.values())):
            placed = False
            for _, node_free in hypothetical:
                if _fits(demand, node_free):
                    _consume(demand, node_free)
                    placed = True
                    break
            if placed:
                continue
            for type_name, cfg in self.node_types.items():
                resources = cfg.get("resources", {})
                launched = current_counts.get(type_name, 0) + \
                    to_launch.get(type_name, 0)
                group = int(cfg.get("slice_hosts", 1))
                # A whole slice group must fit under max_workers — no
                # partial slices.
                if launched + group > cfg.get("max_workers", 10):
                    continue
                if _fits(demand, resources):
                    to_launch[type_name] = to_launch.get(type_name, 0) + \
                        group
                    for _ in range(group):
                        hypothetical.append((type_name, dict(resources)))
                    _consume(demand, hypothetical[-group][1])
                    placed = True
                    break
            if not placed:
                logger.warning("infeasible demand (no node type fits): %s",
                               demand)
        return to_launch


class StandardAutoscaler:
    def __init__(self, provider: NodeProvider,
                 node_types: Dict[str, dict],
                 idle_timeout_s: float = 60.0,
                 max_launch_batch: int = 8):
        self.provider = provider
        self.node_types = node_types
        self.scheduler = ResourceDemandScheduler(node_types)
        self.idle_timeout_s = idle_timeout_s
        self.max_launch_batch = max_launch_batch
        self._idle_since: Dict[str, float] = {}

    def _current_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for pid in self.provider.non_terminated_nodes():
            t = self.provider.node_tags(pid).get("node_type", "?")
            counts[t] = counts.get(t, 0) + 1
        return counts

    def update(self, state: Dict[str, Any]) -> Dict[str, int]:
        """One reconcile pass against the GCS autoscaler state; returns
        node types launched this round."""
        demands = state.get("pending_demands", [])
        free = [n["resources_available"] for n in state.get("nodes", [])]
        counts = self._current_counts()
        to_launch = dict(self.scheduler.get_nodes_to_launch(
            demands, free, counts))
        # Standing capacity requests (sdk.request_resources) are a floor
        # over TOTAL capacity: pack them against resources_total so a
        # busy-but-big-enough cluster doesn't over-scale past the floor.
        requested = state.get("requested_bundles", [])
        if requested:
            total = [dict(n["resources_total"])
                     for n in state.get("nodes", [])]
            for t, c in self.scheduler.get_nodes_to_launch(
                    requested, total, counts).items():
                to_launch[t] = max(to_launch.get(t, 0), c)
        for type_name, count in to_launch.items():
            # Cap the launch batch in whole slice groups — a truncated
            # group would be a partial slice that can't form an ICI mesh.
            group = int(self.node_types.get(type_name, {})
                        .get("slice_hosts", 1))
            max_batch = max(group, (self.max_launch_batch // group) * group)
            count = min(count, max_batch)
            logger.info("autoscaler launching %d x %s", count, type_name)
            self.provider.create_node(type_name, count)
        self._terminate_idle(state)
        return to_launch

    def _terminate_idle(self, state: Dict[str, Any]) -> None:
        """Scale down provider nodes idle past the timeout (reference:
        StandardAutoscaler idle node termination). Task demand resets
        idle timers. A standing request_resources floor keeps ONLY the
        capacity the floor needs warm — nodes beyond it still scale
        down (a 1-CPU floor must not pin 100 idle workers forever)."""
        if state.get("pending_demands"):
            self._idle_since.clear()
            return
        # Per-type node counts the standing floor requires when packed
        # onto fresh nodes of that type.
        keep_floor: Dict[str, int] = {}
        if state.get("requested_bundles"):
            keep_floor = dict(self.scheduler.get_nodes_to_launch(
                state["requested_bundles"], [], {}))
        now = time.monotonic()
        # Map provider nodes to GCS nodes via node_type resources —
        # the fake provider owns its nodes, so just track idleness of
        # the whole provider fleet conservatively: only terminate when
        # the cluster reports every provider-launched node idle.
        idle_flags = {n["node_id"]: n["idle"]
                      for n in state.get("nodes", [])}
        all_idle = all(idle_flags.values()) if idle_flags else False
        for pid in self.provider.non_terminated_nodes():
            if not all_idle:
                self._idle_since.pop(pid, None)
                continue
            node_type = self.provider.node_tags(pid).get("node_type", "?")
            if keep_floor.get(node_type, 0) > 0:
                keep_floor[node_type] -= 1  # held warm by the floor
                self._idle_since.pop(pid, None)
                continue
            since = self._idle_since.setdefault(pid, now)
            if now - since > self.idle_timeout_s:
                logger.info("terminating idle node %s", pid)
                self.provider.terminate_node(pid)
                self._idle_since.pop(pid, None)


class Monitor:
    """Polls GCS autoscaler state and drives StandardAutoscaler.

    Reference: monitor.py:126 — runs beside the GCS on the head node."""

    def __init__(self, provider: NodeProvider, node_types: Dict[str, dict],
                 poll_interval_s: float = 1.0, **autoscaler_kwargs):
        self.autoscaler = StandardAutoscaler(provider, node_types,
                                             **autoscaler_kwargs)
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _fetch_state(self) -> Dict[str, Any]:
        from ray_tpu._private.worker import global_worker

        return global_worker().gcs_call("autoscaler_state", {}) or {}

    def run_once(self) -> Dict[str, int]:
        return self.autoscaler.update(self._fetch_state())

    def start(self) -> None:
        def loop():
            while not self._stop.is_set():
                try:
                    self.run_once()
                except Exception:
                    logger.exception("autoscaler update failed")
                self._stop.wait(self.poll_interval_s)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="autoscaler-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5.0)
