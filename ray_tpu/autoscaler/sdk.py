"""Autoscaler SDK.

Reference: python/ray/autoscaler/sdk.py — ``request_resources`` lets an
application pin a capacity floor independent of current load: the
autoscaler scales up until the requested bundles COULD be placed and
keeps that capacity warm (idle scale-down is suppressed while a request
stands). Each call replaces the previous request; an empty call clears
it.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def request_resources(num_cpus: Optional[int] = None,
                      bundles: Optional[List[Dict[str, float]]] = None
                      ) -> None:
    """Pin a standing capacity request with the GCS.

    num_cpus=N is shorthand for N one-CPU bundles (reference
    semantics: a TOTAL the cluster must be able to place, not per
    node). Pass neither to clear the request."""
    from ray_tpu._private.worker import global_worker

    req: List[Dict[str, float]] = []
    if num_cpus:
        req.extend({"CPU": 1.0} for _ in range(int(num_cpus)))
    if bundles:
        req.extend(dict(b) for b in bundles)
    global_worker().gcs_call("request_resources", {"bundles": req})
