"""ray_tpu.autoscaler — demand-driven cluster scaling.

Parity target: python/ray/autoscaler/ (Monitor, StandardAutoscaler,
ResourceDemandScheduler, NodeProvider plugins incl. FakeMultiNodeProvider
for cloudless tests). TPU-first: slice-typed node groups scale atomically.
"""

from ray_tpu.autoscaler.autoscaler import (Monitor, ResourceDemandScheduler,
                                           StandardAutoscaler)
from ray_tpu.autoscaler.gce import (GceClient, GCETPUNodeProvider,
                                    MockGceClient)
from ray_tpu.autoscaler.node_provider import (FakeMultiNodeProvider,
                                              NodeProvider)
from ray_tpu.autoscaler.sdk import request_resources

__all__ = [
    "request_resources",
    "Monitor",
    "StandardAutoscaler",
    "ResourceDemandScheduler",
    "NodeProvider",
    "FakeMultiNodeProvider",
    "GceClient",
    "GCETPUNodeProvider",
    "MockGceClient",
]
