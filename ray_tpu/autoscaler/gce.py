"""GCE TPU-VM node provider — slice-atomic scale-up.

Reference: python/ray/autoscaler/_private/gcp/node_provider.py (the GCP
provider) + python/ray/_private/accelerators/tpu.py:381 (the
TPU-{pod_type}-head resource that makes a whole slice schedulable as one
unit). The GCE TPU API creates a multi-host slice as ONE resource
(`tpu.googleapis.com/v2 nodes.create` with acceleratorType like
"v5litepod-16"), so scale-up here issues exactly one API call per slice
— never per-host VM creates, never a partial slice.

The HTTP transport is injected (``compute_client``): production wires a
googleapis client; tests (and hermetic images) wire MockGceClient, which
implements the same request/response shapes.
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)

# acceleratorType suffix units differ by generation: v2/v3 count
# TensorCores (8/host), v4/v5p count TensorCores (2/chip x 4 chips =
# 8/host), v5litepod/v6e count CHIPS (4/host). Reference: tpu.py's
# chips-per-host bounds + the TPU API acceleratorType naming.
_SUFFIX_UNITS_PER_HOST = {"v2": 8, "v3": 8, "v4": 8, "v5p": 8,
                          "v5litepod": 4, "v6e": 4}


def slice_hosts(accelerator_type: str) -> int:
    """'v5litepod-16' -> 16 chips / 4 per host = 4 hosts;
    'v4-16' -> 16 cores / 8 per host = 2 hosts."""
    gen, _, suffix = accelerator_type.rpartition("-")
    per_host = _SUFFIX_UNITS_PER_HOST.get(gen, 4)
    return max(1, int(suffix) // per_host)


class GceClient:
    """Transport interface (the googleapis subset the provider needs)."""

    def create_tpu_node(self, name: str, accelerator_type: str,
                        runtime_version: str, zone: str,
                        labels: Dict[str, str]) -> Dict[str, Any]:
        raise NotImplementedError

    def list_tpu_nodes(self, zone: str) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def delete_tpu_node(self, name: str, zone: str) -> None:
        raise NotImplementedError


class MockGceClient(GceClient):
    """In-memory stand-in implementing the same shapes (tests / CI)."""

    def __init__(self):
        self.nodes: Dict[str, Dict[str, Any]] = {}
        self.create_calls: List[Dict[str, Any]] = []
        self.delete_calls: List[str] = []

    def create_tpu_node(self, name, accelerator_type, runtime_version,
                        zone, labels):
        self.create_calls.append({
            "name": name, "acceleratorType": accelerator_type,
            "runtimeVersion": runtime_version, "zone": zone,
            "labels": dict(labels)})
        n_hosts = slice_hosts(accelerator_type)
        node = {
            "name": name,
            "acceleratorType": accelerator_type,
            "state": "READY",
            "labels": dict(labels),
            "networkEndpoints": [
                {"ipAddress": f"10.0.{len(self.nodes)}.{i}"}
                for i in range(n_hosts)],
        }
        self.nodes[name] = node
        return node

    def list_tpu_nodes(self, zone):
        return list(self.nodes.values())

    def delete_tpu_node(self, name, zone):
        self.delete_calls.append(name)
        self.nodes.pop(name, None)


class GCETPUNodeProvider(NodeProvider):
    """Slices are the unit of creation/termination; provider node ids are
    '<slice-name>/<worker-index>' so the autoscaler sees per-host nodes
    while the cloud API sees whole slices."""

    def __init__(self, provider_config: Dict[str, Any],
                 compute_client: Optional[GceClient] = None):
        super().__init__(provider_config)
        self.zone = provider_config.get("zone", "us-central2-b")
        self.runtime_version = provider_config.get(
            "runtime_version", "tpu-ubuntu2204-base")
        self.cluster_name = provider_config.get("cluster_name", "ray-tpu")
        self.client = compute_client or self._default_client()
        self._deleted: set = set()  # slices deleted this provider's life
        self._node_cache: Dict[str, Dict[str, Any]] = {}
        # ONE source of truth for slice size: derive slice_hosts from the
        # accelerator type so the demand scheduler, launch batching, and
        # create_node can never disagree (a mismatch would wedge scale-up
        # on the slice-atomic check forever).
        for cfg in (provider_config.get("node_types") or {}).values():
            accel = cfg.get("accelerator_type")
            if accel:
                cfg["slice_hosts"] = slice_hosts(accel)

    def _default_client(self) -> GceClient:
        raise RuntimeError(
            "no googleapis client available in this environment; pass "
            "compute_client= (MockGceClient for tests)")

    # ---- NodeProvider API ----
    def non_terminated_nodes(self) -> List[str]:
        out = []
        self._node_cache = {}
        for node in self.client.list_tpu_nodes(self.zone):
            if node.get("state") not in ("READY", "CREATING"):
                continue
            if node.get("labels", {}).get("ray-cluster") != \
                    self.cluster_name:
                continue
            self._node_cache[node["name"]] = node
            # CREATING slices have no networkEndpoints yet — count their
            # full host complement or max_workers caps undercount and
            # duplicate slices launch during the minutes-long create.
            n_hosts = (len(node["networkEndpoints"])
                       if node.get("networkEndpoints")
                       else slice_hosts(node["acceleratorType"]))
            for i in range(n_hosts):
                out.append(f"{node['name']}/{i}")
        return out

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        """count is in HOSTS (the autoscaler's unit); hosts are grouped
        into whole slices — one API call per slice."""
        cfg = (self.provider_config.get("node_types") or {}).get(
            node_type, {})
        accelerator_type = cfg.get("accelerator_type")
        if not accelerator_type:
            raise ValueError(
                f"node type {node_type!r} has no accelerator_type")
        hosts_per_slice = slice_hosts(accelerator_type)
        if count % hosts_per_slice:
            raise ValueError(
                f"slice-atomic violation: asked for {count} hosts of "
                f"{accelerator_type} ({hosts_per_slice} hosts/slice) — "
                "scale-up must be whole slices")
        created: List[str] = []
        for _ in range(count // hosts_per_slice):
            name = f"{self.cluster_name}-{node_type}-{uuid.uuid4().hex[:8]}"
            self.client.create_tpu_node(
                name, accelerator_type, self.runtime_version, self.zone,
                labels={"ray-cluster": self.cluster_name,
                        "ray-node-type": node_type})
            # Host count from the accelerator type, NOT networkEndpoints:
            # a real create returns CREATING with no endpoints yet.
            created.extend(f"{name}/{i}"
                           for i in range(hosts_per_slice))
        return created

    def terminate_node(self, provider_node_id: str) -> None:
        """Terminating ANY host of a slice deletes the whole slice (a
        partial slice cannot form an ICI mesh). Idempotent across the
        slice's host ids — the autoscaler iterates per-host.

        rsplit, not split: real v2 API node names are FULL resource
        paths (projects/{p}/locations/{zone}/nodes/{id}) — only the
        trailing /<host-index> is ours."""
        slice_name = provider_node_id.rsplit("/", 1)[0]
        if slice_name in self._deleted:
            return
        # Mark deleted only on success: a transient API failure must stay
        # retryable or the slice leaks (billed) forever.
        self.client.delete_tpu_node(slice_name, self.zone)
        self._deleted.add(slice_name)

    def node_tags(self, provider_node_id: str) -> Dict[str, str]:
        slice_name = provider_node_id.rsplit("/", 1)[0]
        node = self._node_cache.get(slice_name)
        if node is None:  # cache refreshed by non_terminated_nodes
            self.non_terminated_nodes()
            node = self._node_cache.get(slice_name)
        if node is None:
            return {}
        return {
            "node_type": node["labels"].get("ray-node-type", "?"),
            "slice_name": slice_name,
            "accelerator_type": node["acceleratorType"],
        }

    def shutdown(self) -> None:
        pass
