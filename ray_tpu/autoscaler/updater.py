"""Per-node update state machine + docker command runner.

Reference: python/ray/autoscaler/_private/updater.py (NodeUpdater:
wait-ready → rsync file mounts → setup commands → start command, with
per-phase status tracking) and command_runner.py:DockerCommandRunner
(commands exec inside a container on the node; files sync to the host
then into the container).

The launcher (launcher.py) drives one ``NodeUpdater`` per node; a node
whose update FAILS after its retry budget is torn down and REPLACED with
a fresh updater attempt (fresh container/process state) — `up` converges
after partial failure instead of leaving a half-set-up node behind.
"""

from __future__ import annotations

import logging
import shlex
import subprocess
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.autoscaler.launcher import CommandRunner

logger = logging.getLogger(__name__)

# Node update lifecycle (reference: updater.py STATUS_*).
WAITING = "waiting-for-ssh"
SYNCING = "syncing-files"
SETTING_UP = "setting-up"
STARTING = "starting-ray"
RUNNING = "up-to-date"
FAILED = "update-failed"


class DockerCommandRunner(CommandRunner):
    """Run node commands inside a docker container (reference:
    command_runner.py:DockerCommandRunner). Wraps a base runner (local or
    ssh) that talks to the HOST: the container is created on first use,
    commands `docker exec` into it, and file mounts sync host-side then
    `docker cp` into the container."""

    def __init__(self, base: CommandRunner, docker: Dict[str, Any],
                 tag: str):
        self.base = base
        self.image = docker.get("image", "")
        self.container = docker.get(
            "container_name", f"ray_tpu_{tag}").replace("/", "_")
        self.run_options = docker.get("run_options", [])
        self._ensured = False

    def _ensure_container(self) -> None:
        if self._ensured:
            return
        probe = self.base.run(
            f"docker inspect -f '{{{{.State.Running}}}}' "
            f"{shlex.quote(self.container)} 2>/dev/null || echo absent"
        ).strip()
        if probe != "true":
            self.base.run(
                f"docker rm -f {shlex.quote(self.container)} "
                f">/dev/null 2>&1 || true")
            opts = " ".join(self.run_options)
            self.base.run(
                f"docker run -d --name {shlex.quote(self.container)} "
                f"{opts} {shlex.quote(self.image)} sleep infinity")
        self._ensured = True

    def run(self, cmd: str, timeout: float = 600.0) -> str:
        self._ensure_container()
        return self.base.run(
            f"docker exec {shlex.quote(self.container)} "
            f"/bin/sh -c {shlex.quote(cmd)}", timeout=timeout)

    def sync_files(self, mounts: Dict[str, str]) -> None:
        if not mounts:
            return
        self._ensure_container()
        # Host-side mirror first (rsync delta over ssh for remote nodes),
        # then copy into the container.
        self.base.sync_files(mounts)
        for remote, _local in mounts.items():
            self.base.run(
                f"docker exec {shlex.quote(self.container)} "
                f"mkdir -p {shlex.quote(remote)} && "
                f"docker cp {shlex.quote(remote)}/. "
                f"{shlex.quote(self.container)}:{shlex.quote(remote)}")

    def stop_container(self) -> None:
        try:
            self.base.run(
                f"docker rm -f {shlex.quote(self.container)} "
                f">/dev/null 2>&1 || true")
        except Exception:
            pass
        self._ensured = False


@dataclass
class NodeUpdater:
    """Drives one node through the update lifecycle with retries and
    replacement (reference: updater.py NodeUpdater.run)."""

    ip: str
    runner: CommandRunner
    file_mounts: Dict[str, str]
    setup_commands: List[str]
    start_command: str
    tag: str = "node"
    max_update_retries: int = 2
    retry_backoff_s: float = 1.0
    # Called between failed attempts to get a FRESH node/runner (tear
    # down the broken one, provision a replacement). Returning None keeps
    # the current runner (plain retry).
    replace_node: Optional[Callable[[], Optional[CommandRunner]]] = None
    start_detached: Optional[Callable[[CommandRunner, str, str],
                                      None]] = None

    status: str = WAITING
    error: str = ""
    phase_times: Dict[str, float] = field(default_factory=dict)
    attempts: int = 0

    def _phase(self, status: str, fn: Callable[[], None]) -> None:
        self.status = status
        t0 = time.monotonic()
        try:
            fn()
        finally:
            self.phase_times[status] = round(
                time.monotonic() - t0, 3)

    def _attempt(self) -> None:
        self._phase(WAITING, self._wait_ready)
        self._phase(SYNCING,
                    lambda: self.runner.sync_files(self.file_mounts))
        self._phase(SETTING_UP, self._setup)
        self._phase(STARTING, self._start)
        self.status = RUNNING

    def _wait_ready(self, timeout_s: float = 60.0) -> None:
        """Wait for the node to answer a trivial command (ssh up,
        container startable)."""
        deadline = time.monotonic() + timeout_s
        last = ""
        while time.monotonic() < deadline:
            try:
                self.runner.run("true", timeout=15.0)
                return
            except Exception as e:
                last = str(e)
                time.sleep(2.0)
        raise RuntimeError(f"node {self.ip} never became reachable: {last}")

    def _setup(self) -> None:
        for cmd in self.setup_commands:
            logger.info("[%s] setup: %s", self.tag, cmd)
            self.runner.run(cmd)

    def _start(self) -> None:
        if self.start_detached is not None:
            self.start_detached(self.runner, self.start_command, self.tag)
        else:
            self.runner.run(self.start_command)

    def update(self) -> str:
        """Run the lifecycle; on failure, replace the node (if a
        replacement hook is provided) and retry up to the budget.
        Returns the final status (RUNNING or FAILED)."""
        for attempt in range(self.max_update_retries + 1):
            self.attempts = attempt + 1
            try:
                self._attempt()
                self.error = ""
                return self.status
            except Exception as e:
                self.error = f"{self.status}: {e}"
                logger.warning("[%s] update attempt %d failed at %s: %s",
                               self.tag, self.attempts, self.status, e)
                if attempt >= self.max_update_retries:
                    break
                if self.replace_node is not None:
                    try:
                        fresh = self.replace_node()
                        if fresh is not None:
                            self.runner = fresh
                    except Exception as re:
                        logger.warning("[%s] node replacement failed: %s",
                                       self.tag, re)
                time.sleep(self.retry_backoff_s * (attempt + 1))
        self.status = FAILED
        return self.status

    def summary(self) -> Dict[str, Any]:
        return {"ip": self.ip, "status": self.status,
                "attempts": self.attempts, "error": self.error,
                "phase_times": self.phase_times}


def rsync(src: str, dst: str, ssh_argv: Optional[List[str]] = None,
          delete: bool = True, timeout: float = 600.0) -> None:
    """Delta file mirroring via rsync (reference: updater.py rsync up);
    falls back to the caller's copy strategy if rsync is unavailable."""
    argv = ["rsync", "-az"]
    if delete:
        argv.append("--delete")
    if ssh_argv:
        argv += ["-e", " ".join(ssh_argv)]
    argv += [src, dst]
    proc = subprocess.run(argv, capture_output=True, text=True,
                          timeout=timeout)
    if proc.returncode != 0:
        raise RuntimeError(f"rsync failed ({proc.returncode}): "
                           f"{proc.stderr[-1000:]}")
