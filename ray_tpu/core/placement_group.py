"""Placement groups: atomic multi-bundle resource reservation.

Equivalent of the reference's python/ray/util/placement_group.py
(``placement_group()`` :145, PlacementGroup handle :41) with strategies
PACK / SPREAD / STRICT_PACK / STRICT_SPREAD — plus the TPU-native **SLICE**
strategy: all bundles placed one-per-host on the hosts of a single TPU
slice, atomically, so an SPMD gang holds an intact ICI domain (this
subsumes the reference's `TPU-{pod}-head` + STRICT_SPREAD workaround,
python/ray/_private/accelerators/tpu.py:381).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu.core.ids import NodeID, PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD", "SLICE")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID,
                 bundles: Optional[List[Dict[str, float]]] = None):
        self.id = pg_id
        self.bundles = bundles or []

    def ready(self, timeout: float = 60.0) -> bool:
        """Block until created (reference returns an ObjectRef; here a
        blocking call with timeout — use wait() for polling)."""
        from ray_tpu._private.worker import global_worker

        r = global_worker().gcs_call(
            "wait_placement_group",
            {"pg_id": self.id.binary(), "timeout": timeout},
            timeout=timeout + 5)
        return bool(r.get("ok"))

    def wait(self, timeout_seconds: float = 60.0) -> bool:
        return self.ready(timeout_seconds)

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return self.bundles

    def bundle_locations(self) -> Dict[int, NodeID]:
        from ray_tpu._private.worker import global_worker

        view = global_worker().gcs_call(
            "get_placement_group", {"pg_id": self.id.binary()})
        if not view:
            return {}
        return {int(k): NodeID(v)
                for k, v in view["bundle_locations"].items()}

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles))


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "",
                    lifetime: Optional[str] = None) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"invalid strategy {strategy!r}; "
                         f"one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    from ray_tpu._private.worker import global_worker

    worker = global_worker()
    pg_id = PlacementGroupID.from_random()
    r = worker.gcs_call("create_placement_group", {
        "pg_id": pg_id.binary(),
        "bundles": bundles,
        "strategy": strategy,
        "name": name,
        "job_id": worker.core.job_id.binary(),
    })
    if not r.get("ok"):
        raise RuntimeError(r.get("error", "placement group creation failed"))
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu._private.worker import global_worker

    global_worker().gcs_call("remove_placement_group",
                             {"pg_id": pg.id.binary()})


def slice_placement_group(num_hosts: int, chips_per_host: int = 4,
                          cpus_per_host: float = 0.0) -> PlacementGroup:
    """Gang-reserve an entire TPU slice: one bundle per host, SLICE strategy.

    The TPU-native gang-scheduling entrypoint (SURVEY.md §7 step 5): all
    hosts of one slice or nothing.
    """
    bundle: Dict[str, float] = {"TPU": float(chips_per_host)}
    if cpus_per_host:
        bundle["CPU"] = cpus_per_host
    return placement_group([dict(bundle) for _ in range(num_hosts)],
                           strategy="SLICE")
