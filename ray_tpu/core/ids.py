"""Binary IDs for jobs, tasks, actors, objects, nodes, workers, placement groups.

TPU-native equivalent of the reference's ID system
(src/ray/common/id.h; python/ray/includes/unique_ids.pxi): fixed-size random
binary IDs with structured derivation (object IDs derive from the producing
task ID + return index, actor IDs embed the job ID) so ownership and lineage
can be recovered from the ID alone.
"""

from __future__ import annotations

import os
import struct
import threading

_NIL = b"\xff"

# Random-byte pool: os.urandom is a syscall (~60us with profiling, ~2us
# raw) and ID minting sits on the task submission hot path. Refill in
# 16 KiB slabs; reset after fork so children can't mint parents' IDs.
_rand_lock = threading.Lock()
_rand_pool = b""
_rand_off = 0


def _rand_bytes(n: int) -> bytes:
    global _rand_pool, _rand_off
    with _rand_lock:
        if _rand_off + n > len(_rand_pool):
            _rand_pool = os.urandom(max(n, 16384))
            _rand_off = 0
        out = _rand_pool[_rand_off:_rand_off + n]
        _rand_off += n
        return out


def _reset_rand_pool() -> None:
    global _rand_pool, _rand_off
    _rand_pool = b""
    _rand_off = 0


os.register_at_fork(after_in_child=_reset_rand_pool)


class BaseID:
    SIZE = 16
    __slots__ = ("_binary", "_hash")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._binary = binary
        self._hash = hash((type(self).__name__, binary))

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(_rand_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(_NIL * cls.SIZE)

    def is_nil(self) -> bool:
        return self._binary == _NIL * self.SIZE

    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._binary == self._binary

    def __lt__(self, other):
        return self._binary < other._binary

    def __repr__(self):
        return f"{type(self).__name__}({self._binary.hex()})"

    def __reduce__(self):
        return (type(self), (self._binary,))


class JobID(BaseID):
    SIZE = 4

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(struct.pack(">I", value))

    def int_value(self) -> int:
        return struct.unpack(">I", self._binary)[0]


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    """12 random bytes + 4-byte job id suffix."""

    SIZE = 16

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(_rand_bytes(cls.SIZE - JobID.SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._binary[-JobID.SIZE:])


class TaskID(BaseID):
    """16 random bytes + 4-byte job id; actor tasks embed the actor id."""

    SIZE = 20

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        return cls(b"\x00" * (cls.SIZE - JobID.SIZE) + job_id.binary())

    @classmethod
    def of(cls, job_id: JobID) -> "TaskID":
        return cls(_rand_bytes(cls.SIZE - JobID.SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._binary[-JobID.SIZE:])


class ObjectID(BaseID):
    """TaskID (20 bytes) + big-endian return index (4 bytes).

    Mirrors the reference's ObjectID = TaskID + index scheme
    (src/ray/common/id.h) so lineage (which task produced this object)
    is recoverable from the ID.
    """

    SIZE = 24

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack(">I", index))

    @classmethod
    def from_random(cls) -> "ObjectID":
        # Put objects: synthesize a fresh task id namespace.
        return cls(_rand_bytes(TaskID.SIZE) + struct.pack(">I", 0))

    def task_id(self) -> TaskID:
        return TaskID(self._binary[: TaskID.SIZE])

    def return_index(self) -> int:
        return struct.unpack(">I", self._binary[TaskID.SIZE:])[0]


class PlacementGroupID(BaseID):
    SIZE = 16


class ClusterID(BaseID):
    SIZE = 16


class _Counter:
    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
