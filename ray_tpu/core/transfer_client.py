"""ctypes binding for the native object-transfer plane.

Reference role: src/ray/object_manager/ (chunked push/pull). The raylet
starts one native transfer server over its shm store; pulls from remote
nodes stream store-to-store over raw TCP with no Python on the data path
(see _native/transfer.cpp).
"""

from __future__ import annotations

import ctypes
from typing import Optional

from ray_tpu._native.build import load_lib

_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = load_lib("ray_tpu_transfer")
        lib.obj_transfer_serve.argtypes = [ctypes.c_char_p,
                                           ctypes.POINTER(ctypes.c_void_p)]
        lib.obj_transfer_serve.restype = ctypes.c_int
        lib.obj_transfer_stop.argtypes = [ctypes.c_void_p]
        lib.obj_transfer_stop.restype = None
        lib.obj_transfer_fetch.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p]
        lib.obj_transfer_fetch.restype = ctypes.c_int
        _lib = lib
    return _lib


class TransferServer:
    """Serves local sealed objects to remote pullers (runs native threads
    inside the raylet process)."""

    def __init__(self, store_path: str):
        self._handle = ctypes.c_void_p()
        port = _load().obj_transfer_serve(store_path.encode(),
                                          ctypes.byref(self._handle))
        if port <= 0:
            raise OSError(-port, "obj_transfer_serve failed")
        self.port = port

    def stop(self) -> None:
        if self._handle:
            _load().obj_transfer_stop(self._handle)
            self._handle = ctypes.c_void_p()


FETCH_OK = 0
FETCH_REMOTE_MISS = 1
FETCH_ALREADY_LOCAL = 2


def fetch(store_path: str, host: str, port: int, object_id: bytes) -> int:
    """Blocking native pull of one object into the local store. Returns a
    FETCH_* code; raises OSError on I/O errors. Call from a thread
    executor — it blocks on the socket."""
    rc = _load().obj_transfer_fetch(store_path.encode(), host.encode(),
                                    int(port), object_id)
    if rc < 0:
        raise OSError(-rc, f"obj_transfer_fetch({host}:{port}) failed")
    return rc
