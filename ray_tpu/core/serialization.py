"""Serialization: cloudpickle + out-of-band buffers, zero-copy numpy views.

Equivalent of the reference's serialization stack
(python/ray/_private/serialization.py + vendored cloudpickle): pickle
protocol 5 with out-of-band buffer extraction so large numpy arrays are
written to the shared-memory object store without an intermediate copy and
deserialized as zero-copy views onto the store segment.

Wire layout of a serialized object:

    [u32 nbuf][u64 meta_len][meta pickle][pad][buf0][pad][buf1]...
    ...[u64 size0..sizeN-1][u32 nbuf]
      buffers are 64-byte aligned so numpy views are aligned; sizes live in a
      fixed-position trailer so deserialization never copies buffer bytes.

jax.Array values are device-fetched to numpy on serialize (the object store
is host memory); layers that must keep data on device ship it through
device-native channels (ray_tpu.dag) instead.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List

import cloudpickle

_ALIGN = 64
_HEADER = struct.Struct("<IQ")


def _aligned(pos: int) -> int:
    return (pos + _ALIGN - 1) & ~(_ALIGN - 1)


def _to_numpy_if_jax(value: Any) -> Any:
    # Device arrays are fetched to host for the object store. Avoid importing
    # jax unless the object actually came from it.
    mod = type(value).__module__
    if mod.startswith("jaxlib") or mod.startswith("jax"):
        import numpy as np

        try:
            return np.asarray(value)
        except Exception:
            return value
    return value


class SerializedObject:
    """A pickled value plus its out-of-band buffers, ready to write."""

    __slots__ = ("meta", "buffers", "total_size")

    def __init__(self, meta: bytes, buffers: List[memoryview]):
        self.meta = meta
        self.buffers = buffers
        size = _HEADER.size + len(meta)
        for b in buffers:
            size = _aligned(size) + b.nbytes
        self.total_size = size + 8 * len(buffers) + 4

    def write_to(self, dest: memoryview, native_write=None) -> None:
        """native_write(delta, src_addr, nbytes): GIL-dropping memcpy at
        byte offset delta of the destination object — used for large
        payload buffers so a 100-MiB put doesn't stall other threads."""
        _HEADER.pack_into(dest, 0, len(self.buffers), len(self.meta))
        pos = _HEADER.size
        dest[pos: pos + len(self.meta)] = self.meta
        pos += len(self.meta)
        sizes = []
        for b in self.buffers:
            pos = _aligned(pos)
            if native_write is not None and b.nbytes >= 1 << 20:
                import numpy as _np

                try:
                    src = _np.frombuffer(b, dtype=_np.uint8)
                except ValueError:  # non-contiguous: plain copy
                    dest[pos: pos + b.nbytes] = b
                else:
                    native_write(pos, src.ctypes.data, b.nbytes)
            else:
                dest[pos: pos + b.nbytes] = b
            sizes.append(b.nbytes)
            pos += b.nbytes
        n = len(sizes)
        if n:
            struct.pack_into(f"<{n}Q", dest, len(dest) - 4 - 8 * n, *sizes)
        struct.pack_into("<I", dest, len(dest) - 4, n)

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_size)
        self.write_to(memoryview(out))
        return bytes(out)


# Values built only from these types pickle identically under the stdlib
# C pickler and cloudpickle — and the C pickler skips cloudpickle's
# per-call Pickler construction (~10x on trivial task args/returns).
_PLAIN_TYPES = frozenset((int, float, bool, bytes, str, type(None)))


def _is_plain(value: Any, depth: int = 0) -> bool:
    if type(value) in _PLAIN_TYPES:
        return True
    if depth >= 2:
        return False
    t = type(value)
    if t in (tuple, list) and len(value) <= 64:
        return all(_is_plain(v, depth + 1) for v in value)
    if t is dict and len(value) <= 64:
        return all(type(k) in _PLAIN_TYPES and _is_plain(v, depth + 1)
                   for k, v in value.items())
    return False


def serialize(value: Any) -> SerializedObject:
    if _is_plain(value):
        return SerializedObject(pickle.dumps(value, protocol=5), [])
    buffers: List[pickle.PickleBuffer] = []
    value = _to_numpy_if_jax(value)
    meta = cloudpickle.dumps(value, protocol=5, buffer_callback=buffers.append)
    views = []
    for pb in buffers:
        v = pb.raw()
        if not v.contiguous:
            v = memoryview(v.tobytes())
        elif v.format != "B" or v.ndim != 1:
            v = v.cast("B")
        views.append(v)
    return SerializedObject(meta, views)


def deserialize(data: memoryview) -> Any:
    """Deserialize from a (possibly shm-backed) buffer, zero-copy for arrays.

    The returned object may hold views into ``data``; the store client ties
    the lifetime of the underlying segment to these views.
    """
    nbuf, meta_len = _HEADER.unpack_from(data, 0)
    trailer_n = struct.unpack_from("<I", data, len(data) - 4)[0]
    if trailer_n != nbuf:
        raise ValueError("corrupt serialized object trailer")
    sizes = struct.unpack_from(f"<{nbuf}Q", data, len(data) - 4 - 8 * nbuf) if nbuf else ()
    pos = _HEADER.size
    meta = bytes(data[pos: pos + meta_len])
    pos += meta_len
    bufs = []
    for size in sizes:
        pos = _aligned(pos)
        bufs.append(data[pos: pos + size])
        pos += size
    return pickle.loads(meta, buffers=bufs)


def dumps(value: Any) -> bytes:
    return serialize(value).to_bytes()


def loads(data: bytes | bytearray | memoryview) -> Any:
    if isinstance(data, (bytes, bytearray)):
        data = memoryview(data)
    return deserialize(data)


# --- exceptions -----------------------------------------------------------
class RayTaskError(Exception):
    """Wraps an exception raised inside a remote task/actor method.

    Reference: python/ray/exceptions.py RayTaskError — re-raised at every
    ``get()`` of the errored object, with the remote traceback attached.
    """

    def __init__(self, function_name: str, traceback_str: str, cause_repr: str,
                 cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause_repr = cause_repr
        self.cause = cause
        super().__init__(f"Task '{function_name}' failed:\n{traceback_str}")

    def __reduce__(self):
        return (type(self), (self.function_name, self.traceback_str,
                             self.cause_repr, self.cause))


class WorkerCrashedError(Exception):
    pass


class ActorDiedError(Exception):
    pass


class ObjectLostError(Exception):
    pass


class GetTimeoutError(TimeoutError):
    pass


class TaskCancelledError(Exception):
    pass


class PlacementGroupUnavailableError(Exception):
    pass
