"""Actor API: ActorClass, ActorHandle, ActorMethod.

Equivalent of the reference's python/ray/actor.py (ActorClass :566,
``_remote`` :854 → create_actor; method calls :1460 → submit_actor_task).
Handles are serializable: passing one into a task gives the receiver a
working handle to the same actor (resolved through the GCS actor table).
"""

from __future__ import annotations

from typing import Any, Optional

from ray_tpu.core.ids import ActorID


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, opts: dict):
        self._handle = handle
        self._name = name
        self._opts = opts

    def options(self, **opts) -> "ActorMethod":
        return ActorMethod(self._handle, self._name, {**self._opts, **opts})

    def remote(self, *args, **kwargs):
        from ray_tpu._private.worker import global_worker

        worker = global_worker()
        refs = worker.submit_actor_task(
            self._handle._actor_id, self._name, args, kwargs, self._opts)
        num_returns = self._opts.get("num_returns", 1)
        if num_returns == 1 or num_returns == "streaming":
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        from ray_tpu.dag.dag_node import ActorMethodNode

        return ActorMethodNode(self._handle, self._name, args, kwargs)


class ActorHandle:
    def __init__(self, actor_id: ActorID, method_opts: Optional[dict] = None):
        object.__setattr__(self, "_actor_id", actor_id)
        object.__setattr__(self, "_method_opts", method_opts or {})

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name, dict(self._method_opts.get(name, {})))

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:12]})"

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and \
            other._actor_id == self._actor_id

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._method_opts))


class ActorClass:
    def __init__(self, cls: type, opts: dict):
        self._cls = cls
        self._opts = opts
        self._descriptor = None
        self._descriptor_session = None  # session token of the export
        self.__name__ = cls.__name__
        # Collect per-method options declared with @method(...).
        self._method_opts = {
            name: getattr(fn, "__ray_tpu_method_opts__")
            for name, fn in vars(cls).items()
            if callable(fn) and hasattr(fn, "__ray_tpu_method_opts__")
        }

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self.__name__} cannot be instantiated directly; "
            f"use {self.__name__}.remote()")

    def options(self, **opts) -> "ActorClass":
        new = ActorClass(self._cls, {**self._opts, **opts})
        new._descriptor = self._descriptor
        new._descriptor_session = self._descriptor_session
        return new

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ray_tpu._private.api import _resolve_strategy
        from ray_tpu._private.worker import global_worker

        worker = global_worker()
        # Module-level actor classes outlive clusters: re-export when the
        # session changed (a fresh GCS has an empty function table).
        if self._descriptor is None or \
                self._descriptor_session != worker.core.worker_id.binary():
            self._descriptor = worker.export(self._cls)
            self._descriptor_session = worker.core.worker_id.binary()
        opts = _resolve_strategy(self._opts)
        actor_id = worker.create_actor(self._descriptor, args, kwargs, opts)
        return ActorHandle(actor_id, self._method_opts)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag.dag_node import ActorClassNode

        return ActorClassNode(self, args, kwargs)


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    """Look up a named actor (reference: ray.get_actor)."""
    from ray_tpu._private.worker import global_worker

    worker = global_worker()
    view = worker.gcs_call("get_actor_info",
                           {"name": name, "namespace": namespace})
    if view is None or view["state"] == "DEAD":
        raise ValueError(f"no live actor named {name!r}")
    return ActorHandle(ActorID(view["actor_id"]))
