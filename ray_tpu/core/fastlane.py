"""Fastlane client/server: the native task-path transport.

Python face of _native/fastlane.cpp — the C++ submit/receive pump that
replaces the asyncio rpc layer on the task hot path (reference:
src/ray/rpc/server_call.h, src/ray/core_worker/transport/
normal_task_submitter.cc:24). Framing, reply correlation, and all blocking
waits happen in native code with the GIL released; Python supplies only
policy: what to execute, how to store results.

``FastlaneServer`` is the executor side (workers): dispatcher threads pop
requests with :meth:`next` and answer with :meth:`reply`. The native layer
delivers at most one outstanding request per connection, preserving
per-caller FIFO order.

``FastChannel`` is the submitter side (drivers/workers submitting): sends
ride the calling thread; one pump thread per channel correlates replies and
invokes ``on_reply(ctx, reply_dict)`` off the event loop entirely.
"""

from __future__ import annotations

import ctypes
import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import msgpack

from ray_tpu._native.build import load_lib

logger = logging.getLogger(__name__)

CLOSED = object()  # sentinel: the underlying connection/server is gone

_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = load_lib("ray_tpu_fastlane")
        c = ctypes
        lib.fl_connect.argtypes = [c.c_char_p, c.c_int, c.c_int]
        lib.fl_connect.restype = c.c_void_p
        lib.fl_send.argtypes = [c.c_void_p, c.c_uint64, c.c_char_p,
                                c.c_int64]
        lib.fl_send.restype = c.c_int
        lib.fl_wait_any.argtypes = [c.c_void_p, c.c_int,
                                    c.POINTER(c.c_char_p),
                                    c.POINTER(c.c_int64)]
        lib.fl_wait_any.restype = c.c_int64
        lib.fl_closed.argtypes = [c.c_void_p]
        lib.fl_closed.restype = c.c_int
        lib.fl_shutdown.argtypes = [c.c_void_p]
        lib.fl_close.argtypes = [c.c_void_p]
        lib.fl_buf_free.argtypes = [c.c_char_p]
        lib.fl_server_create.argtypes = [c.POINTER(c.c_int)]
        lib.fl_server_create.restype = c.c_void_p
        lib.fl_server_next.argtypes = [c.c_void_p, c.c_int,
                                       c.POINTER(c.c_char_p),
                                       c.POINTER(c.c_int64)]
        lib.fl_server_next.restype = c.c_int64
        lib.fl_server_reply.argtypes = [c.c_void_p, c.c_uint64, c.c_char_p,
                                        c.c_int64]
        lib.fl_server_reply.restype = c.c_int
        lib.fl_server_shutdown.argtypes = [c.c_void_p]
        lib.fl_server_close.argtypes = [c.c_void_p]
        _lib = lib
        return lib


def _take_buf(lib, buf, n) -> bytes:
    data = ctypes.string_at(buf, n.value)
    lib.fl_buf_free(buf)
    return data


class FastlaneServer:
    """Executor-side request server (native accept/read pump)."""

    def __init__(self):
        self._lib = _load()
        port = ctypes.c_int()
        self._h = self._lib.fl_server_create(ctypes.byref(port))
        if not self._h:
            raise OSError("fastlane server bind failed")
        self.port = port.value
        self._shut = False
        self._lock = threading.Lock()

    def next(self, timeout_ms: int = 500):
        """Pop the next request: (reqid, payload) | None on timeout |
        CLOSED after shutdown."""
        buf = ctypes.c_char_p()
        n = ctypes.c_int64()
        rid = self._lib.fl_server_next(self._h, timeout_ms,
                                       ctypes.byref(buf), ctypes.byref(n))
        if rid > 0:
            return rid, _take_buf(self._lib, buf, n)
        return CLOSED if rid < 0 else None

    def reply(self, reqid: int, payload: bytes) -> None:
        # Deferred replies (loop-path fallbacks) can land after close();
        # the lock + handle check keep them off a freed native server.
        with self._lock:
            if self._h:
                self._lib.fl_server_reply(self._h, reqid, payload,
                                          len(payload))

    def shutdown(self) -> None:
        """Wake all dispatchers (they observe CLOSED); handle stays valid."""
        with self._lock:
            if not self._shut:
                self._shut = True
                self._lib.fl_server_shutdown(self._h)

    def close(self) -> None:
        """Free the native server. Only call after every dispatcher thread
        has exited its next() loop."""
        with self._lock:
            if self._h:
                self._lib.fl_server_shutdown(self._h)
                self._lib.fl_server_close(self._h)
                self._h = None


class FastChannel:
    """Submitter-side connection + reply pump.

    submit() runs on the calling thread (one native frame write); the pump
    thread correlates replies and calls ``on_reply(ctx, reply_dict)``. On
    connection loss the pump calls ``on_close([ctx, ...])`` with every
    unanswered submission, in submission order, then frees the native
    handle itself (nobody else may touch it afterwards).
    """

    def __init__(self, address: str,
                 on_reply: Callable[[Any, dict], None],
                 on_close: Callable[[List[Any]], None],
                 connect_timeout_ms: int = 2000):
        self._lib = _load()
        host, port = address.rsplit(":", 1)
        self._h = self._lib.fl_connect(host.encode(), int(port),
                                       connect_timeout_ms)
        if not self._h:
            raise ConnectionError(f"fastlane connect to {address} failed")
        self.address = address
        self._on_reply = on_reply
        self._on_close = on_close
        self._lock = threading.Lock()
        self._next_id = 0
        # msgids are assigned monotonically, so sorted keys ARE submission
        # order — no separate order list to maintain per reply.
        self._pending: Dict[int, Any] = {}
        self._dead = False
        self.graceful_close = False  # owner-initiated (deactivation)
        # Adaptive batching (normal-task channels): wire dicts accumulate
        # while the executor is busy and flush as one frame — when the
        # executor is idle they flush immediately for latency. The pump
        # provides a 5 ms safety flush for fire-and-forget submitters.
        self._buf: List[Tuple[dict, Any]] = []
        self.batch_max = 32
        self._pump = threading.Thread(target=self._pump_loop,
                                      name=f"fl-pump:{address}", daemon=True)
        self._pump.start()

    @property
    def dead(self) -> bool:
        return self._dead

    def pending_count(self) -> int:
        return len(self._pending)

    def submit(self, payload: bytes, ctx: Any) -> bool:
        """Send one request; ctx is handed back to on_reply/on_close.
        Registration happens before the write so a fast reply can't race
        the bookkeeping. Returns False if the channel is dead."""
        with self._lock:
            if self._dead:
                return False
            self._next_id += 1
            mid = self._next_id
            self._pending[mid] = ctx
            if self._lib.fl_send(self._h, mid, payload, len(payload)) != 0:
                self._pending.pop(mid, None)
                return False
        return True

    def submit_batched(self, wire: dict, ctx: Any) -> bool:
        """Queue one task wire dict; flushes when the batch fills or the
        peer has nothing in flight (keep it busy / keep latency low).
        Returns False if the channel is dead (caller takes the rpc path).
        """
        with self._lock:
            if self._dead:
                return False
            self._buf.append((wire, ctx))
            if len(self._buf) >= self.batch_max or not self._pending:
                return self._flush_locked(current_ctx=ctx)
        return True

    def flush(self) -> None:
        """Send any buffered submissions now (called on get()/wait())."""
        with self._lock:
            if not self._dead and self._buf:
                self._flush_locked()

    def _flush_locked(self, current_ctx: Any = None) -> bool:
        batch = self._buf
        self._buf = []
        self._next_id += 1
        mid = self._next_id
        ctxs = [c for _w, c in batch]
        self._pending[mid] = ("__batch__", ctxs)
        payload = msgpack.packb({"tasks": [w for w, _c in batch]},
                                use_bin_type=True)
        if self._lib.fl_send(self._h, mid, payload, len(payload)) != 0:
            self._pending.pop(mid, None)
            # The wound channel's pump will fire on_close for _pending
            # entries; these never made it there, so fail them here —
            # EXCEPT the submission currently in flight: its caller sees
            # False and resubmits it itself (handing it to on_close too
            # would run the task twice).
            fail = [c for c in ctxs if c is not current_ctx]
            if fail:
                try:
                    self._on_close(fail)
                except Exception:
                    logger.exception(
                        "fastlane on_close (flush) failed (%s)",
                        self.address)
            return False
        return True

    def close(self) -> None:
        """Wound the connection; the pump thread finishes the teardown.
        Marks the close as owner-initiated so stragglers caught in the
        window are resubmitted without burning a retry (the worker did
        not die)."""
        with self._lock:
            if not self._dead:
                self.graceful_close = True
                self._lib.fl_shutdown(self._h)

    def _pump_loop(self) -> None:
        lib = self._lib
        buf = ctypes.c_char_p()
        n = ctypes.c_int64()
        while True:
            timeout = 5 if self._buf else 500
            mid = lib.fl_wait_any(self._h, timeout, ctypes.byref(buf),
                                  ctypes.byref(n))
            if self._buf:  # safety flush for fire-and-forget submitters
                self.flush()
            if mid == 0:
                continue
            if mid < 0:
                break
            payload = _take_buf(lib, buf, n)
            with self._lock:
                ctx = self._pending.pop(mid, None)
            if ctx is None:
                continue
            try:
                reply = msgpack.unpackb(payload, raw=False)
                if isinstance(ctx, tuple) and len(ctx) == 2 and \
                        ctx[0] == "__batch__":
                    replies = reply.get("replies", [])
                    for i, one_ctx in enumerate(ctx[1]):
                        one = (replies[i] if i < len(replies) else
                               {"status": "error",
                                "error": "batch reply truncated",
                                "returns": []})
                        self._on_reply(one_ctx, one)
                else:
                    self._on_reply(ctx, reply)
            except Exception:
                logger.exception("fastlane reply handler failed (%s)",
                                 self.address)
        # Connection lost: fail everything outstanding (in submission
        # order), then free. on_close always fires so owners can reap
        # channel state (e.g. return the worker lease) even when idle.
        with self._lock:
            self._dead = True
            pend = []
            for m in sorted(self._pending):
                ctx = self._pending[m]
                if isinstance(ctx, tuple) and len(ctx) == 2 and \
                        ctx[0] == "__batch__":
                    pend.extend(ctx[1])
                else:
                    pend.append(ctx)
            pend.extend(c for _w, c in self._buf)
            self._buf = []
            self._pending.clear()
            lib.fl_close(self._h)
            self._h = None
        try:
            self._on_close(pend)
        except Exception:
            logger.exception("fastlane on_close handler failed (%s)",
                             self.address)
