"""ObjectRef — handle to a (possibly pending) object in the cluster.

Equivalent of the reference's ObjectRef (python/ray/includes/
object_ref.pxi:36): carries the binary ObjectID plus the owner's address so
any holder can locate/borrow the object, and participates in distributed
reference counting — the owning CoreWorker is notified when refs are
created/destroyed in this process.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.core.ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "owner_address", "_worker", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_address: Optional[str] = None,
                 _register: bool = True):
        self.id = object_id
        self.owner_address = owner_address
        self._worker = None
        if _register:
            from ray_tpu._private.worker import global_worker_or_none

            w = global_worker_or_none()
            if w is not None:
                self._worker = w
                w.reference_counter.add_local_ref(self.id)
                if owner_address:
                    # Borrower protocol: record + notify the owner.
                    w.core.register_borrow(self.id, owner_address)

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def task_id(self):
        return self.id.task_id()

    def future(self):
        """A concurrent.futures.Future resolving to the object's value."""
        from ray_tpu._private.worker import global_worker

        return global_worker().as_future(self)

    def __await__(self):
        from ray_tpu._private.worker import global_worker

        return global_worker().get_async(self).__await__()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __del__(self):
        w = self._worker
        if w is not None:
            try:
                w.reference_counter.remove_local_ref(self.id)
            except Exception:
                pass

    def __reduce__(self):
        # Serializing a ref inside a task arg / another object makes the
        # receiver a borrower; registration on deserialize adds a local ref.
        return (_deserialize_ref, (self.id.binary(), self.owner_address))


def _deserialize_ref(id_binary: bytes, owner_address: Optional[str]) -> "ObjectRef":
    return ObjectRef(ObjectID(id_binary), owner_address)
