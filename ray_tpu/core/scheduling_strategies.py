"""Scheduling strategies (reference: python/ray/util/scheduling_strategies.py).
"""

from __future__ import annotations

from typing import Optional, Union

from ray_tpu.core.placement_group import PlacementGroup


class PlacementGroupSchedulingStrategy:
    """Pin a task/actor to a placement-group bundle (reference :15)."""

    def __init__(self, placement_group: PlacementGroup,
                 placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = \
            placement_group_capture_child_tasks


class NodeAffinitySchedulingStrategy:
    """Pin to a node by id (reference :41)."""

    def __init__(self, node_id: Union[str, bytes], soft: bool = False):
        self.node_id = node_id
        self.soft = soft


# "DEFAULT" and "SPREAD" are passed as plain strings, like the reference.
DEFAULT = "DEFAULT"
SPREAD = "SPREAD"
