"""Async RPC layer: length-prefixed msgpack frames over TCP/unix sockets.

TPU-native equivalent of the reference's gRPC wrappers (src/ray/rpc/
grpc_server.h, client_call.h): a small, dependency-light framed protocol with
request/response correlation, notifications (one-way), per-handler chaos
delay injection (src/ray/common/asio/asio_chaos.h analog), and automatic
reconnect-with-retry clients. Control-plane only — bulk object data rides the
same connections but in dedicated chunked messages, and device data never
touches this layer (XLA collectives over ICI carry it in-program).

All values are msgpack-encodable: ints/floats/str/bytes/list/dict. Binary
IDs travel as raw bytes.
"""

from __future__ import annotations

import asyncio
import logging
import os
import struct
import threading
import time
from typing import Any, Awaitable, Callable, Dict, Optional

import msgpack

from ray_tpu.core.config import get_rpc_delay_us

logger = logging.getLogger(__name__)

_LEN = struct.Struct("<I")

REQUEST, RESPONSE, NOTIFY, ERROR = 0, 1, 2, 3


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


def _pack(msg) -> bytes:
    payload = msgpack.packb(msg, use_bin_type=True)
    return _LEN.pack(len(payload)) + payload


async def _read_frame(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    payload = await reader.readexactly(length)
    return msgpack.unpackb(payload, raw=False)


class Connection:
    """One bidirectional RPC connection.

    Both ends can issue requests; the handler (if any) serves incoming ones.
    """

    # Cork threshold: frames accumulate in _out and flush once per loop
    # tick (one write syscall for a burst of messages); anything larger
    # flushes immediately and awaits transport drain for backpressure.
    CORK_BYTES = 256 * 1024
    DRAIN_BYTES = 4 * 1024 * 1024  # small-frame backpressure high-water mark

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        handler: Optional[Callable[[str, Any, "Connection"], Awaitable[Any]]] = None,
        name: str = "",
    ):
        self.reader = reader
        self.writer = writer
        self.handler = handler
        self.name = name
        self._next_id = 0
        self._pending: Dict[int, asyncio.Future] = {}
        self._closed = False
        self._read_task: Optional[asyncio.Task] = None
        self._write_lock = asyncio.Lock()
        self._out = bytearray()
        self._flush_scheduled = False
        self.on_close: Optional[Callable[["Connection"], None]] = None

    def start(self) -> None:
        self._read_task = asyncio.get_running_loop().create_task(self._read_loop())

    async def _read_loop(self) -> None:
        try:
            while True:
                msg = await _read_frame(self.reader)
                kind = msg[0]
                if kind == REQUEST:
                    _, msgid, method, data = msg
                    asyncio.get_running_loop().create_task(
                        self._serve(msgid, method, data))
                elif kind == NOTIFY:
                    _, method, data = msg
                    asyncio.get_running_loop().create_task(
                        self._serve(None, method, data))
                elif kind == RESPONSE:
                    _, msgid, data = msg
                    fut = self._pending.pop(msgid, None)
                    if fut is not None and not fut.done():
                        fut.set_result(data)
                elif kind == ERROR:
                    _, msgid, err = msg
                    fut = self._pending.pop(msgid, None)
                    if fut is not None and not fut.done():
                        fut.set_exception(RpcError(err))
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        except Exception:
            logger.exception("rpc read loop failed (%s)", self.name)
        finally:
            await self._teardown()

    async def _serve(self, msgid: Optional[int], method: str, data: Any) -> None:
        delay_us = get_rpc_delay_us(method)
        if delay_us:
            await asyncio.sleep(delay_us / 1e6)
        try:
            if self.handler is None:
                raise RpcError(f"no handler for {method}")
            result = await self.handler(method, data, self)
            if msgid is not None:
                await self.send((RESPONSE, msgid, result))
        except Exception as e:
            if msgid is not None:
                try:
                    await self.send((ERROR, msgid, f"{type(e).__name__}: {e}"))
                except Exception:
                    pass
            else:
                logger.exception("notify handler %s failed", method)

    async def send(self, msg) -> None:
        data = _pack(msg)
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed")
        if len(data) >= self.CORK_BYTES:
            # Large payload: flush the cork, write directly, apply
            # transport backpressure.
            async with self._write_lock:
                if self._closed:
                    raise ConnectionLost(f"connection {self.name} closed")
                self._flush()
                self.writer.write(data)
                await self.writer.drain()
            return
        self.send_nowait(data)
        # Sustained bursts of small frames to a slow peer must not buffer
        # unboundedly: once the transport's write buffer crosses the
        # high-water mark, fall back to drain()'s backpressure.
        try:
            buffered = self.writer.transport.get_write_buffer_size()
        except Exception:
            buffered = 0
        if buffered + len(self._out) >= self.DRAIN_BYTES:
            async with self._write_lock:
                if self._closed:
                    raise ConnectionLost(f"connection {self.name} closed")
                self._flush()
                await self.writer.drain()

    def send_nowait(self, data: bytes) -> None:
        """Queue a packed frame; flushed once per loop tick. Writes from
        one loop iteration (e.g. a pipelined burst of task pushes or
        replies) coalesce into a single write syscall — the dominant cost
        on small control messages."""
        self._out += data
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush)

    def _flush(self) -> None:
        self._flush_scheduled = False
        if self._closed or not self._out:
            self._out.clear()
            return
        data = bytes(self._out)
        self._out.clear()
        try:
            self.writer.write(data)
        except Exception:
            # Transport write failed (e.g. half-open connection): the
            # frames are lost, so tear down NOW — pending callers get
            # ConnectionLost instead of hanging on futures whose
            # requests never left this process.
            asyncio.get_running_loop().create_task(self._teardown())

    async def call(self, method: str, data: Any = None,
                   timeout: Optional[float] = None) -> Any:
        self._next_id += 1
        msgid = self._next_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[msgid] = fut
        await self.send((REQUEST, msgid, method, data))
        try:
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(msgid, None)

    async def notify(self, method: str, data: Any = None) -> None:
        await self.send((NOTIFY, method, data))

    async def _teardown(self) -> None:
        if self._closed:
            return
        try:
            self._flush()
        except Exception:
            pass
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost(f"connection {self.name} lost"))
        self._pending.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        if self.on_close:
            try:
                self.on_close(self)
            except Exception:
                logger.exception("on_close callback failed")

    async def close(self) -> None:
        if self._read_task:
            self._read_task.cancel()
        await self._teardown()

    @property
    def closed(self) -> bool:
        return self._closed


class Server:
    """RPC server: dispatches `method` to handler.handle_<method>(data, conn)."""

    def __init__(self, handler_obj, host: str = "127.0.0.1", port: int = 0):
        self.handler_obj = handler_obj
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: set[Connection] = set()

    async def _dispatch(self, method: str, data: Any, conn: Connection) -> Any:
        fn = getattr(self.handler_obj, "handle_" + method, None)
        if fn is None:
            raise RpcError(f"unknown method {method}")
        result = fn(data, conn)
        if asyncio.iscoroutine(result):
            result = await result
        return result

    async def _on_client(self, reader, writer) -> None:
        conn = Connection(reader, writer, handler=self._dispatch, name="server")
        self.connections.add(conn)
        conn.on_close = self.connections.discard
        if hasattr(self.handler_obj, "on_connection"):
            self.handler_obj.on_connection(conn)
        conn.start()

    async def start(self) -> int:
        # Large accept backlog: an actor storm lands hundreds of worker
        # connections on the GCS/raylet within one loop lag window; the
        # asyncio default (100) overflows and the kernel REFUSES the
        # excess — workers then burn their whole connect-retry budget
        # and die (observed at 400-actor scale).
        self._server = await asyncio.start_server(
            self._on_client, self.host, self.port, backlog=4096)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def close(self) -> None:
        # Close accepted connections BEFORE wait_closed(): since py3.12
        # wait_closed blocks until every connection handler finishes, so
        # waiting first deadlocks while peers (e.g. the driver) hold
        # connections open.
        if self._server:
            self._server.close()
        for conn in list(self.connections):
            await conn.close()
        if self._server:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2.0)
            except asyncio.TimeoutError:
                pass


async def connect(host: str, port: int,
                  handler: Optional[Callable] = None,
                  name: str = "",
                  timeout: float = 10.0,
                  retry_interval: float = 0.1) -> Connection:
    """Connect with retry (the peer process may still be starting)."""
    deadline = time.monotonic() + timeout
    last_err: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            reader, writer = await asyncio.open_connection(host, port)
            conn = Connection(reader, writer, handler=handler, name=name)
            conn.start()
            return conn
        except (ConnectionRefusedError, OSError) as e:
            last_err = e
            await asyncio.sleep(retry_interval)
    raise ConnectionLost(f"could not connect to {host}:{port}: {last_err}")


class EventLoopThread:
    """A dedicated asyncio loop on a background thread.

    The reference embeds io threads inside CoreWorker
    (src/ray/core_worker/core_worker_process.cc); here the driver/worker's
    synchronous public API posts coroutines onto this loop.
    """

    def __init__(self, name: str = "ray_tpu_io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        profile_dir = os.environ.get("RAY_TPU_IO_PROFILE")
        if profile_dir:
            # Debug aid (like RAY_TPU_WORKER_PROFILE): cProfile this io
            # loop thread, dump at loop stop.
            import cProfile

            prof = cProfile.Profile()
            try:
                prof.runcall(self.loop.run_forever)
            finally:
                os.makedirs(profile_dir, exist_ok=True)
                prof.dump_stats(os.path.join(
                    profile_dir, f"io_{os.getpid()}.prof"))
        else:
            self.loop.run_forever()

    def run(self, coro, timeout: Optional[float] = None):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def run_async(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self) -> None:
        def _cancel_all():
            for task in asyncio.all_tasks(self.loop):
                task.cancel()
        self.loop.call_soon_threadsafe(_cancel_all)
        time.sleep(0.05)
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=2)
