"""Runtime configuration flag table.

Equivalent of the reference's RAY_CONFIG X-macro table
(src/ray/common/ray_config_def.h — 215 knobs populated from env vars and the
``_system_config`` dict passed to init). Here: one dataclass, every field
overridable via ``RAY_TPU_<UPPER_NAME>`` env vars or the ``system_config``
dict argument to ``ray_tpu.init``.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional


@dataclasses.dataclass
class Config:
    # --- object store ---
    object_store_memory: int = 0  # 0 = auto (30% of /dev/shm or RAM cap)
    object_store_auto_fraction: float = 0.3
    object_store_max_auto_bytes: int = 2 << 30
    # Objects smaller than this are inlined into the owner's memory store and
    # task replies instead of the shm store (reference:
    # src/ray/common/ray_config_def.h max_direct_call_object_size = 100KiB).
    max_direct_call_object_size: int = 100 * 1024
    object_transfer_chunk_bytes: int = 4 << 20
    object_spilling_dir: str = ""  # default: <session_dir>/spill
    object_spilling_threshold: float = 0.8
    # --- scheduler ---
    # Hybrid policy: pack onto the first feasible node until its critical
    # resource utilization exceeds this threshold, then spread
    # (reference: scheduler_spread_threshold, hybrid_scheduling_policy.cc).
    scheduler_spread_threshold: float = 0.5
    # Tasks pushed concurrently to one leased worker (reference:
    # max_tasks_in_flight_per_worker, normal_task_submitter.cc — the
    # pipelining that makes tiny-task throughput). Execution on the worker
    # stays serialized (single-thread executor); only queueing overlaps.
    # Set to 1 for strict one-task-per-lease semantics.
    max_tasks_in_flight_per_worker: int = 10
    # Pipelining engages only for scheduling keys whose observed (worker-
    # reported) execution time EMA is at or below this; longer tasks keep
    # strict one-in-flight spread semantics.
    pipeline_task_duration_s: float = 0.1
    # Observed-fast sync methods/functions run inline on the worker's io
    # loop (no executor-thread round trip — 2 GIL handoffs saved per
    # call); anything slower keeps the executor path. <=0 disables.
    inline_task_threshold_s: float = 0.002
    # Streaming generators: max yielded-but-unconsumed items per stream
    # before the producer pauses (reference:
    # _generator_backpressure_num_objects). <=0 disables.
    streaming_backpressure_num_items: int = 8
    # How long a raylet outlives an unreachable GCS before exiting
    # (reference: gcs_rpc_server_reconnect_timeout_s).
    gcs_down_exit_s: float = 60.0
    max_pending_lease_requests: int = 8
    worker_lease_timeout_s: float = 30.0
    # Idle fallback cadence of the GCS cluster-view broadcast; resource
    # CHANGES push immediately (RaySyncer-style event-driven sync).
    # Injectable so distributed tests can pin deterministic freshness.
    resource_broadcast_interval_ms: int = 200
    # --- health / failure detection ---
    health_check_period_ms: int = 1000
    # Generous threshold (10s): worker-spawn storms (hundreds of actors)
    # can lag loops for seconds; the reference's defaults allow ~15s
    # (health_check_timeout_ms + failure threshold).
    health_check_failure_threshold: int = 10
    num_heartbeats_timeout: int = 30
    # --- workers ---
    num_workers_soft_limit: int = 0  # 0 = num_cpus
    worker_startup_timeout_s: float = 60.0
    prestart_workers: bool = True
    worker_register_timeout_s: float = 30.0
    # Zygote worker factory (reference: worker_pool.h PrestartWorkers /
    # StartWorkerProcess): fork CPU workers from a warm pre-imported
    # template (~10ms) instead of a fresh interpreter (~0.25s, >1s under
    # spawn storms). TPU-flavored workers always use fresh interpreters.
    forkserver_enabled: bool = True
    # --- task retries / lineage ---
    task_max_retries: int = 3
    actor_max_restarts: int = 0
    lineage_enabled: bool = True
    # --- memory monitor (reference: memory_monitor.h + kill policies) ---
    memory_monitor_refresh_ms: int = 0  # 0 disables
    memory_usage_threshold: float = 0.95
    # --- rpc ---
    rpc_connect_timeout_s: float = 10.0
    rpc_max_message_bytes: int = 512 << 20
    # Native task-path fast lane (_native/fastlane.cpp): framing, reply
    # correlation, and the submit/receive pump run in C++ threads off the
    # asyncio loops; simple tasks execute without touching the loop at
    # all (reference: the C++ lease/push pipeline,
    # normal_task_submitter.cc:24, server_call.h).
    fastlane_enabled: bool = True
    # GIL switch interval applied in every ray_tpu process (0 = leave
    # Python's 5 ms default). Sub-ms keeps the io loop responsive while
    # the executor thread runs user code — the Python substitute for the
    # reference's dedicated C++ io threads. Matters most on few-core hosts.
    gil_switch_interval_s: float = 0.001
    # --- chaos / testing (reference: src/ray/common/asio/asio_chaos.h) ---
    # "handler_name=delay_us,..." — injects latency into named control-plane
    # handlers for deterministic race amplification.
    testing_rpc_delay: str = ""
    # --- logging / observability ---
    log_dir: str = ""
    # Stream worker stdout/stderr to the driver console via the raylet
    # log monitor + GCS pubsub (reference: log_monitor.py).
    log_to_driver: bool = True
    task_events_enabled: bool = True
    task_events_max_buffer: int = 10000
    # Events per report batch: bigger batches = fewer GCS round trips on
    # the submission hot path (reference: task_events_report_interval_ms
    # batching in TaskEventBuffer).
    task_events_batch_size: int = 1000
    metrics_report_interval_ms: int = 2000
    # --- session ---
    temp_dir: str = "/tmp/ray_tpu"

    @classmethod
    def from_env(cls, system_config: Optional[dict] = None) -> "Config":
        cfg = cls()
        for f in dataclasses.fields(cls):
            env_key = "RAY_TPU_" + f.name.upper()
            if env_key in os.environ:
                raw = os.environ[env_key]
                setattr(cfg, f.name, _coerce(raw, f.type))
        if system_config:
            for k, v in system_config.items():
                if not hasattr(cfg, k):
                    raise ValueError(f"Unknown system_config key: {k}")
                setattr(cfg, k, v)
        return cfg

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Config":
        return cls(**d)

    def to_json(self) -> str:
        return json.dumps(self.to_dict())


def _coerce(raw: str, typ) -> object:
    t = str(typ)
    if "int" in t:
        return int(raw)
    if "float" in t:
        return float(raw)
    if "bool" in t:
        return raw.lower() in ("1", "true", "yes")
    return raw


_rpc_delays: Optional[dict] = None


def get_rpc_delay_us(handler: str, config: Optional[Config] = None) -> int:
    """Chaos hook: per-handler injected delay, parsed once.

    Reference: src/ray/common/asio/asio_chaos.h:20 (RAY_testing_asio_delay_us).
    """
    global _rpc_delays
    if _rpc_delays is None:
        spec = (config.testing_rpc_delay if config else
                os.environ.get("RAY_TPU_TESTING_RPC_DELAY", ""))
        _rpc_delays = {}
        for part in spec.split(","):
            if "=" in part:
                name, us = part.split("=", 1)
                _rpc_delays[name.strip()] = int(us)
    return _rpc_delays.get(handler, 0)
