"""TaskSpec — the unit shipped from caller to executor.

Equivalent of the reference's TaskSpecification
(src/ray/common/task/task_spec.h): function descriptor, inlined small args /
object-ref args, resource demands, scheduling strategy, retry policy, actor
identity for actor tasks. Wire format is a msgpack dict.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID

NORMAL_TASK = 0
ACTOR_CREATION_TASK = 1
ACTOR_TASK = 2


@dataclasses.dataclass
class FunctionDescriptor:
    """Identifies the callable: module-qualified name + a GCS function-table
    key holding the pickled definition (reference:
    python/ray/_private/function_manager.py export scheme)."""

    module: str
    qualname: str
    function_key: bytes  # GCS KV key of the pickled function/class

    def to_wire(self) -> list:
        return [self.module, self.qualname, self.function_key]

    @classmethod
    def from_wire(cls, w: list) -> "FunctionDescriptor":
        return cls(w[0], w[1], w[2])

    def display(self) -> str:
        return f"{self.module}.{self.qualname}"


# An argument is either an inlined serialized value ("v") or an object ref
# ("r") that the executor must resolve from the store. DependencyResolver
# inlines small owner-local objects before submission (reference:
# src/ray/core_worker/transport/dependency_resolver.cc).
ARG_VALUE = 0
ARG_REF = 1


@dataclasses.dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: int
    function: FunctionDescriptor
    args: List[Tuple[int, bytes, Optional[str]]]  # (kind, payload|id, owner_addr)
    num_returns: int
    resources: Dict[str, float]
    caller_address: str
    # scheduling
    scheduling_strategy: Optional[dict] = None
    placement_group_id: Optional[PlacementGroupID] = None
    placement_group_bundle_index: int = -1
    max_retries: int = 0
    retry_exceptions: bool = False
    # actor fields
    actor_id: Optional[ActorID] = None
    actor_method: str = ""
    actor_seqno: int = -1
    actor_creation_spec: Optional[dict] = None  # max_restarts, max_concurrency...
    # runtime env / options
    runtime_env: Optional[dict] = None
    name: str = ""
    # Distributed trace context (reference: tracing_helper.py:326 —
    # span context injected into task metadata and propagated through
    # nested submissions): {"trace_id": hex, "parent_span_id": hex}.
    # A task's own span id IS its task id.
    trace_ctx: Optional[dict] = None
    # keyword-argument names: args holds positional args followed by the
    # kwarg values in this order
    kwarg_keys: List[str] = dataclasses.field(default_factory=list)

    def return_ids(self) -> List[ObjectID]:
        if self.num_returns < 0:  # streaming: returns materialize as yielded
            return []
        return [ObjectID.for_task_return(self.task_id, i)
                for i in range(self.num_returns)]

    @property
    def is_streaming(self) -> bool:
        return self.num_returns < 0

    def to_wire(self) -> dict:
        return {
            "task_id": self.task_id.binary(),
            "job_id": self.job_id.binary(),
            "task_type": self.task_type,
            "function": self.function.to_wire(),
            "args": [list(a) for a in self.args],
            "num_returns": self.num_returns,
            "resources": self.resources,
            "caller_address": self.caller_address,
            "scheduling_strategy": self.scheduling_strategy,
            "pg_id": self.placement_group_id.binary() if self.placement_group_id else None,
            "pg_bundle": self.placement_group_bundle_index,
            "max_retries": self.max_retries,
            "retry_exceptions": self.retry_exceptions,
            "actor_id": self.actor_id.binary() if self.actor_id else None,
            "actor_method": self.actor_method,
            "actor_seqno": self.actor_seqno,
            "actor_creation_spec": self.actor_creation_spec,
            "runtime_env": self.runtime_env,
            "name": self.name,
            "kwarg_keys": self.kwarg_keys,
            "trace_ctx": self.trace_ctx,
        }

    @classmethod
    def from_wire(cls, w: dict) -> "TaskSpec":
        return cls(
            task_id=TaskID(w["task_id"]),
            job_id=JobID(w["job_id"]),
            task_type=w["task_type"],
            function=FunctionDescriptor.from_wire(w["function"]),
            args=[tuple(a) for a in w["args"]],
            num_returns=w["num_returns"],
            resources=w["resources"],
            caller_address=w["caller_address"],
            scheduling_strategy=w.get("scheduling_strategy"),
            placement_group_id=PlacementGroupID(w["pg_id"]) if w.get("pg_id") else None,
            placement_group_bundle_index=w.get("pg_bundle", -1),
            max_retries=w.get("max_retries", 0),
            retry_exceptions=w.get("retry_exceptions", False),
            actor_id=ActorID(w["actor_id"]) if w.get("actor_id") else None,
            actor_method=w.get("actor_method", ""),
            actor_seqno=w.get("actor_seqno", -1),
            actor_creation_spec=w.get("actor_creation_spec"),
            runtime_env=w.get("runtime_env"),
            name=w.get("name", ""),
            kwarg_keys=w.get("kwarg_keys", []),
            trace_ctx=w.get("trace_ctx"),
        )

    def scheduling_key(self) -> tuple:
        """Tasks with the same key can reuse each other's worker leases
        (reference: NormalTaskSubmitter scheduling_key)."""
        return (
            self.function.function_key,
            tuple(sorted(self.resources.items())),
            self.placement_group_id.binary() if self.placement_group_id else b"",
        )
