"""Python client for the native shared-memory object store.

Counterpart of the reference's plasma client (src/ray/object_manager/plasma/
client.cc) — but since the store is a single file-backed mapping (see
_native/shm_store.cpp), the "client" is just ctypes calls into the mapped
region plus an mmap for zero-copy buffer views. Buffers returned by ``get``
pin the object (shm refcount) until the last view is garbage collected.
"""

from __future__ import annotations

import ctypes
import functools
import mmap
import os
import weakref
from typing import Optional, Tuple

from ray_tpu._native.build import load_lib
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.serialization import SerializedObject

OK = 0
ERR_EXISTS = -1
ERR_NOT_FOUND = -2
ERR_FULL = -3
ERR_TIMEOUT = -4
ERR_INVALID = -5
ERR_NOT_SEALED = -6
ERR_IN_USE = -7

_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = load_lib("ray_tpu_store")
        lib.shm_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                         ctypes.c_uint32]
        lib.shm_store_create.restype = ctypes.c_int
        lib.shm_store_open.argtypes = [ctypes.c_char_p]
        lib.shm_store_open.restype = ctypes.c_void_p
        lib.shm_store_close.argtypes = [ctypes.c_void_p]
        lib.shm_store_prefault.argtypes = [ctypes.c_void_p,
                                           ctypes.c_uint64]
        lib.shm_store_prefault.restype = None
        lib.shm_store_write.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_void_p, ctypes.c_uint64]
        lib.shm_store_write.restype = None
        for fn, extra in [
            ("shm_create", [ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64)]),
            ("shm_seal", []),
            ("shm_abort", []),
            ("shm_get", [ctypes.c_long, ctypes.POINTER(ctypes.c_uint64),
                         ctypes.POINTER(ctypes.c_uint64)]),
            ("shm_release", []),
            ("shm_delete", []),
            ("shm_contains", []),
        ]:
            f = getattr(lib, fn)
            f.argtypes = [ctypes.c_void_p, ctypes.c_char_p] + extra
            f.restype = ctypes.c_int
        lib.shm_stats.argtypes = [ctypes.c_void_p] + [
            ctypes.POINTER(ctypes.c_uint64)] * 4
        lib.shm_stats.restype = ctypes.c_int
        # Without an explicit signature ctypes would truncate the 64-bit
        # handle to a C int — a raylet-killing segfault in the spill path.
        lib.shm_list.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
        lib.shm_list.restype = ctypes.c_int
        _lib = lib
    return _lib


class StoreFullError(Exception):
    pass


class _PinState:
    """Shared pin count for one get(): 1 owner pin (the PlasmaBuffer object)
    + one per exported buffer view. The shm refcount is released only when
    all of them are gone, so zero-copy numpy views deserialized out of the
    store keep the object pinned against eviction/spilling for their entire
    lifetime (reference: plasma client buffers pin objects while mapped)."""

    __slots__ = ("pins", "handle_ref", "id_binary", "view")

    def __init__(self, handle_ref, id_binary: bytes, view: memoryview):
        self.pins = 1
        self.handle_ref = handle_ref
        self.id_binary = id_binary
        self.view = view

    def drop_pin(self):
        self.pins -= 1
        if self.pins == 0:
            self.view.release()
            handle = self.handle_ref()
            if handle is not None and handle.value_ptr:
                _load().shm_release(handle.value_ptr, self.id_binary)


class PlasmaBuffer:
    """Zero-copy handle to a sealed object.

    Exports the buffer protocol (PEP 688): ``memoryview(buf)`` / ``.data``
    and every slice derived from it holds a pin; the shm refcount drops only
    after the buffer object *and* all views are gone.
    """

    __slots__ = ("_view", "_state", "_finalizer", "__weakref__")

    def __init__(self, client: "ShmClient", object_id: ObjectID,
                 view: memoryview):
        self._view = view
        self._state = _PinState(client._lib_handle_ref, object_id.binary(),
                                view)
        self._finalizer = weakref.finalize(self, self._state.drop_pin)

    @property
    def data(self) -> memoryview:
        return memoryview(self)

    def __buffer__(self, flags) -> memoryview:
        self._state.pins += 1
        return self._view[:]

    def __release_buffer__(self, view: memoryview) -> None:
        view.release()
        self._state.drop_pin()

    def __len__(self) -> int:
        return self._view.nbytes

    def release(self):
        """Drop the owner pin (idempotent); exported views keep their own."""
        self._finalizer()


class _HandleBox:
    """Keeps the ctypes store handle alive for finalizers after client close."""

    def __init__(self, ptr):
        self.value_ptr = ptr


class ShmClient:
    def __init__(self, path: str):
        self.path = path
        lib = _load()
        ptr = lib.shm_store_open(path.encode())
        if not ptr:
            raise RuntimeError(f"cannot open shm store at {path}")
        self._handle = _HandleBox(ptr)
        self._lib_handle_ref = weakref.ref(self._handle)
        fd = os.open(path, os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, 0)
        finally:
            os.close(fd)
        self._mv = memoryview(self._mm)

    @staticmethod
    def create_store(path: str, capacity: int, n_slots: int = 4096) -> None:
        rc = _load().shm_store_create(path.encode(), capacity, n_slots)
        if rc != 0:
            raise OSError(-rc, f"shm_store_create({path}) failed")

    @property
    def _ptr(self):
        return self._handle.value_ptr

    def put_serialized(self, object_id: ObjectID, sobj: SerializedObject) -> bool:
        """Returns False if the object already exists (idempotent put).

        Large payload buffers are copied by the native shm_store_write
        (ctypes drops the GIL during the call), so big puts don't stall
        other Python threads; small header/trailer writes go through the
        mapped view directly.
        """
        off = ctypes.c_uint64()
        rc = _load().shm_create(self._ptr, object_id.binary(),
                                sobj.total_size, ctypes.byref(off))
        if rc == ERR_EXISTS:
            return False
        if rc == ERR_FULL:
            raise StoreFullError(
                f"object of {sobj.total_size} bytes does not fit in store")
        if rc != OK:
            raise RuntimeError(f"shm_create failed: {rc}")
        try:
            dest = self._mv[off.value: off.value + sobj.total_size]
            sobj.write_to(dest, native_write=functools.partial(
                _load().shm_store_write, self._ptr, off.value))
            dest.release()
        except BaseException:
            _load().shm_abort(self._ptr, object_id.binary())
            raise
        _load().shm_seal(self._ptr, object_id.binary())
        # Creator's initial reference: hand it off — the object is now
        # owned by the distributed refcounter, not this client.
        _load().shm_release(self._ptr, object_id.binary())
        return True

    def prefault(self, max_bytes: int = 4 << 30) -> None:
        """Background pre-population of (a prefix of) the arena —
        first-touch page faults move off the first puts' critical path."""
        _load().shm_store_prefault(self._ptr, max_bytes)

    def put_bytes(self, object_id: ObjectID, data: bytes) -> bool:
        off = ctypes.c_uint64()
        rc = _load().shm_create(self._ptr, object_id.binary(), len(data),
                                ctypes.byref(off))
        if rc == ERR_EXISTS:
            return False
        if rc == ERR_FULL:
            raise StoreFullError(f"object of {len(data)} bytes does not fit")
        if rc != OK:
            raise RuntimeError(f"shm_create failed: {rc}")
        self._mv[off.value: off.value + len(data)] = data
        _load().shm_seal(self._ptr, object_id.binary())
        _load().shm_release(self._ptr, object_id.binary())
        return True

    def get(self, object_id: ObjectID,
            timeout_ms: int = 0) -> Optional[PlasmaBuffer]:
        """Pin + return a zero-copy buffer, or None if absent (timeout)."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = _load().shm_get(self._ptr, object_id.binary(), timeout_ms,
                             ctypes.byref(off), ctypes.byref(size))
        if rc in (ERR_NOT_FOUND, ERR_TIMEOUT):
            return None
        if rc != OK:
            raise RuntimeError(f"shm_get failed: {rc}")
        view = self._mv[off.value: off.value + size.value]
        return PlasmaBuffer(self, object_id, view)

    def contains(self, object_id: ObjectID) -> bool:
        return bool(_load().shm_contains(self._ptr, object_id.binary()))

    def object_size(self, object_id: ObjectID) -> Optional[int]:
        """Size of a locally-resident sealed object (None if absent).
        Pins briefly via shm_get(timeout=0) + release."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        lib = _load()
        rc = lib.shm_get(self._ptr, object_id.binary(), 0,
                         ctypes.byref(off), ctypes.byref(size))
        if rc != OK:
            return None
        lib.shm_release(self._ptr, object_id.binary())
        return size.value

    def delete(self, object_id: ObjectID) -> bool:
        return _load().shm_delete(self._ptr, object_id.binary()) == OK

    def stats(self) -> dict:
        vals = [ctypes.c_uint64() for _ in range(4)]
        _load().shm_stats(self._ptr, *[ctypes.byref(v) for v in vals])
        return {
            "bytes_used": vals[0].value,
            "capacity": vals[1].value,
            "num_objects": vals[2].value,
            "num_evictions": vals[3].value,
        }

    def close(self):
        ptr = self._handle.value_ptr
        self._handle.value_ptr = None
        if ptr:
            _load().shm_store_close(ptr)
        try:
            self._mv.release()
            self._mm.close()
        except (BufferError, ValueError):
            pass  # outstanding zero-copy views keep the mapping alive
