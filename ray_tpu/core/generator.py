"""Streaming generators — ``num_returns="streaming"``.

Equivalent of the reference's ObjectRefGenerator (python/ray/_raylet.pyx:277)
and the streaming-generator protocol around it: a task whose function is a
generator yields values as it produces them; each yielded value becomes an
owned object of the CALLER, reported out-of-band while the task is still
running, and the caller iterates ObjectRefs without waiting for the task to
finish.  This is the primitive under Ray Data's per-block yields and Serve's
streaming responses.

Protocol (this framework's TPU-native redesign — item reports ride the
worker→caller rpc plane, completion rides the normal push_task reply):

- caller registers a ``StreamState`` keyed by task id at submission;
- the executing worker sends one ``stream_item`` notify per yielded value
  (inline bytes for small values; plasma + location registration for big
  ones) to the caller's rpc server;
- the push_task reply carries ``stream_total`` (count produced) and, on a
  mid-stream exception, ``stream_error`` (a serialized RayTaskError raised
  to the consumer after all produced items are drained);
- item notifies and the completion reply travel on different connections,
  so the consumer waits for item *i* until it arrives even if the total is
  already known.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Set

__all__ = ["ObjectRefGenerator", "StreamState", "STREAMING"]

# Wire value of num_returns for streaming tasks.
STREAMING = -1

_END = object()  # async-iteration end sentinel


class StreamState:
    """Caller-side state of one streaming task's output channel."""

    def __init__(self) -> None:
        self.cond = threading.Condition()
        self.ready: Dict[int, Any] = {}      # index -> ObjectRef (unconsumed)
        self.received: Set[int] = set()      # all indices ever accepted
        self.total: Optional[int] = None     # set by the completion reply
        self.error_blob: Optional[bytes] = None
        self.error_raised = False
        self.next_index = 0                  # consumer cursor
        self.actor_id = None                 # set for actor streams (cancel)
        self.producer_conn = None            # ack/cancel channel (set on
        #                                      first stream_item)
        self.released = False                # consumer abandoned the stream


class ObjectRefGenerator:
    """Iterator of ObjectRefs for a streaming task's yields.

    Sync and async iteration both work; each ``__next__`` blocks until the
    next yielded value's ref is available (the value itself may still be a
    plasma object fetched lazily by ``ray_tpu.get``).
    """

    def __init__(self, task_id, worker) -> None:
        self._task_id = task_id
        self._worker = worker

    # -- sync protocol ----------------------------------------------------
    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self):
        return self._worker.stream_next(self._task_id)

    def next(self, timeout: Optional[float] = None):
        """__next__ with a timeout (raises GetTimeoutError)."""
        return self._worker.stream_next(self._task_id, timeout)

    # -- async protocol ---------------------------------------------------
    def __aiter__(self) -> "ObjectRefGenerator":
        return self

    async def __anext__(self):
        import asyncio

        def step():
            # StopIteration can't be raised into a Future; use a sentinel.
            try:
                return self._worker.stream_next(self._task_id)
            except StopIteration:
                return _END

        ref = await asyncio.get_running_loop().run_in_executor(None, step)
        if ref is _END:
            raise StopAsyncIteration
        return ref

    def completed(self) -> bool:
        """True once every produced item has been consumed."""
        return self._worker.stream_completed(self._task_id)

    def cancel(self) -> None:
        """Cooperatively stop the producer (actor streams); the stream
        still ends with the completion reply's total."""
        self._worker.cancel_stream_sync(self._task_id)

    def task_id(self):
        return self._task_id

    def __repr__(self) -> str:
        return f"ObjectRefGenerator({self._task_id.hex()[:12]})"

    def __del__(self) -> None:
        w = self._worker
        if w is not None:
            try:
                w.release_stream(self._task_id)
            except Exception:
                pass
