"""ray_tpu.workflow — durable workflows on task DAGs.

Parity target: python/ray/workflow/ (step checkpointing via
WorkflowStorage workflow_storage.py:229, run/resume semantics, events).
"""

from ray_tpu.workflow.api import (WorkflowStatus, delete, get_output,
                                  get_status, init, list_all, resume,
                                  resume_all, run, run_async,
                                  wait_for_event)
from ray_tpu.workflow.storage import WorkflowStorage

__all__ = [
    "WorkflowStatus",
    "WorkflowStorage",
    "init",
    "run",
    "run_async",
    "resume",
    "resume_all",
    "get_status",
    "get_output",
    "list_all",
    "delete",
    "wait_for_event",
]
