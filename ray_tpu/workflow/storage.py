"""WorkflowStorage — durable step-output checkpointing.

Reference: python/ray/workflow/workflow_storage.py:229 (WorkflowStorage)
with the filesystem backend (storage/filesystem.py): one directory per
workflow, one pickle per completed step, a JSON status record, atomic
writes via rename.
"""

from __future__ import annotations

import json
import os
import pickle
import tempfile
from typing import Any, Dict, List, Optional

_DEFAULT_ROOT = os.path.expanduser(
    os.environ.get("RAY_TPU_WORKFLOW_ROOT", "/tmp/ray_tpu/workflows"))


class WorkflowStorage:
    def __init__(self, root: Optional[str] = None):
        self.root = root or _DEFAULT_ROOT
        os.makedirs(self.root, exist_ok=True)

    def _wf_dir(self, workflow_id: str) -> str:
        return os.path.join(self.root, workflow_id)

    def _atomic_write(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise

    # ---- step outputs ----

    def _step_path(self, workflow_id: str, step_id: str) -> str:
        return os.path.join(self._wf_dir(workflow_id), "steps",
                            f"{step_id}.pkl")

    def has_step_output(self, workflow_id: str, step_id: str) -> bool:
        return os.path.exists(self._step_path(workflow_id, step_id))

    def save_step_output(self, workflow_id: str, step_id: str,
                         value: Any) -> None:
        self._atomic_write(self._step_path(workflow_id, step_id),
                           pickle.dumps(value))

    def load_step_output(self, workflow_id: str, step_id: str) -> Any:
        with open(self._step_path(workflow_id, step_id), "rb") as f:
            return pickle.load(f)

    # ---- workflow records ----

    def _meta_path(self, workflow_id: str) -> str:
        return os.path.join(self._wf_dir(workflow_id), "meta.json")

    def save_meta(self, workflow_id: str, meta: Dict[str, Any]) -> None:
        self._atomic_write(self._meta_path(workflow_id),
                           json.dumps(meta).encode())

    def load_meta(self, workflow_id: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._meta_path(workflow_id)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def save_dag(self, workflow_id: str, dag: Any) -> None:
        import cloudpickle

        self._atomic_write(
            os.path.join(self._wf_dir(workflow_id), "dag.pkl"),
            cloudpickle.dumps(dag))

    def load_dag(self, workflow_id: str) -> Any:
        with open(os.path.join(self._wf_dir(workflow_id), "dag.pkl"),
                  "rb") as f:
            return pickle.load(f)

    def list_workflows(self) -> List[str]:
        try:
            return sorted(
                d for d in os.listdir(self.root)
                if os.path.isdir(self._wf_dir(d)))
        except FileNotFoundError:
            return []

    def delete_workflow(self, workflow_id: str) -> bool:
        import shutil

        path = self._wf_dir(workflow_id)
        if os.path.isdir(path):
            shutil.rmtree(path)
            return True
        return False
