"""Durable workflow execution over task DAGs.

Reference: python/ray/workflow/api.py + task_executor.py — a DAG built
with .bind() runs step-by-step; every step's output is checkpointed to
WorkflowStorage before its downstream runs, so a crashed workflow resumes
from its last completed step instead of rerunning finished work.

Step identity: a deterministic id derived from the DAG structure
(function name + argument positions), matching the reference's
name-based step ids. Steps whose id already has a checkpoint are skipped
on resume. Non-deterministic DAG shapes across resumes are the user's
responsibility, as in the reference.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.dag.dag_node import DAGNode, FunctionNode, InputNode
from ray_tpu.workflow.storage import WorkflowStorage


class WorkflowStatus:
    RUNNING = "RUNNING"
    SUCCESSFUL = "SUCCESSFUL"
    FAILED = "FAILED"
    RESUMABLE = "RESUMABLE"


_storage: Optional[WorkflowStorage] = None


def init(storage_root: Optional[str] = None) -> None:
    """Configure the storage root (reference: workflow.init(storage=...))."""
    global _storage
    _storage = WorkflowStorage(storage_root)


def _get_storage() -> WorkflowStorage:
    global _storage
    if _storage is None:
        _storage = WorkflowStorage()
    return _storage


def _assign_step_ids(root: Any) -> Dict[int, str]:
    """Canonical step ids: one per unique node, numbered in deterministic
    first-visit (depth-first, args-then-kwargs) order.

    Keyed per NODE, not per structural path, so a diamond-shaped DAG
    (one node feeding two parents) gets exactly one checkpoint and is
    never re-executed on resume regardless of which parent reaches it
    first."""
    ids: Dict[int, str] = {}
    counter = [0]

    def walk(node: Any) -> None:
        if isinstance(node, (list, tuple)):
            for v in node:
                walk(v)
            return
        if not isinstance(node, DAGNode) or id(node) in ids:
            return
        if isinstance(node, FunctionNode):
            name = getattr(node._remote_fn, "__name__", "fn")
        elif isinstance(node, InputNode):
            name = "input"
        else:
            name = type(node).__name__
        ids[id(node)] = f"{name}-{counter[0]:04d}"
        counter[0] += 1
        for a in node._bound_args:
            walk(a)
        for k in sorted(node._bound_kwargs):
            walk(node._bound_kwargs[k])

    walk(root)
    return ids


def _execute_node(node: Any, workflow_id: str, input_value: Any,
                  storage: WorkflowStorage, step_ids: Dict[int, str],
                  cache: Dict[int, Any]) -> Any:
    """Bottom-up execution with per-step checkpointing."""
    if not isinstance(node, DAGNode):
        if isinstance(node, (list, tuple)):
            return type(node)(
                _execute_node(v, workflow_id, input_value, storage,
                              step_ids, cache)
                for v in node)
        return node
    if id(node) in cache:
        return cache[id(node)]
    if isinstance(node, InputNode):
        cache[id(node)] = input_value
        return input_value

    step_id = step_ids[id(node)]
    if storage.has_step_output(workflow_id, step_id):
        value = storage.load_step_output(workflow_id, step_id)
        cache[id(node)] = value
        return value

    args = tuple(
        _execute_node(a, workflow_id, input_value, storage, step_ids,
                      cache)
        for a in node._bound_args)
    kwargs = {
        k: _execute_node(v, workflow_id, input_value, storage, step_ids,
                         cache)
        for k, v in node._bound_kwargs.items()}

    if isinstance(node, FunctionNode):
        value = ray_tpu.get(node._remote_fn.remote(*args, **kwargs))
    else:
        raise TypeError(
            f"workflows support function DAGs; got {type(node).__name__} "
            "(actor nodes are not durable)")
    storage.save_step_output(workflow_id, step_id, value)
    cache[id(node)] = value
    return value


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        input_value: Any = None) -> Any:
    """Run a DAG durably; returns the root output.

    Reference: workflow.run(dag, workflow_id=...)."""
    workflow_id = workflow_id or f"workflow-{int(time.time() * 1000):x}"
    storage = _get_storage()
    # Preserve the original start_time across failure/resume cycles.
    prev = storage.load_meta(workflow_id) or {}
    start_time = prev.get("start_time", time.time())
    storage.save_meta(workflow_id, {
        "status": WorkflowStatus.RUNNING, "start_time": start_time})
    try:
        storage.save_dag(workflow_id, dag)
    except Exception:
        pass  # non-picklable closures: resume() then needs the dag passed
    try:
        result = _execute_node(dag, workflow_id, input_value, storage,
                               _assign_step_ids(dag), {})
    except Exception as e:
        storage.save_meta(workflow_id, {
            "status": WorkflowStatus.RESUMABLE,
            "error": f"{type(e).__name__}: {e}",
            "start_time": start_time})
        raise
    storage.save_step_output(workflow_id, "__output__", result)
    storage.save_meta(workflow_id, {
        "status": WorkflowStatus.SUCCESSFUL, "start_time": start_time,
        "end_time": time.time()})
    return result


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              input_value: Any = None):
    """Returns a concurrent.futures.Future of the workflow output."""
    import concurrent.futures

    pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
    fut = pool.submit(run, dag, workflow_id=workflow_id,
                      input_value=input_value)
    pool.shutdown(wait=False)
    return fut


def resume(workflow_id: str, dag: Optional[DAGNode] = None,
           input_value: Any = None) -> Any:
    """Re-run a workflow; completed steps load from their checkpoints."""
    storage = _get_storage()
    if storage.has_step_output(workflow_id, "__output__"):
        return storage.load_step_output(workflow_id, "__output__")
    if dag is None:
        dag = storage.load_dag(workflow_id)
    return run(dag, workflow_id=workflow_id, input_value=input_value)


def resume_all() -> List[str]:
    """Resume every RESUMABLE workflow; returns their ids."""
    storage = _get_storage()
    resumed = []
    for wid in storage.list_workflows():
        meta = storage.load_meta(wid) or {}
        if meta.get("status") == WorkflowStatus.RESUMABLE:
            try:
                resume(wid)
                resumed.append(wid)
            except Exception:
                pass
    return resumed


def get_status(workflow_id: str) -> Optional[str]:
    meta = _get_storage().load_meta(workflow_id)
    return meta.get("status") if meta else None


def get_output(workflow_id: str) -> Any:
    storage = _get_storage()
    if not storage.has_step_output(workflow_id, "__output__"):
        raise ValueError(f"workflow {workflow_id!r} has no output yet")
    return storage.load_step_output(workflow_id, "__output__")


def list_all() -> List[Dict[str, Any]]:
    storage = _get_storage()
    return [{"workflow_id": wid,
             **(storage.load_meta(wid) or {})}
            for wid in storage.list_workflows()]


def delete(workflow_id: str) -> bool:
    return _get_storage().delete_workflow(workflow_id)


def wait_for_event(poll_fn: Callable[[], Any], timeout_s: float = 300.0,
                   poll_interval_s: float = 0.5) -> Any:
    """Minimal event-listener analog (reference: event_listener.py):
    polls until poll_fn returns a truthy value, then returns it. Use
    inside a step function to gate on external state."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = poll_fn()
        if value:
            return value
        time.sleep(poll_interval_s)
    raise TimeoutError("wait_for_event timed out")
