"""JobSubmissionClient + the JobSupervisor actor.

Reference: python/ray/dashboard/modules/job/sdk.py:35 (submit_job :125),
job_manager.py (JobManager + JobSupervisor actor running the entrypoint
shell command). Metadata is stored in the GCS KV under the "job" namespace
so any client connected to the cluster can list/poll jobs.
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu

_KV_NS = b"job_submission"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    submission_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    message: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    metadata: Dict[str, str] = field(default_factory=dict)

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_json(raw: bytes) -> "JobInfo":
        return JobInfo(**json.loads(raw))


class JobSupervisor:
    """Detached actor that runs one job's entrypoint as a subprocess.

    Reference: dashboard/modules/job/job_supervisor.py — owns the child
    process, streams logs to a file, records the terminal status in KV.
    """

    def __init__(self, submission_id: str, entrypoint: str,
                 env: Optional[Dict[str, str]] = None,
                 log_dir: Optional[str] = None,
                 metadata: Optional[Dict[str, str]] = None):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.log_dir = log_dir or "/tmp/ray_tpu/job_logs"
        os.makedirs(self.log_dir, exist_ok=True)
        self.log_path = os.path.join(self.log_dir,
                                     f"{submission_id}.log")
        self.proc: Optional[subprocess.Popen] = None
        self._env = dict(env or {})
        self._metadata = dict(metadata or {})
        self._lock = threading.Lock()
        self._stop_requested = False
        self._status = JobStatus.PENDING
        self._message = ""
        self._start_time = time.time()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _put_info(self) -> None:
        info = JobInfo(
            submission_id=self.submission_id,
            entrypoint=self.entrypoint,
            status=self._status,
            message=self._message,
            start_time=self._start_time,
            end_time=time.time() if self._status in JobStatus.TERMINAL
            else 0.0,
            metadata=self._metadata)
        from ray_tpu._private.worker import global_worker

        global_worker().gcs_call("kv_put", {
            "ns": _KV_NS, "key": self.submission_id.encode(),
            "value": info.to_json()})

    def _run(self) -> None:
        env = dict(os.environ)
        env.update(self._env)
        env["RAY_TPU_JOB_SUBMISSION_ID"] = self.submission_id
        # Let the entrypoint connect to this cluster with
        # ray_tpu.init(address=os.environ["RAY_TPU_ADDRESS"]).
        try:
            from ray_tpu._private.worker import global_worker

            env["RAY_TPU_ADDRESS"] = global_worker().core.gcs_address
        except Exception:
            pass
        try:
            with self._lock:
                if self._stop_requested:
                    self._status = JobStatus.STOPPED
                    self._put_info()
                    return
                self._status = JobStatus.RUNNING
                log = open(self.log_path, "wb")
                self.proc = subprocess.Popen(
                    self.entrypoint, shell=True, stdout=log,
                    stderr=subprocess.STDOUT, env=env,
                    start_new_session=True)
            self._put_info()
            with log:
                code = self.proc.wait()
            with self._lock:
                if self._stop_requested:
                    self._status = JobStatus.STOPPED
                elif code == 0:
                    self._status = JobStatus.SUCCEEDED
                else:
                    self._status = JobStatus.FAILED
                    self._message = f"entrypoint exited with code {code}"
        except Exception as e:
            self._status = JobStatus.FAILED
            self._message = f"{type(e).__name__}: {e}"
        self._put_info()

    def status(self) -> str:
        return self._status

    def logs(self) -> str:
        try:
            with open(self.log_path, "rb") as f:
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""

    def read_logs(self, offset: int = 0) -> bytes:
        """Raw bytes from offset — incremental tailing stays O(n) and
        byte offsets never drift on multibyte characters (the client
        decodes incrementally)."""
        try:
            with open(self.log_path, "rb") as f:
                f.seek(offset)
                return f.read()
        except FileNotFoundError:
            return b""

    def log_size(self) -> int:
        try:
            return os.path.getsize(self.log_path)
        except OSError:
            return 0

    def stop(self) -> bool:
        with self._lock:
            if self._status in JobStatus.TERMINAL:
                return False
            self._stop_requested = True
            proc = self.proc
        if proc and proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), 15)
            except Exception:
                proc.terminate()
            return True
        # Not launched yet: _run will observe the flag and mark STOPPED.
        return True

    def ping(self) -> bool:
        return True


class JobSubmissionClient:
    """Reference: python/ray/job_submission/JobSubmissionClient — same
    method surface (submit_job/get_job_status/get_job_logs/stop_job/
    list_jobs/delete_job), minus the HTTP hop."""

    def __init__(self, address: Optional[str] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address)

    def _gcs(self, method: str, data: dict):
        from ray_tpu._private.worker import global_worker

        return global_worker().gcs_call(method, data)

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   entrypoint_num_cpus: float = 1.0) -> str:
        submission_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        env = {}
        if runtime_env and runtime_env.get("env_vars"):
            env.update(runtime_env["env_vars"])
        info = JobInfo(submission_id=submission_id, entrypoint=entrypoint,
                       metadata=dict(metadata or {}),
                       start_time=time.time())
        self._gcs("kv_put", {"ns": _KV_NS,
                             "key": submission_id.encode(),
                             "value": info.to_json()})
        supervisor_cls = ray_tpu.remote(JobSupervisor)
        supervisor_cls.options(
            name=f"_job_supervisor:{submission_id}",
            namespace="ray_tpu.jobs",
            lifetime="detached",
            num_cpus=entrypoint_num_cpus,
        ).remote(submission_id, entrypoint, env,
                 metadata=dict(metadata or {}))
        return submission_id

    def _supervisor(self, submission_id: str):
        from ray_tpu.core.actor import get_actor

        return get_actor(f"_job_supervisor:{submission_id}",
                         namespace="ray_tpu.jobs")

    def get_job_info(self, submission_id: str) -> JobInfo:
        raw = self._gcs("kv_get", {"ns": _KV_NS,
                                   "key": submission_id.encode()})
        if raw is None:
            raise ValueError(f"no job {submission_id!r}")
        return JobInfo.from_json(raw)

    def get_job_status(self, submission_id: str) -> str:
        # Prefer the live supervisor; fall back to the KV record (e.g.
        # after the supervisor exited or its node died).
        try:
            sup = self._supervisor(submission_id)
            return ray_tpu.get(sup.status.remote(), timeout=10.0)
        except Exception:
            return self.get_job_info(submission_id).status

    def get_job_logs(self, submission_id: str) -> str:
        sup = self._supervisor(submission_id)
        return ray_tpu.get(sup.logs.remote(), timeout=10.0)

    def stop_job(self, submission_id: str) -> bool:
        try:
            sup = self._supervisor(submission_id)
            return ray_tpu.get(sup.stop.remote(), timeout=10.0)
        except ValueError:
            return False

    def delete_job(self, submission_id: str) -> bool:
        # Stop first: killing only the supervisor actor would orphan the
        # entrypoint subprocess (it runs in its own session).
        try:
            status = self.get_job_status(submission_id)
            if status not in JobStatus.TERMINAL:
                self.stop_job(submission_id)
                deadline = time.time() + 10
                while time.time() < deadline and \
                        self.get_job_status(submission_id) not in \
                        JobStatus.TERMINAL:
                    time.sleep(0.2)
        except Exception:
            pass
        try:
            sup = self._supervisor(submission_id)
            ray_tpu.kill(sup)
        except Exception:
            pass
        return bool(self._gcs("kv_del", {"ns": _KV_NS,
                                         "key": submission_id.encode()}))

    def list_jobs(self) -> List[JobInfo]:
        keys = self._gcs("kv_keys", {"ns": _KV_NS}) or []
        out = []
        for key in keys:
            raw = self._gcs("kv_get", {"ns": _KV_NS, "key": key})
            if raw:
                out.append(JobInfo.from_json(raw))
        return out

    def tail_job_logs(self, submission_id: str,
                      poll_interval_s: float = 0.5):
        """Generator yielding log increments until the job terminates.
        Polls with a byte offset so each RPC ships only new output; an
        incremental decoder keeps multibyte chars intact across reads."""
        import codecs

        sup = self._supervisor(submission_id)
        decoder = codecs.getincrementaldecoder("utf-8")(errors="replace")
        offset = 0

        def _read():
            nonlocal offset
            raw = ray_tpu.get(sup.read_logs.remote(offset), timeout=10.0)
            offset += len(raw)
            return decoder.decode(raw) if raw else ""

        while True:
            chunk = _read()
            if chunk:
                yield chunk
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                chunk = _read() + decoder.decode(b"", final=True)
                if chunk:
                    yield chunk
                return
            time.sleep(poll_interval_s)
