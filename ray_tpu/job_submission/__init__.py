"""Job submission: run shell entrypoints as supervised cluster jobs.

Reference: python/ray/job_submission/ + dashboard/modules/job/
(JobSubmissionClient.submit_job sdk.py:35 → REST → JobManager spawns a
supervisor actor running the entrypoint command, job_manager.py). Here the
client talks straight to the cluster (no REST hop): job metadata lives in
the GCS KV, and each job runs under a detached JobSupervisor actor.
"""

from ray_tpu.job_submission.sdk import (JobStatus, JobSubmissionClient,
                                        JobInfo)

__all__ = ["JobSubmissionClient", "JobStatus", "JobInfo"]
