"""serve.llm — thin deployment shim over the LLM serving fleet.

The fleet itself (router + replica pool + autoscaler) is pure models/
code (`ray_tpu.models.fleet.LLMFleet`) and knows nothing about Serve.
This module is the glue that makes it deployable:

- `LLMFleetServer` is a deployment body: construct it with an engine
  factory and (optionally) a `FleetAutoscalingConfig`, call
  `generate()` per request. Works equally outside Serve (tests,
  notebooks drive it directly) and inside a replica, where every
  `generate` also publishes the fleet's `stats()` snapshot through the
  serve metric plane and records the fleet's scaling signal via
  `record_autoscaling_metric` — so the serve CONTROLLER's own
  autoscaler (scaling replica actors, each holding a whole fleet) sees
  the same pressure the fleet-internal scaler acts on.

- `llm_deployment(...)` wraps it in `@serve.deployment` with the
  usual options.

Custom-metric wiring (the previously dangling seam): when the fleet's
`FleetAutoscalingConfig` sets `target_custom_metric` but no
`custom_metric_source`, the shim plugs in
`serve.metrics.recorded_autoscaling_metric` — so any scalar the
replica publishes with `serve.metrics.record_autoscaling_metric(v)`
becomes a live scale-up/-down signal for the fleet autoscaler.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Union

from ray_tpu.models.fleet import (FleetAutoscalingConfig,
                                  FleetHealthConfig, FleetRouter,
                                  LLMFleet)
from ray_tpu.serve import metrics as serve_metrics

__all__ = ["LLMFleetServer", "llm_deployment"]


class LLMFleetServer:
    """Deployment body fronting one `LLMFleet`.

    ``engine_factory(name) -> DecodeEngine`` builds each replica's
    engine. ``autoscaling`` may be a `FleetAutoscalingConfig` or a
    plain dict of its kwargs (config-file friendly), and ``health``
    (a `FleetHealthConfig` or dict) tunes the fleet's replica health
    state machine / retry policy the same way. All other kwargs pass
    through to `LLMFleet`."""

    def __init__(self, engine_factory: Callable[[str], object], *,
                 router: Union[str, FleetRouter] = "pow2_affinity",
                 autoscaling: Union[FleetAutoscalingConfig, dict,
                                    None] = None,
                 health: Union[FleetHealthConfig, dict, None] = None,
                 fleet_id: str = "llm-fleet",
                 report_stats: bool = True,
                 **fleet_kwargs):
        if isinstance(autoscaling, dict):
            autoscaling = FleetAutoscalingConfig(**autoscaling)
        if isinstance(health, dict):
            health = FleetHealthConfig(**health)
        if autoscaling is not None and \
                autoscaling.target_custom_metric is not None and \
                autoscaling.custom_metric_source is None:
            # The dangling seam, closed: scalars recorded through
            # serve.metrics.record_autoscaling_metric now feed the
            # fleet autoscaler's custom-metric breach check.
            autoscaling.custom_metric_source = \
                serve_metrics.recorded_autoscaling_metric
        self.fleet = LLMFleet(engine_factory, router=router,
                              autoscaling=autoscaling, health=health,
                              fleet_id=fleet_id, **fleet_kwargs)
        self._report_stats = report_stats
        # Serving state API registration (weak): the deployment body
        # shows up in `ray_tpu.util.state.servers()` beside the fleet
        # and engines it fronts.
        from ray_tpu.util.state.serving import register_server
        register_server(self)

    def register_model(self, model_id: str, lora_params) -> None:
        """Admit a LoRA fine-tune under a serving model id: fans out
        to every fleet replica's AdapterPool (and future replicas).
        `generate(model_id=...)` — or the Serve multiplex header, via
        `get_multiplexed_model_id()` — then resolves through this
        table to a per-row adapter in the shared batch."""
        self.fleet.register_adapter(model_id, lora_params)

    def unregister_model(self, model_id: str, *_evicted) -> None:
        """Drop a registered fine-tune fleet-wide. Also suitable as a
        `serve.multiplexed(on_evict=...)` callback (extra positional
        model payload ignored), so multiplex LRU eviction and the
        adapter pools cannot disagree about residency."""
        self.fleet.unregister_adapter(model_id)

    def model_ids(self) -> List[str]:
        return self.fleet.adapter_ids()

    def generate(self, token_ids: List[int],
                 max_new_tokens: int = 32, priority: int = 0,
                 deadline_s: Optional[float] = None,
                 model_id: Optional[str] = None) -> Dict:
        """Route one request through the fleet and drive it to
        completion. Returns ``{"tokens": prompt + generated,
        "shed": bool}`` — a shed request (past its deadline before
        prefill) comes back with the bare prompt and shed=True instead
        of an error, so callers distinguish 'declined under overload'
        from failure. A request whose replica DIED propagates the
        fleet's typed error (`RetriesExhausted` after the retry budget,
        `ReplicaUnavailable` with no replica left to recover onto)
        instead of looping forever — failed requests join `finished`
        and `pop_result` raises.

        ``model_id`` selects a fine-tune registered through
        `register_model` (None/"" = base model). When omitted INSIDE a
        Serve replica, it defaults to the request's multiplex header
        (`serve.get_multiplexed_model_id()`), so a deployment fronted
        by the Serve router's multiplex-aware placement resolves to
        the right adapter with no per-call plumbing. An id that was
        never registered raises KeyError — not silent base-model
        fallback, which would return wrong-model tokens."""
        if model_id is None:
            from ray_tpu.serve.multiplex import get_multiplexed_model_id
            model_id = get_multiplexed_model_id()
        adapter_id = model_id or None      # "" = no multiplex header
        fid = self.fleet.submit(token_ids, max_new_tokens,
                                priority=priority,
                                deadline_s=deadline_s,
                                adapter_id=adapter_id)
        while fid not in self.fleet.finished:
            self.fleet.step()
        shed = fid in self.fleet.shed_ids
        out = self.fleet.pop_result(fid)
        if self._report_stats:
            self._publish()
        return {"tokens": list(token_ids) + out, "shed": shed}

    def _publish(self) -> None:
        """Fleet stats() -> serve-tagged gauges, plus the replica-level
        autoscaling scalar (queued work per running replica — the
        controller's cue that this whole-fleet replica is saturating).
        Publishing the scalar through record_autoscaling_metric ALSO
        makes it visible to the fleet-internal autoscaler when its
        config targets the custom metric, closing the loop both ways.
        Outside a replica the gauges still record (untagged) and the
        scalar is skipped."""
        stats = self.fleet.stats()
        serve_metrics.report_engine_stats(stats,
                                          prefix="serve_llm_fleet")
        from ray_tpu.serve._private.replica import get_current_replica
        if get_current_replica() is not None:
            per_rep = stats["queue_depth"] / max(
                stats["replicas_running"], 1.0)
            serve_metrics.record_autoscaling_metric(per_rep)

    def stats(self) -> Dict[str, float]:
        return self.fleet.stats()

    def dump_trace(self, path: Optional[str] = None) -> List[Dict]:
        """chrome://tracing export of the fleet's request-lifecycle
        spans (route spans + every traced replica's engine spans) —
        `LLMFleet.dump_trace` passed through, so a Serve handle can
        pull a timeline off a live deployment:
        ``handle.dump_trace.remote()``. Empty when tracing is off
        (``trace=`` knob / RAY_TPU_TRACE env gate)."""
        return self.fleet.dump_trace(path)

    def drain(self) -> None:
        """Flush every replica (prepare_for_shutdown hook): finish all
        queued/in-flight work so a replica actor holding this fleet
        can exit without losing tokens."""
        for rep in list(self.fleet.replicas):
            self.fleet.drain_replica(rep.name)
        self.fleet.run()


def llm_deployment(engine_factory: Callable[[str], object], *,
                   name: str = "llm", **deployment_options):
    """`LLMFleetServer` as a bound serve application:

        app = llm_deployment(factory,
                             autoscaling={"max_replicas": 4})
        handle = serve.run(app)
        handle.generate.remote([1, 2, 3], max_new_tokens=16)

    Keyword args that `LLMFleetServer` understands (router,
    autoscaling, fleet_id, initial_replicas, ...) are forwarded to it
    at bind time; the rest are `@serve.deployment` options."""
    from ray_tpu.serve.deployment import deployment

    shim_keys = ("router", "autoscaling", "health", "fleet_id",
                 "report_stats", "initial_replicas", "trace", "clock",
                 "rng_seed", "fault_injector")
    shim_kwargs = {k: deployment_options.pop(k)
                   for k in list(deployment_options)
                   if k in shim_keys}
    dep = deployment(name=name, **deployment_options)(LLMFleetServer)
    return dep.bind(engine_factory, **shim_kwargs)
