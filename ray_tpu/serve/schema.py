"""Serve declarative config: YAML/dict schema + build/deploy.

Reference: python/ray/serve/schema.py (ServeDeploySchema: applications
with import_path + per-deployment overrides) and the `serve deploy` /
`serve build` CLI. An application's import_path points at a bound
Application object (`module.sub:app`); per-deployment option overrides
from the config are applied before serve.run.
"""

from __future__ import annotations

import dataclasses
import importlib
import json
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class DeploymentSchema:
    name: str
    num_replicas: Optional[int] = None
    max_ongoing_requests: Optional[int] = None
    user_config: Optional[dict] = None
    autoscaling_config: Optional[dict] = None
    ray_actor_options: Optional[dict] = None

    def overrides(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for field in ("num_replicas", "max_ongoing_requests",
                      "user_config", "autoscaling_config",
                      "ray_actor_options"):
            v = getattr(self, field)
            if v is not None:
                out[field] = v
        return out


@dataclasses.dataclass
class ApplicationSchema:
    import_path: str
    name: str = "default"
    # "/" when omitted; an EXPLICIT null in the config means handle-only
    # (no HTTP route) — serve.run(route_prefix=None) semantics.
    route_prefix: Optional[str] = "/"
    args: Dict[str, Any] = dataclasses.field(default_factory=dict)
    deployments: List[DeploymentSchema] = dataclasses.field(
        default_factory=list)

    @staticmethod
    def from_dict(d: dict) -> "ApplicationSchema":
        deps = [DeploymentSchema(**dep)
                for dep in d.get("deployments", [])]
        return ApplicationSchema(
            import_path=d["import_path"],
            name=d.get("name", "default"),
            route_prefix=d.get("route_prefix", "/"),
            args=d.get("args", {}),
            deployments=deps)


@dataclasses.dataclass
class ServeDeploySchema:
    applications: List[ApplicationSchema]

    @staticmethod
    def from_dict(d: dict) -> "ServeDeploySchema":
        schema = ServeDeploySchema(
            applications=[ApplicationSchema.from_dict(a)
                          for a in d.get("applications", [])])
        prefixes = [a.route_prefix for a in schema.applications
                    if a.route_prefix is not None]
        dupes = {p for p in prefixes if prefixes.count(p) > 1}
        if dupes:
            raise ValueError(
                f"route_prefix collision across applications: "
                f"{sorted(dupes)!r} — give each app a distinct prefix "
                "(or route_prefix: null for handle-only apps)")
        return schema

    @staticmethod
    def from_file(path: str) -> "ServeDeploySchema":
        with open(path) as f:
            text = f.read()
        try:
            import yaml

            data = yaml.safe_load(text)
        except ImportError:
            try:
                data = json.loads(text)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path!r} is not valid JSON and PyYAML is not "
                    "installed — install PyYAML for YAML configs or "
                    "provide the config as JSON") from e
        return ServeDeploySchema.from_dict(data)


def _import_application(import_path: str, args: Dict[str, Any]):
    """'pkg.module:attr' -> a bound Application. `attr` may be the app
    itself or a builder fn taking the schema args dict."""
    module_path, _, attr = import_path.partition(":")
    if not attr:
        raise ValueError(
            f"import_path {import_path!r} must be 'module.sub:attr'")
    module = importlib.import_module(module_path)
    target = getattr(module, attr)
    if hasattr(target, "deployments"):  # already a bound Application
        return target
    if callable(target):  # app builder fn
        return target(args)
    raise TypeError(
        f"{import_path!r} is neither a bound Application nor a builder")


def deploy_from_schema(schema: ServeDeploySchema) -> Dict[str, Any]:
    """Run every application in the schema; returns name -> handle."""
    from ray_tpu import serve

    handles = {}
    for app_schema in schema.applications:
        app = _import_application(app_schema.import_path,
                                  app_schema.args)
        overrides = {d.name: d.overrides()
                     for d in app_schema.deployments}
        if overrides:
            unknown = set(overrides) - set(app.deployments)
            if unknown:
                raise ValueError(
                    f"config overrides for unknown deployments "
                    f"{sorted(unknown)!r}; app {app_schema.name!r} has "
                    f"{sorted(app.deployments)!r}")
            app = app.with_deployment_overrides(overrides)
        handles[app_schema.name] = serve.run(
            app, name=app_schema.name,
            route_prefix=app_schema.route_prefix)
    return handles


def deploy_config_file(path: str) -> Dict[str, Any]:
    return deploy_from_schema(ServeDeploySchema.from_file(path))
