"""serve.start / run / shutdown / status / handles.

Reference: python/ray/serve/api.py — serve.run (:535) deploys an
Application to the controller and returns the ingress DeploymentHandle;
serve.start boots the controller + HTTP proxy.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.core.actor import get_actor
from ray_tpu.serve.config import HTTPOptions
from ray_tpu.serve.deployment import Application, build_app
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve._private.common import (
    SERVE_CONTROLLER_NAME, SERVE_DEFAULT_APP_NAME, SERVE_NAMESPACE)

logger = logging.getLogger(__name__)

_controller_handle = None


def _get_controller(create: bool = False,
                    http_options: Optional[HTTPOptions] = None):
    global _controller_handle
    if _controller_handle is not None:
        return _controller_handle
    try:
        _controller_handle = get_actor(SERVE_CONTROLLER_NAME,
                                       namespace=SERVE_NAMESPACE)
        return _controller_handle
    except Exception:
        if not create:
            raise RuntimeError(
                "Serve is not running; call serve.start() or serve.run()")
    from ray_tpu.serve._private.controller import ServeController

    if isinstance(http_options, dict):  # reference: serve.start accepts
        http_options = HTTPOptions(**http_options)  # plain dicts too
    http_dict = (http_options or HTTPOptions()).to_dict()
    _controller_handle = ServeController.options(
        name=SERVE_CONTROLLER_NAME).remote(http_dict)
    # Fire-and-forget: the reconcile loop runs for the controller's life.
    _controller_handle.run_control_loop.remote()
    return _controller_handle


def start(http_options: Optional[HTTPOptions] = None, *,
          proxy: bool = True) -> None:
    """Boot the Serve control plane (controller + optional HTTP proxy).
    Reference: serve.start (python/ray/serve/api.py:83)."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    if isinstance(http_options, dict):
        http_options = HTTPOptions(**http_options)
    controller = _get_controller(create=True, http_options=http_options)
    if proxy:
        _ensure_proxy(controller, http_options)


def _ensure_proxy(controller,
                  http_options: Optional[HTTPOptions] = None) -> None:
    from ray_tpu.serve._private.proxy import ProxyActor

    try:
        get_actor("SERVE_PROXY", namespace=SERVE_NAMESPACE)
        return
    except Exception:
        pass
    if http_options is not None:
        http = http_options.to_dict()
    else:
        http = ray_tpu.get(controller.get_http_options.remote(), timeout=30)
    proxy = ProxyActor.options(
        name="SERVE_PROXY", namespace=SERVE_NAMESPACE,
        lifetime="detached", max_concurrency=1000).remote(http)
    # Block until the HTTP server is listening.
    ray_tpu.get(proxy.ready.remote(), timeout=60)


def run(app: Application, *, name: str = SERVE_DEFAULT_APP_NAME,
        route_prefix: Optional[str] = "/", blocking: bool = False,
        _proxy: bool = True, timeout_s: float = 60.0) -> DeploymentHandle:
    """Deploy an application and wait for it to be RUNNING.
    Reference: serve.run (python/ray/serve/api.py:535, _run :459)."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    controller = _get_controller(create=True)
    if _proxy and route_prefix is not None:
        _ensure_proxy(controller)
    payloads = build_app(app, name)
    ingress = payloads[-1]["name"]  # root visited last (post-order append)
    ray_tpu.get(controller.deploy_application.remote(
        name, payloads, route_prefix), timeout=30)
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        statuses = ray_tpu.get(controller.get_app_statuses.remote(),
                               timeout=30)
        st = statuses.get(name, {})
        if st.get("status") == "RUNNING":
            break
        if st.get("status") == "DEPLOY_FAILED":
            raise RuntimeError(
                f"deploying app {name!r} failed: {st.get('message')}")
        time.sleep(0.1)
    else:
        raise TimeoutError(
            f"app {name!r} did not become RUNNING within {timeout_s}s")
    handle = DeploymentHandle(ingress, name)
    if blocking:
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            pass
    return handle


def delete(name: str, _blocking: bool = True,
           timeout_s: float = 30.0) -> None:
    controller = _get_controller()
    ray_tpu.get(controller.delete_application.remote(name), timeout=30)
    if _blocking:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            statuses = ray_tpu.get(controller.get_app_statuses.remote(),
                                   timeout=30)
            if name not in statuses:
                return
            time.sleep(0.1)


def status() -> dict:
    """Cluster-wide Serve status (reference: serve.status →
    python/ray/serve/schema.py ServeStatus)."""
    try:
        controller = _get_controller()
    except RuntimeError:
        return {"applications": {}, "proxies": {}}
    apps = ray_tpu.get(controller.get_app_statuses.remote(), timeout=30)
    return {"applications": apps, "proxies": _proxy_status()}


def _proxy_status() -> dict:
    try:
        proxy = get_actor("SERVE_PROXY", namespace=SERVE_NAMESPACE)
        return ray_tpu.get(proxy.status.remote(), timeout=5)
    except Exception:
        return {}


def get_app_handle(name: str = SERVE_DEFAULT_APP_NAME) -> DeploymentHandle:
    controller = _get_controller()
    statuses = ray_tpu.get(controller.get_app_statuses.remote(), timeout=30)
    if name not in statuses:
        raise ValueError(f"no application named {name!r}")
    route_table = ray_tpu.get(controller.get_route_table.remote(),
                              timeout=30)
    for _prefix, entry in route_table.items():
        if entry["app_name"] == name:
            return DeploymentHandle(entry["deployment"], name)
    # No route (route_prefix=None): find the app's ingress deployment.
    deployments = statuses[name].get("deployments", {})
    if not deployments:
        raise ValueError(f"application {name!r} has no deployments")
    return DeploymentHandle(next(iter(deployments)), name)


def get_deployment_handle(deployment_name: str,
                          app_name: str = SERVE_DEFAULT_APP_NAME
                          ) -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def shutdown() -> None:
    """Tear down all Serve actors (reference: serve.shutdown)."""
    global _controller_handle
    from ray_tpu.serve._private.router import Router

    Router.stop_all()
    try:
        controller = _get_controller()
    except Exception:
        _controller_handle = None
        return
    try:
        ray_tpu.get(controller.graceful_shutdown.remote(), timeout=60)
    except Exception:
        pass
    try:
        proxy = get_actor("SERVE_PROXY", namespace=SERVE_NAMESPACE)
        ray_tpu.get(proxy.stop_server.remote(), timeout=10)
        ray_tpu.kill(proxy)
    except Exception:
        pass
    try:
        ray_tpu.kill(controller)
    except Exception:
        pass
    _controller_handle = None
