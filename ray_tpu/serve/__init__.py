"""ray_tpu.serve — scalable model serving on the TPU-native runtime.

Reference: python/ray/serve/__init__.py public API. Architecture mirrors
the reference (controller actor + HTTP proxy + power-of-two router +
replica actors) with TPU-first replicas: deployments hold jitted JAX
callables and the router keeps batches large for the MXU.
"""

from ray_tpu.serve.api import (
    delete,
    get_app_handle,
    get_deployment_handle,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.asgi import HTTPResponse
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig, \
    HTTPOptions
from ray_tpu.serve.deployment import Application, Deployment, deployment, \
    ingress
from ray_tpu.serve.handle import (DeploymentHandle, DeploymentResponse,
                                  DeploymentResponseGenerator)
from ray_tpu.serve import metrics
from ray_tpu.serve import llm
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve._private.proxy import ServeRequest
from ray_tpu.serve.schema import (ApplicationSchema, DeploymentSchema,
                                  ServeDeploySchema, deploy_config_file,
                                  deploy_from_schema)

__all__ = [
    "ApplicationSchema",
    "DeploymentSchema",
    "ServeDeploySchema",
    "deploy_config_file",
    "deploy_from_schema",
    "Application",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentConfig",
    "HTTPResponse",
    "DeploymentHandle",
    "DeploymentResponse",
    "DeploymentResponseGenerator",
    "HTTPOptions",
    "ServeRequest",
    "batch",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "get_multiplexed_model_id",
    "ingress",
    "llm",
    "metrics",
    "multiplexed",
    "run",
    "shutdown",
    "start",
    "status",
]
