"""@serve.deployment decorator + Application bind graph.

Reference: python/ray/serve/deployment.py (Deployment, Application) and
python/ray/serve/api.py:@deployment. ``D.bind(*args)`` builds a lazy graph;
nested bound deployments become DeploymentHandles at deploy time (model
composition, reference python/ray/serve/_private/build_app.py).
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from ray_tpu.core import serialization as ser
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.handle import DeploymentHandle


class Deployment:
    def __init__(self, func_or_class: Union[Callable, type],
                 name: str, config: DeploymentConfig):
        self._func_or_class = func_or_class
        self._name = name
        self._config = config

    @property
    def name(self) -> str:
        return self._name

    @property
    def func_or_class(self):
        return self._func_or_class

    def options(self, **kwargs) -> "Deployment":
        cfg = DeploymentConfig.from_dict(self._config.to_dict())
        name = kwargs.pop("name", self._name)
        auto = kwargs.pop("autoscaling_config", None)
        if auto is not None and not isinstance(auto, AutoscalingConfig):
            auto = AutoscalingConfig(**auto)
        if auto is not None:
            cfg.autoscaling_config = auto
        for k, v in kwargs.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown deployment option {k!r}")
            setattr(cfg, k, v)
        return Deployment(self._func_or_class, name, cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __call__(self, *a, **kw):
        raise RuntimeError(
            "deployments cannot be called directly; use handle.remote() "
            "or serve.run(deployment.bind())")


class Application:
    """A bound deployment (possibly with nested bound deployments in its
    init args)."""

    def __init__(self, deployment: Deployment, init_args: tuple,
                 init_kwargs: dict):
        self._deployment = deployment
        self._init_args = init_args
        self._init_kwargs = init_kwargs

    @property
    def deployment(self) -> Deployment:
        return self._deployment

    @staticmethod
    def _map_graph(value, on_app):
        """One container-aware traversal shared by every bind-graph walk
        (matches build_app's resolve(): Applications may be nested in
        lists/tuples/dicts of init args)."""
        if isinstance(value, Application):
            return on_app(value)
        if isinstance(value, (list, tuple)):
            return type(value)(Application._map_graph(x, on_app)
                               for x in value)
        if isinstance(value, dict):
            return {k: Application._map_graph(x, on_app)
                    for k, x in value.items()}
        return value

    @property
    def deployments(self) -> list:
        """Names of every unique deployment in the bind graph (shared
        nodes counted once; container-nested bindings included)."""
        names = []
        seen = set()

        def visit(app: "Application"):
            if id(app) not in seen:
                seen.add(id(app))
                names.append(app._deployment.name)
                for a in list(app._init_args) + \
                        list(app._init_kwargs.values()):
                    Application._map_graph(a, visit)
            return app

        visit(self)
        return names

    def with_deployment_overrides(self,
                                  overrides: dict) -> "Application":
        """Rebuild the bind graph applying per-deployment option
        overrides (declarative config; reference: config deployments
        overriding code-declared options). Shared nodes stay shared —
        build_app dedups by object identity, so a diamond graph must map
        each original node to exactly ONE rebuilt node. Applications
        nested inside list/tuple/dict init args are handled like
        build_app does."""
        rebuilt: dict = {}

        def rebuild(app: "Application") -> "Application":
            cached = rebuilt.get(id(app))
            if cached is not None:
                return cached
            dep = app._deployment
            ov = overrides.get(dep.name)
            if ov:
                dep = dep.options(**ov)
            args = tuple(Application._map_graph(a, rebuild)
                         for a in app._init_args)
            kwargs = {k: Application._map_graph(v, rebuild)
                      for k, v in app._init_kwargs.items()}
            new = Application(dep, args, kwargs)
            rebuilt[id(app)] = new
            return new

        return rebuild(self)


def build_app(app: Application, app_name: str) -> List[dict]:
    """Flatten the bind graph into controller deploy payloads. The root is
    the ingress deployment; nested Applications are replaced with
    DeploymentHandles (reference build_app.py)."""
    out: List[dict] = []
    seen: Dict[int, str] = {}
    used_names: Dict[str, int] = {}

    def unique_name(base: str) -> str:
        n = used_names.get(base, 0)
        used_names[base] = n + 1
        return base if n == 0 else f"{base}_{n}"

    def visit(node: Application, is_ingress: bool) -> str:
        if id(node) in seen:
            return seen[id(node)]
        name = unique_name(node._deployment.name)
        seen[id(node)] = name

        def resolve(v):
            if isinstance(v, Application):
                return DeploymentHandle(visit(v, False), app_name)
            if isinstance(v, (list, tuple)):
                return type(v)(resolve(x) for x in v)
            if isinstance(v, dict):
                return {k: resolve(x) for k, x in v.items()}
            return v

        args = tuple(resolve(a) for a in node._init_args)
        kwargs = {k: resolve(v) for k, v in node._init_kwargs.items()}
        out.append({
            "name": name,
            "serialized_def": ser.dumps(node._deployment.func_or_class),
            "init_args_blob": ser.dumps((args, kwargs)),
            "config_dict": node._deployment._config.to_dict(),
            "is_ingress": is_ingress,
        })
        return name

    visit(app, True)
    return out


def deployment(_func_or_class: Optional[Callable] = None, *,
               name: Optional[str] = None,
               num_replicas: Optional[Union[int, str]] = None,
               autoscaling_config: Optional[Union[dict,
                                                  AutoscalingConfig]] = None,
               max_ongoing_requests: int = 5,
               user_config: Any = None,
               ray_actor_options: Optional[dict] = None,
               health_check_period_s: float = 10.0,
               health_check_timeout_s: float = 30.0,
               graceful_shutdown_timeout_s: float = 20.0,
               version: Optional[str] = None):
    """@serve.deployment (reference python/ray/serve/api.py:deployment)."""

    def wrap(func_or_class):
        nonlocal autoscaling_config, num_replicas
        if num_replicas == "auto":
            num_replicas = None
            if autoscaling_config is None:
                autoscaling_config = AutoscalingConfig(min_replicas=1,
                                                      max_replicas=100)
        if isinstance(autoscaling_config, dict):
            autoscaling_config = AutoscalingConfig(**autoscaling_config)
        cfg = DeploymentConfig(
            num_replicas=num_replicas or 1,
            max_ongoing_requests=max_ongoing_requests,
            autoscaling_config=autoscaling_config,
            user_config=user_config,
            ray_actor_options=ray_actor_options or {},
            health_check_period_s=health_check_period_s,
            health_check_timeout_s=health_check_timeout_s,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
            version=version,
        )
        return Deployment(func_or_class,
                          name or func_or_class.__name__, cfg)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


def ingress(app):
    """Route a deployment's HTTP traffic through an ASGI app (reference:
    serve.ingress; implementation in ray_tpu.serve.asgi)."""
    from ray_tpu.serve.asgi import ingress as _asgi_ingress

    return _asgi_ingress(app)
