"""Request router: picks a replica per request.

Reference: python/ray/serve/_private/router.py (Router :319) +
replica_scheduler/pow_2_scheduler.py (PowerOfTwoChoicesReplicaScheduler
:49) — sample two replicas, send to the one with fewer ongoing requests.
Replica membership is pushed from the controller via long poll; ongoing
counts are tracked client-side and reconciled when responses complete.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Dict, List, Optional

import ray_tpu
from ray_tpu.core.actor import get_actor
from ray_tpu.serve._private.common import (RequestMetadata,
                                           RunningReplicaInfo,
                                           SERVE_CONTROLLER_NAME,
                                           SERVE_NAMESPACE)

logger = logging.getLogger(__name__)


class _ReplicaEntry:
    __slots__ = ("info", "handle", "ongoing")

    def __init__(self, info: RunningReplicaInfo):
        self.info = info
        self.handle = None
        self.ongoing = 0

    def resolve(self):
        if self.handle is None:
            self.handle = get_actor(self.info.actor_name,
                                    namespace=SERVE_NAMESPACE)
        return self.handle


class PowerOfTwoChoicesReplicaScheduler:
    """Power-of-two routing with backoff, locality, and multiplexing
    (reference: replica_scheduler/pow_2_scheduler.py —
    choose_two_replicas_with_backoff :294):

    - candidates narrow to replicas holding the request's multiplexed
      model (when known), else to same-node replicas when at least two
      exist (prefer-local), else all;
    - two candidates are sampled and the less-loaded one chosen; when
      both are saturated (ongoing >= max_ongoing_requests), the caller
      backs off exponentially and resamples rather than piling onto a
      loaded replica.
    """

    BACKOFF_BASE_S = 0.025
    BACKOFF_MAX_S = 1.0

    def __init__(self, local_node_id: str = ""):
        self._replicas: Dict[str, _ReplicaEntry] = {}
        self._lock = threading.Lock()
        self._local_node_id = local_node_id

    def update_replicas(self, infos: List[dict]) -> None:
        with self._lock:
            new = {}
            for d in infos:
                info = RunningReplicaInfo.from_dict(d)
                prev = self._replicas.get(info.replica_id)
                entry = prev if prev is not None else _ReplicaEntry(info)
                entry.info = info
                new[info.replica_id] = entry
            self._replicas = new

    def num_replicas(self) -> int:
        return len(self._replicas)

    def _candidates(self, model_replica_ids: Optional[set],
                    widen: bool = False) -> List[_ReplicaEntry]:
        with self._lock:
            entries = list(self._replicas.values())
        if widen:
            return entries  # narrowed pool saturated: consider everyone
        if model_replica_ids:
            with_model = [e for e in entries
                          if e.info.replica_id in model_replica_ids]
            if with_model:
                return with_model
        if self._local_node_id:
            local = [e for e in entries
                     if e.info.node_id == self._local_node_id]
            if len(local) >= 2:
                return local
        return entries

    def _sample_two(self, model_replica_ids: Optional[set],
                    widen: bool = False) -> Optional[_ReplicaEntry]:
        entries = self._candidates(model_replica_ids, widen)
        if not entries:
            return None
        if len(entries) == 1:
            return entries[0]
        a, b = random.sample(entries, 2)
        return a if a.ongoing <= b.ongoing else b

    # After this many saturated rounds the preferred (model/local) pool
    # is abandoned for the full set (reference: backoff widens
    # candidates rather than piling onto a hot subset).
    _WIDEN_AFTER_ROUNDS = 2

    def choose_replica(self, model_replica_ids: Optional[set] = None,
                       deadline: Optional[float] = None
                       ) -> Optional[_ReplicaEntry]:
        """Pick a replica; with a deadline, backs off while every sampled
        candidate is at its max_ongoing_requests cap (widening from the
        preferred pool to all replicas after a couple of rounds) and
        returns the best-effort pick at the deadline (the replica queues
        it). Without a deadline: single pass, immediate answer. None
        only when no replicas exist."""
        backoff = self.BACKOFF_BASE_S
        rounds = 0
        while True:
            entry = self._sample_two(
                model_replica_ids, widen=rounds >= self._WIDEN_AFTER_ROUNDS)
            if entry is None:
                return None
            if entry.ongoing < entry.info.max_ongoing_requests:
                return entry
            if deadline is None or time.time() >= deadline:
                return entry  # saturated everywhere: queue on the best
            time.sleep(min(backoff, max(deadline - time.time(), 0.001)))
            backoff = min(backoff * 2, self.BACKOFF_MAX_S)
            rounds += 1

    def on_request_sent(self, entry: _ReplicaEntry) -> None:
        entry.ongoing += 1

    def on_request_done(self, entry: _ReplicaEntry) -> None:
        entry.ongoing = max(entry.ongoing - 1, 0)

    def drop_replica(self, replica_id: str) -> None:
        with self._lock:
            self._replicas.pop(replica_id, None)


class Router:
    """One per (handle, deployment). Owns a scheduler + a membership
    long-poll thread against the controller."""

    _routers: Dict[tuple, "Router"] = {}
    _routers_lock = threading.Lock()

    def __init__(self, controller, app_name: str, deployment: str):
        self._controller = controller
        self._app_name = app_name
        self._deployment = deployment
        try:
            local_node = ray_tpu.get_runtime_context().node_id.hex()
        except Exception:
            local_node = ""
        self._scheduler = PowerOfTwoChoicesReplicaScheduler(
            local_node_id=local_node)
        self._snapshot_id = -1
        self._stopped = False
        try:
            infos = ray_tpu.get(
                controller.get_running_replicas.remote(app_name, deployment),
                timeout=30)
            self._scheduler.update_replicas(infos)
        except Exception as e:
            logger.warning("initial replica fetch failed: %s", e)
        self._poll_thread = threading.Thread(
            target=self._poll_loop, daemon=True,
            name=f"serve-router-{app_name}#{deployment}")
        self._poll_thread.start()

    @classmethod
    def shared(cls, controller, app_name: str, deployment: str) -> "Router":
        key = (app_name, deployment)
        with cls._routers_lock:
            r = cls._routers.get(key)
            if r is None or r._stopped:
                r = Router(controller, app_name, deployment)
                cls._routers[key] = r
            return r

    @classmethod
    def stop_all(cls) -> None:
        with cls._routers_lock:
            for r in cls._routers.values():
                r._stopped = True
            cls._routers.clear()

    def _poll_loop(self) -> None:
        from ray_tpu.serve._private.controller import replicas_key

        key = replicas_key(self._app_name, self._deployment)
        while not self._stopped:
            try:
                if self._controller is None:
                    # Controller died (crash recovery spawns a NEW actor
                    # under the same name): re-resolve, and reset the
                    # snapshot id — the fresh incarnation numbers its
                    # snapshots from scratch.
                    self._controller = get_actor(
                        SERVE_CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
                    self._snapshot_id = -1
                ref = self._controller.listen_for_change.remote(
                    {key: self._snapshot_id})
                updates = ray_tpu.get(ref, timeout=60)
            except Exception:
                if self._stopped:
                    return
                self._controller = None
                time.sleep(1.0)
                continue
            if key in (updates or {}):
                self._snapshot_id = updates[key]["snapshot_id"]
                self._scheduler.update_replicas(updates[key]["value"])

    # --------------------------------------------------------------- sending
    def assign_request(self, meta: RequestMetadata, args: tuple,
                       kwargs: dict, timeout_s: float = 30.0):
        """Pick a replica and submit; returns (ObjectRef, completion_cb)."""
        deadline = time.time() + timeout_s
        model_ids = None
        if meta.multiplexed_model_id:
            model_ids = self._multiplex_candidates(
                meta.multiplexed_model_id)
        entry = self._scheduler.choose_replica(model_ids,
                                               deadline=deadline)
        while entry is None:
            if time.time() > deadline:
                raise RuntimeError(
                    f"no running replicas for deployment "
                    f"{self._app_name}#{self._deployment} after "
                    f"{timeout_s:.0f}s")
            time.sleep(0.1)
            entry = self._scheduler.choose_replica(model_ids,
                                                   deadline=deadline)
        handle = entry.resolve()
        self._scheduler.on_request_sent(entry)
        # Idempotent release: fires on normal completion OR an early
        # caller-side cancel (e.g. proxy request timeout) — never both,
        # so a hung replica can't accumulate phantom ongoing load and a
        # normal completion can't double-decrement.
        released = []

        def release_once():
            if not released:
                released.append(1)
                self._scheduler.on_request_done(entry)

        if meta.stream:
            # Streaming rides the core streaming-generator protocol:
            # chunks arrive as ObjectRefGenerator items; the replica
            # counts as loaded until the consumer drains/cancels.
            try:
                gen = handle.handle_request_streaming.options(
                    num_returns="streaming").remote(
                        meta.to_dict(), *args, **kwargs)
            except Exception:
                release_once()
                self._scheduler.drop_replica(entry.info.replica_id)
                raise
            return gen, None, handle, release_once
        try:
            ref = handle.handle_request.remote(meta.to_dict(), *args,
                                               **kwargs)
        except Exception:
            release_once()
            self._scheduler.drop_replica(entry.info.replica_id)
            raise
        worker = ray_tpu.get_runtime_context()._worker
        fut = worker.as_future(ref)
        fut.add_done_callback(lambda _f: release_once())
        return ref, fut, handle, release_once

    _MULTIPLEX_CACHE_TTL_S = 2.0

    def _multiplex_candidates(self, model_id: str) -> Optional[set]:
        """Replica-id set that already holds the model — the pow-2
        scheduler samples among THESE, keeping load balance even within
        the model's replicas (reference: multiplex-aware candidates in
        pow_2_scheduler.py). The model→replica map is cached and
        refreshed from a background thread so the hot path never blocks
        on the fan-out RPC."""
        now = time.time()
        if now - getattr(self, "_mux_fetched_at", 0.0) > \
                self._MULTIPLEX_CACHE_TTL_S and \
                not getattr(self, "_mux_refreshing", False):
            self._mux_refreshing = True

            def _bg():
                try:
                    self._refresh_multiplex_cache()
                    self._mux_fetched_at = time.time()
                finally:
                    self._mux_refreshing = False

            threading.Thread(target=_bg, daemon=True,
                             name="serve-mux-refresh").start()
        cache: Dict[str, List[str]] = getattr(self, "_mux_models", {})
        ids = cache.get(model_id)
        return set(ids) if ids else None

    def _refresh_multiplex_cache(self) -> None:
        with self._scheduler._lock:
            entries = list(self._scheduler._replicas.values())
        refs, ids = [], []
        for e in entries:
            try:
                refs.append(e.resolve().get_metadata.remote())
                ids.append(e.info.replica_id)
            except Exception:
                pass
        models: Dict[str, List[str]] = {}
        if refs:
            done, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=2.0)
            for rid, ref in zip(ids, refs):
                if ref not in done:
                    continue
                try:
                    meta = ray_tpu.get(ref)
                except Exception:
                    continue
                for mid in meta.get("multiplexed_model_ids", []):
                    models.setdefault(mid, []).append(rid)
        self._mux_models = models
