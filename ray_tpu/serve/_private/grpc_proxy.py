"""gRPC ingress: the versioned serve_call schema on a standard transport.

Reference: python/ray/serve/_private/proxy.py:540 (gRPCProxy) +
src/ray/protobuf/serve.proto. The wire contract is the SAME versioned
msgpack schema as the framed-rpc ingress (ingress_schema.py) carried as
raw gRPC message bytes through grpc's generic-handler API — so any gRPC
client in any language can call a deployment with nothing generated and
nothing imported from ray_tpu:

    channel = grpc.insecure_channel(addr)
    call = channel.unary_unary("/rayserve.ServeAPI/Call")
    resp = msgpack.unpackb(call(msgpack.packb({
        "schema_version": 1, "app": "default", "payload": ...})))

Methods:
    /rayserve.ServeAPI/Call        unary-unary   one response envelope
    /rayserve.ServeAPI/StreamCall  unary-stream  envelope per chunk, a
                                                 final envelope carries
                                                 {"eos": True}
"""

from __future__ import annotations

import asyncio
import logging
from concurrent import futures
from typing import Optional

import msgpack

logger = logging.getLogger(__name__)

SERVICE = "rayserve.ServeAPI"
METHOD_CALL = f"/{SERVICE}/Call"
METHOD_STREAM = f"/{SERVICE}/StreamCall"


class GrpcIngress:
    """Serves the versioned schema over grpc beside the HTTP proxy.

    Handlers run on grpc's thread pool and bridge onto the proxy's
    asyncio loop (where the router lives) via run_coroutine_threadsafe.
    """

    def __init__(self, rpc_ingress, loop: asyncio.AbstractEventLoop,
                 host: str = "127.0.0.1", port: int = 0,
                 request_timeout_s: Optional[float] = 60.0,
                 tls: Optional[dict] = None):
        import grpc

        self._ingress = rpc_ingress
        self._loop = loop
        self._timeout = request_timeout_s
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8,
                                       thread_name_prefix="serve-grpc"))
        outer = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, call_details):
                if call_details.method == METHOD_CALL:
                    return grpc.unary_unary_rpc_method_handler(
                        outer._handle_call)
                if call_details.method == METHOD_STREAM:
                    return grpc.unary_stream_rpc_method_handler(
                        outer._handle_stream)
                return None

        self._server.add_generic_rpc_handlers((Handler(),))
        if tls:
            unknown = set(tls) - {"cert_path", "key_path", "ca_path"}
            if unknown or not (tls.get("cert_path") and
                               tls.get("key_path")):
                # A present-but-broken TLS config must NEVER silently
                # downgrade to plaintext.
                raise ValueError(
                    "grpc_tls requires cert_path and key_path "
                    f"(got keys {sorted(tls)}; unknown: {sorted(unknown)})")
        if tls and tls.get("cert_path") and tls.get("key_path"):
            # TLS ingress (http_options["grpc_tls"]): server-side certs;
            # optional client-cert verification via ca_path.
            with open(tls["key_path"], "rb") as f:
                key = f.read()
            with open(tls["cert_path"], "rb") as f:
                cert = f.read()
            ca = None
            if tls.get("ca_path"):
                with open(tls["ca_path"], "rb") as f:
                    ca = f.read()
            creds = grpc.ssl_server_credentials(
                [(key, cert)], root_certificates=ca,
                require_client_auth=bool(ca))
            self.port = self._server.add_secure_port(
                f"{host}:{port}", creds)
        else:
            self.port = self._server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            raise OSError(f"grpc ingress failed to bind {host}:{port}")
        self._server.start()
        logger.info("serve grpc ingress on %s:%d", host, self.port)

    # grpc generic handlers receive/return raw bytes (no serializers
    # registered): the payload IS the msgpack schema message.
    def _handle_call(self, request: bytes, context) -> bytes:
        from ray_tpu.serve._private.ingress_schema import (STATUS_INVALID,
                                                           ServeCallResponse)

        try:
            data = msgpack.unpackb(request, raw=False)
        except Exception as e:
            return msgpack.packb(ServeCallResponse(
                status=STATUS_INVALID,
                error=f"bad msgpack request: {e}").to_wire(),
                use_bin_type=True)
        fut = asyncio.run_coroutine_threadsafe(
            self._ingress.handle_serve_call(data, None), self._loop)
        reply = fut.result(timeout=(self._timeout or 0) + 30
                           if self._timeout else None)
        return msgpack.packb(reply, use_bin_type=True)

    def _handle_stream(self, request: bytes, context):
        from ray_tpu.serve._private.ingress_schema import (STATUS_APP_ERROR,
                                                           STATUS_INVALID,
                                                           STATUS_OK,
                                                           ServeCallResponse)

        def envelope(**kw) -> bytes:
            return msgpack.packb(ServeCallResponse(**kw).to_wire(),
                                 use_bin_type=True)

        try:
            data = msgpack.unpackb(request, raw=False)
        except Exception as e:
            yield envelope(status=STATUS_INVALID,
                           error=f"bad msgpack request: {e}")
            return
        try:
            gen = asyncio.run_coroutine_threadsafe(
                self._ingress.open_serve_stream(data), self._loop
            ).result(timeout=30.0)
        except Exception as e:
            yield envelope(status=STATUS_APP_ERROR,
                           error=f"{type(e).__name__}: {e}")
            return
        if isinstance(gen, dict):
            yield msgpack.packb(gen, use_bin_type=True)  # error envelope
            return
        request_id = data.get("request_id", "")
        try:
            for chunk in gen:
                yield envelope(status=STATUS_OK, result=chunk,
                               request_id=request_id)
        except Exception as e:
            yield envelope(status=STATUS_APP_ERROR,
                           error=f"{type(e).__name__}: {e}",
                           request_id=request_id)
            return
        final = ServeCallResponse(status=STATUS_OK,
                                  request_id=request_id).to_wire()
        final["eos"] = True
        yield msgpack.packb(final, use_bin_type=True)

    def stop(self) -> None:
        self._server.stop(grace=0.5)
