"""HTTP proxy actor.

Reference: python/ray/serve/_private/proxy.py — ProxyActor (:1130) hosts an
HTTPProxy (:761, ASGI/uvicorn in the reference; aiohttp here) that matches
routes against the controller-pushed route table and forwards to
DeploymentHandles. Built-in endpoints: /-/routes, /-/healthz.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
from typing import Dict, Optional

import ray_tpu
from ray_tpu.serve._private.common import SERVE_NAMESPACE

logger = logging.getLogger(__name__)


class ServeRequest:
    """What an ingress deployment's __call__ receives for HTTP requests.
    A picklable stand-in for starlette.requests.Request (reference ships
    the ASGI scope over the handle; python/ray/serve/_private/
    http_util.py)."""

    def __init__(self, method: str, path: str, route_prefix: str,
                 query: Dict[str, str], headers: Dict[str, str],
                 body: bytes):
        self.method = method
        self.path = path
        self.route_prefix = route_prefix
        self.query_params = query
        self.headers = headers
        self.body = body

    def json(self):
        return json.loads(self.body or b"null")

    def text(self) -> str:
        return (self.body or b"").decode()


class _RpcIngress:
    """rpc-framing ingress beside HTTP (the reference's gRPCProxy
    analog, proxy.py:540 + serve.proto): requests/responses follow the
    VERSIONED contract in ingress_schema.py — an externally-consumable
    wire API, not an internal convenience."""

    def __init__(self, proxy: "ProxyActor"):
        self._proxy = proxy

    async def handle_serve_call(self, data, conn):
        from ray_tpu.serve._private.ingress_schema import (
            STATUS_APP_ERROR, STATUS_NOT_FOUND, STATUS_OK, STATUS_TIMEOUT,
            STATUS_INVALID, SchemaError, ServeCallRequest,
            ServeCallResponse)
        from ray_tpu.serve.handle import DeploymentHandle

        try:
            req = ServeCallRequest.from_wire(data)
        except SchemaError as e:
            return ServeCallResponse(status=STATUS_INVALID,
                                     error=str(e)).to_wire()
        deployment = req.deployment
        if deployment is None:
            # Route by app name through the route table (ingress
            # deployment of that app).
            entry = next((e for e in
                          self._proxy._route_table.values()
                          if e["app_name"] == req.app), None)
            if entry is None:
                return ServeCallResponse(
                    status=STATUS_NOT_FOUND,
                    error=f"no application {req.app!r}",
                    request_id=req.request_id).to_wire()
            deployment = entry["deployment"]
        handle = DeploymentHandle(deployment, req.app)
        if req.method:
            handle = handle.options(method_name=req.method)
        if req.multiplexed_model_id:
            handle = handle.options(
                multiplexed_model_id=req.multiplexed_model_id)
        self._proxy._num_requests += 1
        loop = asyncio.get_running_loop()
        response = await loop.run_in_executor(
            None, lambda: handle.remote(req.payload))
        # Same bound as the HTTP path: a hung replica must not leak the
        # serve task/connection forever; a dropped ingress connection
        # cancels the request end-to-end.
        try:
            result = await asyncio.wait_for(
                _await_response(response),
                timeout=self._proxy._request_timeout_s)
        except asyncio.TimeoutError:
            _cancel_response(response)
            return ServeCallResponse(
                status=STATUS_TIMEOUT,
                error=f"request timed out after "
                      f"{self._proxy._request_timeout_s}s",
                request_id=req.request_id).to_wire()
        except asyncio.CancelledError:
            _cancel_response(response)
            raise
        except Exception as e:
            return ServeCallResponse(
                status=STATUS_APP_ERROR,
                error=f"{type(e).__name__}: {e}",
                request_id=req.request_id).to_wire()
        return ServeCallResponse(status=STATUS_OK, result=result,
                                 request_id=req.request_id).to_wire()

    async def open_serve_stream(self, data):
        """Streaming variant for the grpc ingress (unary-stream): routes
        like handle_serve_call but opens a streaming handle call and
        returns its DeploymentResponseGenerator (sync-iterable from the
        grpc worker thread). Error envelopes return as dicts."""
        from ray_tpu.serve._private.ingress_schema import (
            STATUS_INVALID, STATUS_NOT_FOUND, SchemaError,
            ServeCallRequest, ServeCallResponse)
        from ray_tpu.serve.handle import DeploymentHandle

        try:
            req = ServeCallRequest.from_wire(data)
        except SchemaError as e:
            return ServeCallResponse(status=STATUS_INVALID,
                                     error=str(e)).to_wire()
        deployment = req.deployment
        if deployment is None:
            entry = next((e for e in self._proxy._route_table.values()
                          if e["app_name"] == req.app), None)
            if entry is None:
                return ServeCallResponse(
                    status=STATUS_NOT_FOUND,
                    error=f"no application {req.app!r}",
                    request_id=req.request_id).to_wire()
            deployment = entry["deployment"]
        handle = DeploymentHandle(deployment, req.app).options(stream=True)
        if req.method:
            handle = handle.options(method_name=req.method)
        if req.multiplexed_model_id:
            handle = handle.options(
                multiplexed_model_id=req.multiplexed_model_id)
        self._proxy._num_requests += 1
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, lambda: handle.remote(req.payload))


async def _await_response(response):
    """Shared by the HTTP and rpc ingress paths."""
    return await response


def _cancel_response(response) -> None:
    cancel = getattr(response, "cancel", None)
    if cancel is not None:
        try:
            cancel()
        except Exception:
            pass


@ray_tpu.remote(max_concurrency=1000, lifetime="detached",
                namespace=SERVE_NAMESPACE)
class ProxyActor:
    def __init__(self, http_options: dict):
        self._host = http_options.get("host", "127.0.0.1")
        self._port = int(http_options.get("port", 8000))
        # None = wait forever (reference: HTTPOptions.request_timeout_s).
        self._request_timeout_s = http_options.get("request_timeout_s", 60)
        # Optional TLS for the gRPC ingress:
        # {"cert_path", "key_path", "ca_path"(opt, enables mTLS)}.
        self._grpc_tls = http_options.get("grpc_tls")
        self._route_table: Dict[str, dict] = {}
        self._num_requests = 0
        self._ready_evt = threading.Event()
        self._stop_evt: Optional[asyncio.Event] = None
        self._server_loop: Optional[asyncio.AbstractEventLoop] = None
        self._error: Optional[str] = None
        self._rpc_server = None
        self._rpc_port = 0
        self._grpc_server = None
        self._grpc_port = 0
        self._thread = threading.Thread(target=self._serve_thread,
                                        daemon=True, name="serve-proxy-http")
        self._thread.start()
        self._poll = threading.Thread(target=self._route_poll_loop,
                                      daemon=True, name="serve-proxy-poll")
        self._poll.start()

    # ------------------------------------------------------------ lifecycle
    def ready(self) -> str:
        if not self._ready_evt.wait(timeout=30):
            raise RuntimeError(f"proxy failed to start: {self._error}")
        return f"http://{self._host}:{self._port}"

    def status(self) -> dict:
        return {"address": f"http://{self._host}:{self._port}",
                "rpc_port": self._rpc_port,
                "grpc_port": getattr(self, "_grpc_port", 0),
                "num_requests": self._num_requests,
                "routes": sorted(self._route_table)}

    def rpc_address(self) -> str:
        """Address of the rpc ingress (gRPC-proxy analog)."""
        self.ready()
        return f"{self._host}:{self._rpc_port}"

    def grpc_address(self) -> str:
        """Address of the standard-gRPC ingress (reference: gRPCProxy)."""
        self.ready()
        port = getattr(self, "_grpc_port", 0)
        if not port:
            raise RuntimeError("grpc ingress is not available")
        return f"{self._host}:{port}"

    def stop_server(self) -> None:
        if self._server_loop is not None and self._stop_evt is not None:
            self._server_loop.call_soon_threadsafe(self._stop_evt.set)

    # ---------------------------------------------------------- route table
    def _route_poll_loop(self) -> None:
        from ray_tpu.core.actor import get_actor
        from ray_tpu.serve._private.common import SERVE_CONTROLLER_NAME
        from ray_tpu.serve._private.controller import ROUTE_TABLE_KEY

        snapshot_id = -1
        controller = None
        while True:
            try:
                if controller is None:
                    controller = get_actor(SERVE_CONTROLLER_NAME,
                                           namespace=SERVE_NAMESPACE)
                    # A crash-recovered controller numbers snapshots from
                    # scratch: a stale high-water mark would make this
                    # long-poll wait forever (routes never update).
                    snapshot_id = -1
                ref = controller.listen_for_change.remote(
                    {ROUTE_TABLE_KEY: snapshot_id})
                updates = ray_tpu.get(ref, timeout=60)
                if ROUTE_TABLE_KEY in (updates or {}):
                    update = updates[ROUTE_TABLE_KEY]
                    snapshot_id = update["snapshot_id"]
                    self._route_table = update["value"]
                    logger.info("route table updated: %s",
                                sorted(self._route_table))
            except Exception as e:
                logger.debug("route poll failed: %s", e)
                controller = None
                time.sleep(1.0)

    def _match_route(self, path: str) -> Optional[tuple]:
        best = None
        for prefix, entry in self._route_table.items():
            norm = prefix.rstrip("/") or ""
            if path == norm or path.startswith(norm + "/") or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, entry)
        return best

    # ----------------------------------------------------------- http server
    def _serve_thread(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._server_loop = loop
        try:
            loop.run_until_complete(self._run_server())
        except Exception as e:
            self._error = str(e)
            logger.exception("proxy server died")
        finally:
            loop.close()

    async def _run_server(self) -> None:
        from aiohttp import web

        self._stop_evt = asyncio.Event()
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle_http)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, self._host, self._port)
        await site.start()
        # Second ingress: the framework's rpc framing (reference:
        # gRPCProxy beside HTTPProxy, proxy.py:540) — clients call
        # `serve_call {app, method, payload}` with msgpack payloads
        # instead of HTTP.
        from ray_tpu.core import rpc as _rpc

        ingress = _RpcIngress(self)
        self._rpc_server = _rpc.Server(ingress, self._host, 0)
        self._rpc_port = await self._rpc_server.start()
        # Third ingress: the SAME versioned schema on standard gRPC
        # (reference: gRPCProxy, proxy.py:540) — reachable by clients
        # that import nothing from ray_tpu.
        try:
            from ray_tpu.serve._private.grpc_proxy import GrpcIngress

            self._grpc_server = GrpcIngress(
                ingress, asyncio.get_running_loop(), self._host, 0,
                request_timeout_s=self._request_timeout_s,
                tls=getattr(self, "_grpc_tls", None))
            self._grpc_port = self._grpc_server.port
        except Exception:
            logger.exception("grpc ingress unavailable; msgpack-framed "
                             "rpc ingress remains")
            self._grpc_server = None
            self._grpc_port = 0
        self._ready_evt.set()
        logger.info("Serve proxy listening on %s:%d", self._host, self._port)
        await self._stop_evt.wait()
        if self._grpc_server is not None:
            self._grpc_server.stop()
        await self._rpc_server.close()
        await runner.cleanup()

    async def _handle_http(self, request):
        from aiohttp import web

        path = "/" + request.match_info.get("tail", "")
        if path == "/-/healthz":
            return web.Response(text="success")
        if path == "/-/routes":
            return web.json_response(
                {p: f"{e['app_name']}#{e['deployment']}"
                 for p, e in self._route_table.items()})
        match = self._match_route(path)
        if match is None:
            return web.Response(
                status=404,
                text=f"no Serve application at {path!r}; "
                     f"routes: {sorted(self._route_table)}")
        prefix, entry = match
        body = await request.read()
        serve_req = ServeRequest(
            method=request.method, path=path, route_prefix=prefix,
            query=dict(request.query),
            headers={k: v for k, v in request.headers.items()},
            body=body)
        self._num_requests += 1
        try:
            # Routing (replica pick + submit) is short blocking work — run
            # it on the executor; the long wait for the reply is awaited on
            # the event loop, so one slow request does not hold a thread
            # (reference: fully-async HTTPProxy, proxy.py:761).
            response = await asyncio.get_running_loop().run_in_executor(
                None, self._submit, entry, serve_req)
            result = await asyncio.wait_for(
                _await_response(response),
                timeout=self._request_timeout_s)
        except asyncio.TimeoutError:
            # Release the replica slot NOW: a hung replica must not keep
            # counting as ongoing load (ADVICE r1) or hold the client.
            _cancel_response(response)
            return web.Response(
                status=504,
                text=f"request timed out after {self._request_timeout_s}s")
        except asyncio.CancelledError:
            # Client disconnected: aiohttp cancels the handler task —
            # cancel the in-flight request end-to-end (release the
            # replica slot + best-effort task cancel).
            _cancel_response(response)
            raise
        except Exception as e:
            logger.exception("request to %s failed", path)
            return web.Response(status=500, text=f"{type(e).__name__}: {e}")
        return self._to_response(result)

    def _submit(self, entry: dict, serve_req: ServeRequest):
        from ray_tpu.serve.handle import DeploymentHandle

        handle = DeploymentHandle(entry["deployment"], entry["app_name"])
        return handle.remote(serve_req)

    @staticmethod
    def _to_response(result):
        from aiohttp import web

        from ray_tpu.serve.asgi import HTTPResponse

        if isinstance(result, HTTPResponse):
            return web.Response(body=result.body, status=result.status,
                                headers=result.headers)
        if isinstance(result, (bytes, bytearray)):
            return web.Response(body=bytes(result))
        if isinstance(result, str):
            return web.Response(text=result)
        return web.json_response(result)
