"""Serve controller: the cluster-singleton control plane actor.

Reference: python/ray/serve/_private/controller.py — ServeController (:86)
owns ApplicationStateManager + DeploymentStateManager + autoscaling +
proxy state, runs a reconcile loop, broadcasts config via LongPollHost,
and checkpoints its state to the GCS KV so a restarted controller recovers
(reference :222 run_control_loop, :722 deploy_application).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.core import serialization as ser
from ray_tpu.serve.config import HTTPOptions
from ray_tpu.serve._private.application_state import ApplicationStateManager
from ray_tpu.serve._private.autoscaling import AutoscalingState
from ray_tpu.serve._private.common import (DeploymentID, SERVE_NAMESPACE)
from ray_tpu.serve._private.deployment_state import DeploymentStateManager
from ray_tpu.serve._private.long_poll import LongPollHost

logger = logging.getLogger(__name__)

CONTROL_LOOP_INTERVAL_S = 0.2
CHECKPOINT_KEY = b"serve:controller_checkpoint"
ROUTE_TABLE_KEY = "route_table"


def replicas_key(app_name: str, deployment: str) -> str:
    return f"replicas::{app_name}#{deployment}"


@ray_tpu.remote(max_concurrency=1000, lifetime="detached",
                namespace=SERVE_NAMESPACE)
class ServeController:
    def __init__(self, http_options: Optional[dict] = None):
        self._long_poll = LongPollHost()
        self._dsm = DeploymentStateManager(self._on_running_changed)
        self._asm = ApplicationStateManager(self._dsm)
        self._autoscaling: Dict[DeploymentID, AutoscalingState] = {}
        self._http_options = http_options or HTTPOptions().to_dict()
        self._proxy_handle = None
        self._shutting_down = False
        self._loop_running = False
        self._app_blobs: Dict[str, dict] = {}  # for checkpoint/recovery
        self._recover_from_checkpoint()
        import threading

        self._loop_thread = threading.Thread(
            target=self._control_loop, daemon=True, name="serve-control-loop")
        self._loop_thread.start()

    # -------------------------------------------------------- control loop
    def run_control_loop(self) -> None:
        """API-parity no-op: the loop starts in __init__ (daemon thread) so
        a recovered controller reconciles without an external kick."""
        return

    def _control_loop(self) -> None:
        """Reconcile forever. Plain thread: blocking ray_tpu.get/wait are
        safe here (they hop to the worker's io loop)."""
        if self._loop_running:
            return
        self._loop_running = True
        last_autoscale = 0.0
        while not self._shutting_down:
            try:
                self._dsm.reconcile_all()
                self._asm.update_all()
                now = time.time()
                if now - last_autoscale >= 1.0:
                    self._run_autoscaling_pass()
                    last_autoscale = now
                self._long_poll.notify_if_changed(
                    ROUTE_TABLE_KEY, self._asm.route_table())
            except Exception:
                logger.exception("control loop iteration failed")
            time.sleep(CONTROL_LOOP_INTERVAL_S)

    def _run_autoscaling_pass(self) -> None:
        for did, state in self._dsm.all_states().items():
            cfg = state.target_config
            if cfg is None or cfg.autoscaling_config is None:
                self._autoscaling.pop(did, None)
                continue
            if did not in self._autoscaling:
                self._autoscaling[did] = AutoscalingState(
                    cfg.autoscaling_config)
            a = self._autoscaling[did]
            a.config = cfg.autoscaling_config
            use_custom = getattr(cfg.autoscaling_config,
                                 "target_custom_metric", None) is not None
            state.collect_autoscaling_stats(custom=use_custom)
            a.record(state.total_custom_metric() if use_custom
                     else state.total_ongoing_requests())
            desired = a.desired_replicas(state.target_num_replicas)
            if desired != state.target_num_replicas:
                logger.info("autoscaling %s: %d -> %d replicas", did,
                            state.target_num_replicas, desired)
                state.set_target_num_replicas(desired)

    def _on_running_changed(self, deployment_id: DeploymentID,
                            infos: List[dict]) -> None:
        self._long_poll.notify_changed(
            replicas_key(deployment_id.app_name, deployment_id.name), infos)

    # ----------------------------------------------------------- public API
    def deploy_application(self, name: str, deployments: List[dict],
                           route_prefix: Optional[str]) -> None:
        logger.info("deploying application %r (%d deployments)", name,
                    len(deployments))
        self._asm.deploy_app(name, deployments, route_prefix)
        self._app_blobs[name] = {"deployments": deployments,
                                 "route_prefix": route_prefix}
        self._checkpoint()
        self._long_poll.notify_changed(ROUTE_TABLE_KEY,
                                       self._asm.route_table())

    def delete_application(self, name: str) -> None:
        self._asm.delete_app(name)
        self._app_blobs.pop(name, None)
        self._checkpoint()
        self._long_poll.notify_changed(ROUTE_TABLE_KEY,
                                       self._asm.route_table())

    def get_app_statuses(self) -> Dict[str, dict]:
        out = {}
        for name, info in self._asm.all_status_infos().items():
            out[name] = {
                "status": info.status.value,
                "message": info.message,
                "deployed_at": info.deployed_at,
                "route_prefix": info.route_prefix,
                "deployments": {
                    dn: {"status": di.status.value, "message": di.message,
                         "replica_states": di.replica_states}
                    for dn, di in info.deployments.items()},
            }
        return out

    def get_running_replicas(self, app_name: str,
                             deployment: str) -> List[dict]:
        state = self._dsm.get(DeploymentID(deployment, app_name))
        return state.running_replica_infos() if state else []

    def get_route_table(self) -> Dict[str, dict]:
        return self._asm.route_table()

    def get_http_options(self) -> dict:
        return self._http_options

    def set_proxy_started(self) -> None:
        self._proxy_started = True

    async def listen_for_change(self, keys_to_snapshot_ids: Dict[str, int]
                                ) -> dict:
        return await self._long_poll.listen_for_change(keys_to_snapshot_ids)

    def graceful_shutdown(self) -> None:
        """Delete all apps and wait for replicas to stop."""
        self._shutting_down = True
        for name in list(self._asm.all_status_infos()):
            self._asm.delete_app(name)
        deadline = time.time() + 30
        while time.time() < deadline:
            self._dsm.reconcile_all()
            self._asm.update_all()
            if not self._dsm.all_states():
                break
            time.sleep(0.1)
        try:
            worker = ray_tpu.get_runtime_context()._worker
            worker.gcs_call("kv_del", {"ns": b"serve", "key": CHECKPOINT_KEY})
        except Exception:
            pass

    # ---------------------------------------------------------- checkpoint
    def _checkpoint(self) -> None:
        """Persist app definitions to GCS KV (reference: controller
        checkpointing via _private/storage/kv_store.py)."""
        try:
            worker = ray_tpu.get_runtime_context()._worker
            worker.gcs_call("kv_put", {
                "ns": b"serve", "key": CHECKPOINT_KEY,
                "value": ser.dumps(self._app_blobs)})
        except Exception:
            logger.exception("controller checkpoint failed")

    def _recover_from_checkpoint(self) -> None:
        try:
            worker = ray_tpu.get_runtime_context()._worker
            blob = worker.gcs_call("kv_get", {"ns": b"serve",
                                              "key": CHECKPOINT_KEY})
            if not blob:
                return
            apps = ser.loads(blob)
        except Exception:
            return
        for name, app in apps.items():
            try:
                self._asm.deploy_app(name, app["deployments"],
                                     app["route_prefix"])
                self._app_blobs[name] = app
            except Exception:
                logger.exception("failed to recover app %r", name)
