"""Shared Serve types.

Reference: python/ray/serve/_private/common.py (DeploymentID, ReplicaID,
DeploymentStatus, ApplicationStatus, RunningReplicaInfo).
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

SERVE_CONTROLLER_NAME = "SERVE_CONTROLLER"
SERVE_NAMESPACE = "serve"
SERVE_DEFAULT_APP_NAME = "default"


@dataclass(frozen=True)
class DeploymentID:
    name: str
    app_name: str = SERVE_DEFAULT_APP_NAME

    def __str__(self) -> str:
        return f"{self.app_name}#{self.name}"

    def to_replica_actor_prefix(self) -> str:
        return f"SERVE_REPLICA::{self.app_name}#{self.name}"


class DeploymentStatus(str, enum.Enum):
    UPDATING = "UPDATING"
    HEALTHY = "HEALTHY"
    UNHEALTHY = "UNHEALTHY"
    UPSCALING = "UPSCALING"
    DOWNSCALING = "DOWNSCALING"


class ApplicationStatus(str, enum.Enum):
    NOT_STARTED = "NOT_STARTED"
    DEPLOYING = "DEPLOYING"
    RUNNING = "RUNNING"
    DEPLOY_FAILED = "DEPLOY_FAILED"
    DELETING = "DELETING"
    UNHEALTHY = "UNHEALTHY"


class ReplicaState(str, enum.Enum):
    STARTING = "STARTING"
    RUNNING = "RUNNING"
    STOPPING = "STOPPING"


@dataclass
class RunningReplicaInfo:
    """What routers need to reach a replica: its named-actor name.

    The reference ships ActorHandles in LongPoll snapshots
    (python/ray/serve/_private/common.py RunningReplicaInfo); here replicas
    are *named* actors so routers resolve handles with ray_tpu.get_actor —
    handles stay process-local.
    """

    replica_id: str
    actor_name: str
    deployment: str
    app_name: str
    max_ongoing_requests: int = 5
    node_id: str = ""  # hex; enables prefer-local routing

    def to_dict(self) -> dict:
        return {
            "replica_id": self.replica_id,
            "actor_name": self.actor_name,
            "deployment": self.deployment,
            "app_name": self.app_name,
            "max_ongoing_requests": self.max_ongoing_requests,
            "node_id": self.node_id,
        }

    @staticmethod
    def from_dict(d: dict) -> "RunningReplicaInfo":
        return RunningReplicaInfo(**d)


@dataclass
class RequestMetadata:
    """Per-request routing metadata (reference:
    python/ray/serve/_private/common.py RequestMetadata)."""

    request_id: str = ""
    call_method: str = "__call__"
    multiplexed_model_id: str = ""
    is_http_request: bool = False
    stream: bool = False

    def to_dict(self) -> dict:
        return {
            "request_id": self.request_id,
            "call_method": self.call_method,
            "multiplexed_model_id": self.multiplexed_model_id,
            "is_http_request": self.is_http_request,
            "stream": self.stream,
        }

    @staticmethod
    def from_dict(d: dict) -> "RequestMetadata":
        return RequestMetadata(**d)


@dataclass
class DeploymentStatusInfo:
    name: str
    status: DeploymentStatus
    message: str = ""
    replica_states: Dict[str, int] = field(default_factory=dict)


@dataclass
class ApplicationStatusInfo:
    name: str
    status: ApplicationStatus
    message: str = ""
    deployed_at: float = field(default_factory=time.time)
    deployments: Dict[str, DeploymentStatusInfo] = field(default_factory=dict)
    route_prefix: Optional[str] = None


def format_replica_actor_name(deployment_id: DeploymentID,
                              replica_suffix: str) -> str:
    return f"{deployment_id.to_replica_actor_prefix()}#{replica_suffix}"
