"""Replica actor: hosts the user callable.

Reference: python/ray/serve/_private/replica.py — ReplicaActor (:231) wraps
the user class/function in a UserCallableWrapper (:750), tracks ongoing
requests, exposes health checks and graceful drain.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import time
from typing import Any, Optional

import ray_tpu
from ray_tpu.core import serialization as ser
from ray_tpu.serve._private.common import RequestMetadata

logger = logging.getLogger(__name__)


class UserCallableWrapper:
    """Instantiates and calls the user's deployment class/function."""

    def __init__(self, serialized_def: bytes, init_args: tuple,
                 init_kwargs: dict):
        self._def = ser.loads(serialized_def)
        self._init_args = init_args
        self._init_kwargs = init_kwargs
        self._callable: Any = None

    def initialize(self) -> None:
        if inspect.isclass(self._def):
            self._callable = self._def(*self._init_args, **self._init_kwargs)
        else:
            # Plain function deployment: calls go straight to it.
            self._callable = self._def

    def get_method(self, name: str):
        if inspect.isfunction(self._def) or inspect.ismethod(self._def):
            return self._callable
        target = getattr(self._callable, name, None)
        if target is None:
            raise AttributeError(
                f"deployment has no method {name!r}")
        return target

    def reconfigure(self, user_config: Any) -> None:
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    def call_health_check(self) -> None:
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            fn()


@ray_tpu.remote
class ReplicaActor:
    """One serving replica. Created as a named detached actor by the
    controller; routers resolve it with ray_tpu.get_actor."""

    def __init__(self, replica_id: str, deployment: str, app_name: str,
                 serialized_def: bytes, init_args_blob: bytes,
                 config_dict: dict):
        self._replica_id = replica_id
        self._deployment = deployment
        self._app_name = app_name
        init_args, init_kwargs = ser.loads(init_args_blob)
        self._wrapper = UserCallableWrapper(serialized_def, init_args,
                                            init_kwargs)
        self._wrapper.initialize()
        user_config = config_dict.get("user_config")
        if user_config is not None:
            self._wrapper.reconfigure(user_config)
        self._num_ongoing = 0
        self._total_served = 0
        self._total_errors = 0
        self._draining = False
        self._multiplexed_model_ids: list = []
        self._started_at = time.time()
        # Replica-side custom autoscaling metric
        # (serve.metrics.record_autoscaling_metric); polled by the
        # controller when the deployment declares target_custom_metric.
        self._custom_autoscaling_metric: Optional[float] = None
        # Set on the CANONICAL module (not `global`): this class ships
        # to the worker pickled by value, so its methods' __globals__
        # are a reconstructed namespace — a bare `global` write would
        # land there and user code importing the module (serve.metrics
        # context tags) would still see None.
        import ray_tpu.serve._private.replica as _rmod

        _rmod._current_replica = self
        # Built-in per-deployment metrics (reference: serve/metrics.py
        # request counter / error counter / processing latency): flow
        # through the metrics pipeline to the dashboard /metrics.
        from ray_tpu.util import metrics as um

        tags = {"deployment": deployment, "replica": replica_id,
                "application": app_name}
        keys = tuple(tags)
        self._m_requests = um.Counter(
            "serve_deployment_request_counter",
            "requests served per deployment replica",
            tag_keys=keys).set_default_tags(tags)
        self._m_errors = um.Counter(
            "serve_deployment_error_counter",
            "user-code errors per deployment replica",
            tag_keys=keys).set_default_tags(tags)
        self._m_latency = um.Histogram(
            "serve_deployment_processing_latency_ms",
            "request processing latency (ms)",
            boundaries=[1, 2, 5, 10, 20, 50, 100, 200, 500, 1000,
                        2000, 5000],
            tag_keys=keys).set_default_tags(tags)

    # ------------------------------------------------------------- data path
    async def handle_request(self, request_meta: dict, *args, **kwargs):
        """Execute one request (reference replica.py handle_request)."""
        meta = RequestMetadata.from_dict(request_meta)
        self._num_ongoing += 1
        t0 = time.perf_counter()
        try:
            method = self._wrapper.get_method(meta.call_method)
            if meta.multiplexed_model_id:
                _set_multiplex_context(meta.multiplexed_model_id)
            if inspect.iscoroutinefunction(method):
                result = await method(*args, **kwargs)
            else:
                # to_thread (not run_in_executor) so the multiplex
                # ContextVar propagates into the worker thread.
                result = await asyncio.to_thread(method, *args, **kwargs)
            if inspect.isgenerator(result) or \
                    inspect.isasyncgen(result):
                # Non-stream callers (plain handle / HTTP) must opt
                # in — otherwise the generator would leak.
                raise TypeError(
                    f"{meta.call_method!r} returned a generator; "
                    "call it with handle.options(stream=True)")
            self._total_served += 1
            return result
        except Exception:
            self._total_errors += 1
            self._m_errors.inc()
            raise
        finally:
            self._num_ongoing -= 1
            self._m_requests.inc()
            self._m_latency.observe(
                (time.perf_counter() - t0) * 1000.0)

    _STREAM_END = object()

    async def handle_request_streaming(self, request_meta: dict,
                                       *args, **kwargs):
        """Streaming request path: an async-generator actor method driven
        by the core streaming-generator protocol — the router calls it
        with num_returns="streaming", so every yielded chunk reaches the
        caller as an ObjectRefGenerator item with no per-chunk RPC round
        trip (reference: streaming responses over the
        streaming-generator protocol in replica.py)."""
        meta = RequestMetadata.from_dict(request_meta)
        self._num_ongoing += 1
        t0 = time.perf_counter()
        try:
            method = self._wrapper.get_method(meta.call_method)
            if meta.multiplexed_model_id:
                _set_multiplex_context(meta.multiplexed_model_id)
            if inspect.isasyncgenfunction(method):
                result = method(*args, **kwargs)
            elif inspect.iscoroutinefunction(method):
                result = await method(*args, **kwargs)
            else:
                result = await asyncio.to_thread(method, *args, **kwargs)
            if inspect.isasyncgen(result):
                async for chunk in result:
                    yield chunk
            elif inspect.isgenerator(result):
                while True:
                    # StopIteration cannot cross coroutine/future
                    # boundaries — drain with a sentinel default.
                    chunk = await asyncio.to_thread(
                        next, result, self._STREAM_END)
                    if chunk is self._STREAM_END:
                        break
                    yield chunk
            else:
                # Non-generator result through stream=True: one chunk.
                yield result
            self._total_served += 1
        except Exception:
            self._total_errors += 1
            self._m_errors.inc()
            raise
        finally:
            self._num_ongoing -= 1
            self._m_requests.inc()
            self._m_latency.observe(
                (time.perf_counter() - t0) * 1000.0)

    # ----------------------------------------------------------- control path
    def get_num_ongoing_requests(self) -> int:
        return self._num_ongoing

    def get_autoscaling_metric(self) -> Optional[float]:
        """The user-recorded custom autoscaling value (None when the
        replica never called record_autoscaling_metric)."""
        return self._custom_autoscaling_metric

    def get_metadata(self) -> dict:
        return {
            "replica_id": self._replica_id,
            "deployment": self._deployment,
            "app_name": self._app_name,
            "num_ongoing": self._num_ongoing,
            "total_served": self._total_served,
            "total_errors": self._total_errors,
            "started_at": self._started_at,
            "multiplexed_model_ids": list(self._multiplexed_model_ids),
        }

    def record_multiplexed_model(self, model_id: str) -> None:
        if model_id not in self._multiplexed_model_ids:
            self._multiplexed_model_ids.append(model_id)

    def reconfigure(self, user_config: Any) -> None:
        self._wrapper.reconfigure(user_config)

    def check_health(self) -> str:
        self._wrapper.call_health_check()
        return "ok"

    async def prepare_for_shutdown(self, timeout_s: float = 20.0,
                                   wait_loop_s: float = 0.5) -> bool:
        """Drain: wait until no ongoing requests (graceful_shutdown in
        reference replica.py)."""
        self._draining = True
        deadline = time.time() + timeout_s
        while self._num_ongoing > 0 and time.time() < deadline:
            await asyncio.sleep(wait_loop_s)
        return self._num_ongoing == 0


import contextvars

# Per-request, not process-global: concurrent requests on an async replica
# must not clobber each other's model id (reference uses a ContextVar in
# serve/context.py).
_multiplex_context: contextvars.ContextVar = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")
_current_replica = None  # the ReplicaActor instance living in this process


def get_current_replica():
    """The ReplicaActor living in this process (None outside one) —
    the serve metrics API reads its identity tags from here."""
    return _current_replica


def _set_multiplex_context(model_id: str) -> None:
    _multiplex_context.set(model_id)


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id requested by the current call
    (reference: serve.get_multiplexed_model_id)."""
    return _multiplex_context.get()
