"""Application-level state: a named group of deployments + a route prefix.

Reference: python/ray/serve/_private/application_state.py —
ApplicationState (:119) owns its deployments' target state and aggregates
their statuses; ApplicationStateManager reconciles all apps.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

from ray_tpu.serve.config import DeploymentConfig
from ray_tpu.serve._private.common import (
    ApplicationStatus, ApplicationStatusInfo, DeploymentID,
    DeploymentStatus)
from ray_tpu.serve._private.deployment_state import DeploymentStateManager

logger = logging.getLogger(__name__)


class ApplicationState:
    def __init__(self, name: str, deployment_state_manager:
                 DeploymentStateManager):
        self.name = name
        self.route_prefix: Optional[str] = None
        self.ingress_deployment: Optional[str] = None
        self.deployment_names: List[str] = []
        self.status = ApplicationStatus.NOT_STARTED
        self.message = ""
        self.deployed_at = time.time()
        self.deleting = False
        self._dsm = deployment_state_manager

    def deploy(self, deployments: List[dict],
               route_prefix: Optional[str]) -> None:
        """deployments: [{name, serialized_def, init_args_blob, config_dict,
        is_ingress}]"""
        self.route_prefix = route_prefix
        self.deployed_at = time.time()
        self.deleting = False
        new_names = []
        for d in deployments:
            did = DeploymentID(d["name"], self.name)
            config = DeploymentConfig.from_dict(d["config_dict"])
            self._dsm.deploy(did, d["serialized_def"], d["init_args_blob"],
                             config)
            new_names.append(d["name"])
            if d.get("is_ingress"):
                self.ingress_deployment = d["name"]
        # Remove deployments dropped from the app definition.
        for name in self.deployment_names:
            if name not in new_names:
                self._dsm.delete(DeploymentID(name, self.name))
        self.deployment_names = new_names
        self.status = ApplicationStatus.DEPLOYING

    def delete(self) -> None:
        self.deleting = True
        self.status = ApplicationStatus.DELETING
        for name in self.deployment_names:
            self._dsm.delete(DeploymentID(name, self.name))

    def update_status(self) -> None:
        if self.deleting:
            if not self._dsm.states_for_app(self.name):
                self.status = ApplicationStatus.NOT_STARTED
            return
        infos = [self._dsm.get(DeploymentID(n, self.name)).curr_status_info()
                 for n in self.deployment_names
                 if self._dsm.get(DeploymentID(n, self.name)) is not None]
        if any(i.status == DeploymentStatus.UNHEALTHY for i in infos):
            self.status = ApplicationStatus.DEPLOY_FAILED
            self.message = "; ".join(
                i.message for i in infos
                if i.status == DeploymentStatus.UNHEALTHY)
        elif all(i.status == DeploymentStatus.HEALTHY for i in infos):
            self.status = ApplicationStatus.RUNNING
            self.message = ""
        else:
            self.status = ApplicationStatus.DEPLOYING

    def status_info(self) -> ApplicationStatusInfo:
        deployments = {}
        for n in self.deployment_names:
            st = self._dsm.get(DeploymentID(n, self.name))
            if st is not None:
                deployments[n] = st.curr_status_info()
        return ApplicationStatusInfo(
            name=self.name, status=self.status, message=self.message,
            deployed_at=self.deployed_at, deployments=deployments,
            route_prefix=self.route_prefix)

    def is_deleted(self) -> bool:
        return self.deleting and not self._dsm.states_for_app(self.name)


class ApplicationStateManager:
    def __init__(self, deployment_state_manager: DeploymentStateManager):
        self._dsm = deployment_state_manager
        self._apps: Dict[str, ApplicationState] = {}

    def deploy_app(self, name: str, deployments: List[dict],
                   route_prefix: Optional[str]) -> None:
        if name not in self._apps:
            self._apps[name] = ApplicationState(name, self._dsm)
        self._apps[name].deploy(deployments, route_prefix)

    def delete_app(self, name: str) -> None:
        if name in self._apps:
            self._apps[name].delete()

    def get(self, name: str) -> Optional[ApplicationState]:
        return self._apps.get(name)

    def update_all(self) -> None:
        for app in list(self._apps.values()):
            app.update_status()
        for name in [n for n, a in self._apps.items() if a.is_deleted()]:
            del self._apps[name]

    def route_table(self) -> Dict[str, dict]:
        """{route_prefix: {app_name, ingress_deployment}} for the proxy."""
        table = {}
        for app in self._apps.values():
            if app.route_prefix and app.ingress_deployment and \
                    not app.deleting:
                table[app.route_prefix] = {
                    "app_name": app.name,
                    "deployment": app.ingress_deployment,
                }
        return table

    def all_status_infos(self) -> Dict[str, ApplicationStatusInfo]:
        return {n: a.status_info() for n, a in self._apps.items()}
