"""Long-poll config push.

Reference: python/ray/serve/_private/long_poll.py — LongPollHost (:177)
lives in the controller; LongPollClient (:64) loops an async actor call
that blocks server-side until the watched keys change, so config updates
(route tables, running-replica sets) propagate without polling storms.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Callable, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

LISTEN_TIMEOUT_S = 30.0


class LongPollHost:
    """Embedded in the controller actor. Keys map to (snapshot_id, value)."""

    def __init__(self):
        self._snapshot_ids: Dict[str, int] = {}
        self._values: Dict[str, Any] = {}

    def notify_changed(self, key: str, value: Any) -> None:
        """Thread-safe under the GIL: called from the controller's sync
        control loop (executor thread) while listeners read on the event
        loop."""
        self._values[key] = value
        self._snapshot_ids[key] = self._snapshot_ids.get(key, -1) + 1

    def notify_if_changed(self, key: str, value: Any) -> None:
        """notify_changed, but a no-op when the value is unchanged — safe to
        call every control-loop tick."""
        if key in self._values and self._values[key] == value:
            return
        self.notify_changed(key, value)

    async def listen_for_change(
            self, keys_to_snapshot_ids: Dict[str, int]) -> dict:
        """Block until any watched key's snapshot_id advances past the
        client's, then return {key: {"snapshot_id": i, "value": v}}.
        Internally sleep-polls the snapshot table (cheap dict reads) so no
        cross-thread asyncio primitives are needed."""
        deadline = asyncio.get_running_loop().time() + LISTEN_TIMEOUT_S
        while True:
            updates = {
                key: {"snapshot_id": self._snapshot_ids[key],
                      "value": self._values[key]}
                for key, client_id in keys_to_snapshot_ids.items()
                if self._snapshot_ids.get(key, -1) > client_id
            }
            if updates:
                return updates
            if asyncio.get_running_loop().time() >= deadline:
                return {}
            await asyncio.sleep(0.05)


class LongPollClient:
    """Runs a listen loop against the controller from any process.

    ``callbacks``: {key: fn(value)} invoked on each update.
    """

    def __init__(self, host_actor, callbacks: Dict[str, Callable[[Any], None]],
                 loop: Optional[asyncio.AbstractEventLoop] = None):
        self._host = host_actor
        self._callbacks = callbacks
        self._snapshot_ids = {key: -1 for key in callbacks}
        self._stopped = False
        self._task = None
        loop = loop or asyncio.get_event_loop()
        self._task = loop.create_task(self._poll_loop())

    def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()

    async def _poll_loop(self) -> None:
        import ray_tpu

        while not self._stopped:
            try:
                ref = self._host.listen_for_change.remote(self._snapshot_ids)
                updates = await asyncio.wait_for(
                    ray_tpu.get_runtime_context()._worker.get_async(ref),
                    timeout=LISTEN_TIMEOUT_S + 10)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                continue
            except Exception as e:
                if self._stopped:
                    return
                logger.warning("long poll failed: %s; retrying", e)
                await asyncio.sleep(1.0)
                continue
            for key, update in (updates or {}).items():
                self._snapshot_ids[key] = update["snapshot_id"]
                try:
                    self._callbacks[key](update["value"])
                except Exception:
                    logger.exception("long poll callback for %r failed", key)
