"""Request-driven deployment autoscaling.

Reference: python/ray/serve/_private/autoscaling_state.py +
python/ray/serve/autoscaling_policy.py — desired = ceil(total_ongoing /
target_ongoing_requests), clamped to [min, max], applied only after the
decision has held for upscale_delay_s / downscale_delay_s.
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from ray_tpu.serve.config import AutoscalingConfig


class AutoscalingState:
    def __init__(self, config: AutoscalingConfig):
        self.config = config
        self._metrics: Deque[Tuple[float, float]] = deque()  # (ts, ongoing)
        self._decision_value: Optional[int] = None
        self._decision_since: float = 0.0

    def record(self, total_ongoing_requests: float) -> None:
        now = time.time()
        self._metrics.append((now, total_ongoing_requests))
        cutoff = now - self.config.look_back_period_s
        while self._metrics and self._metrics[0][0] < cutoff:
            self._metrics.popleft()

    def _avg_ongoing(self) -> float:
        if not self._metrics:
            return 0.0
        return sum(v for _, v in self._metrics) / len(self._metrics)

    def desired_replicas(self, current: int) -> int:
        cfg = self.config
        avg = self._avg_ongoing()
        target = (cfg.target_custom_metric
                  if getattr(cfg, "target_custom_metric", None)
                  is not None else cfg.target_ongoing_requests)
        raw = math.ceil(avg / max(target, 1e-9))
        if raw > current and cfg.upscaling_factor:
            raw = min(raw, math.ceil(current * cfg.upscaling_factor) or 1)
        if raw < current and cfg.downscaling_factor:
            raw = max(raw, int(current * cfg.downscaling_factor))
        desired = min(max(raw, cfg.min_replicas), cfg.max_replicas)
        now = time.time()
        if desired == current:
            self._decision_value = None
            return current
        if self._decision_value != desired:
            self._decision_value = desired
            self._decision_since = now
            return current
        delay = (cfg.upscale_delay_s if desired > current
                 else cfg.downscale_delay_s)
        if now - self._decision_since >= delay:
            self._decision_value = None
            return desired
        return current
